"""Chunkserver daemon: serving, write chains, master link, replicator.

The analog of the reference's chunkserver (reference:
src/chunkserver/network_worker_thread.cc serving state machine,
masterconn.cc master link, chunk_replicator.cc EC recovery). Disk work
runs in worker threads via ``asyncio.to_thread`` (the bgjobs pool
analog); the event loop stays non-blocking.

Data-plane flows:
  * read: CltocsRead -> stream of CstoclReadData (per-block CRC) +
    CstoclReadStatus
  * write: CltocsWriteInit opens a chain — this server stores the part
    and pipelines every CltocsWriteData to the next server in the chain;
    a write is acked upstream (CstoclWriteStatus) only when the local
    write AND the downstream ack both landed
  * replicate: master sends MatocsReplicate with source part locations;
    the replicator builds a recovery plan (copy same part / recover
    data / recover parity — slice_recovery_planner.h:29-38 modes all
    reduce to a SliceReadPlanner plan + ChunkEncoder recovery), executes
    it over the network, writes the part with fresh CRCs, reports
    CstomaChunkNew.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time

import numpy as np

from lizardfs_tpu.chunkserver.chunk_store import (
    ChunkStore,
    ChunkStoreError,
    MultiStore,
)
from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.core import geometry, native_io, plans
from lizardfs_tpu.core import read_executor
from lizardfs_tpu.core.encoder import get_encoder
from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu import constants as constants_mod
from lizardfs_tpu.runtime import accounting
from lizardfs_tpu.runtime import faults as faultsmod
from lizardfs_tpu.runtime import qos as qosmod
from lizardfs_tpu.runtime import retry as retrymod
from lizardfs_tpu.runtime import tracing
from lizardfs_tpu.runtime.daemon import Daemon
from lizardfs_tpu.runtime.rpc import RpcConnection


class _WriteSession:
    """State for one open write chain on one connection.

    One session == one (chunk, part): clients and forwarding peers open
    a dedicated connection per chain head (csserventry analog).
    """

    def __init__(self, chunk_id: int, version: int, part_id: int,
                 trace_id: int = 0, session_id: int = 0):
        self.chunk_id = chunk_id
        self.version = version
        self.part_id = part_id
        self.trace_id = trace_id  # request trace from WriteInit
        self.session_id = session_id  # originating client session
        self.downstream: tuple[asyncio.StreamReader, asyncio.StreamWriter] | None = None
        self.down_status: dict[int, int] = {}  # write_id -> status
        self.down_event: dict[int, asyncio.Event] = {}
        self.relay_task: asyncio.Task | None = None

    async def close(self):
        if self.relay_task is not None:
            self.relay_task.cancel()
        if self.downstream is not None:
            _, w = self.downstream
            # bounded: a dead next-hop must not park session close
            await retrymod.close_writer(w, swallow_cancel=True)


class ChunkServer(Daemon):
    name = "chunkserver"

    def __init__(
        self,
        data_folder: str | list[str],
        master_addr: tuple[str, int] | list[tuple[str, int]] | None,
        host: str = "127.0.0.1",
        port: int = 0,
        label: str = "_",
        encoder_name: str | None = "cpu",
        wave_timeout: float = 0.3,
        heartbeat_interval: float = 5.0,
        native_data_plane: bool = True,
        admin_password: str | None = None,
    ):
        super().__init__(host, port)
        self.admin_password = admin_password
        folders = [data_folder] if isinstance(data_folder, str) else list(data_folder)
        self.store = MultiStore(folders)
        # flight-recorder incidents (breached-SLO trace captures) live
        # in the first data folder
        self.slo.recorder.set_dir(os.path.join(folders[0], "incidents"))
        # damaged chunks found by the scrubber since start — a health
        # rollup signal alongside damaged folders. Keyed so a bad part
        # that stays on disk is counted once, not once per scrub lap
        # (the master only drops it from the registry; the file — and
        # its re-detection — persists)
        self.chunks_damaged = 0
        self._damaged_seen: set[tuple[int, int]] = set()
        # per-session data-plane accounting (runtime/accounting.py):
        # reads/writes charge the originating session carried by the
        # request's trailing session_id; native-plane ops (no session
        # on their frames) aggregate under the "native" row. The top-K
        # summary folds into heartbeat health_json for the master's
        # cluster-wide `top` view.
        self.session_ops = accounting.SessionOps(
            self.metrics, "chunkserver", max_sessions=16
        )
        # per-chunk heat accumulator between heartbeats: chunk_id ->
        # [ops, bytes]. The top slice folds into heartbeat heat_json
        # (master/heat.py heavy-hitter sketch); bounded so a scan over
        # millions of chunks can't balloon the daemon — once full, new
        # (cold) chunks are dropped and the hot set keeps charging
        self._heat: dict[int, list[float]] = {}
        # (total, used) from the last heartbeat's store.space() so the
        # health snapshot doesn't re-stat the folders
        self._last_space: tuple[int, int] | None = None
        # native C++ data-plane listener (network_worker_thread analog);
        # its port is registered with the master as data_port
        self.data_server = None
        self._want_native_plane = native_data_plane
        # one or more master addresses (active + shadows); registration
        # cycles until the active master accepts
        if isinstance(master_addr, tuple):
            master_addr = [master_addr]
        self.master_addrs: list[tuple[str, int]] | None = master_addr
        self.master_addr = master_addr[0] if master_addr else None
        self.label = label
        self.cs_id = 0
        self.master: RpcConnection | None = None
        # highest cluster fencing epoch observed on any master link
        # (register/heartbeat acks and mirror refusals carry it). Echoed
        # on every registration and heartbeat so a deposed ex-primary
        # hears about the election from its own chunkservers and steps
        # down; an ack BELOW this fences the command link instead of
        # obeying a zombie. 0 = pre-HA / LZ_HA off, fencing disengaged.
        self.cluster_epoch = 0
        self.encoder = get_encoder(encoder_name)
        # replicator recovery backend, resolved lazily on first rebuild:
        # the auto ladder's mesh-sharded backend when real multichip
        # silicon is attached (LZ_SHARDED_RECOVERY=0 kills it), else
        # the configured encoder
        self._recovery_encoder = None
        self.wave_timeout = wave_timeout
        self.heartbeat_interval = heartbeat_interval
        # chunk-tester pacing (hdd_test_chunk analog: the reference
        # scrubs ONE chunk per HDD_TEST_FREQ tick, rotating through the
        # folder — never a fixed prefix): rotate a cursor and stop after
        # ~budget bytes per round, so scrubbing is steady background
        # load instead of a 60 s storm that contends every part flock
        # with live writers
        self.test_budget_bytes = 16 * 2**20
        self._test_cursor = 0
        # write-chain next-hop init reply bound (unbounded-await audit
        # regression pin rides tests/test_chaos.py); class-level default
        # overridable per instance for tests
        self.CHAIN_INIT_TIMEOUT = 10.0
        self.log = logging.getLogger("chunkserver")
        # replication bandwidth cap (bytes/s, 0 = unlimited) — tweakable
        # at runtime (replication_bandwidth_limiter analog)
        from lizardfs_tpu.runtime.limiter import TokenBucket

        self._repl_bps = self.tweaks.register("replication_bps", 0)
        self._repl_bucket = TokenBucket(0.0)
        # multi-tenant QoS data plane (runtime/qos.py): per-tenant
        # in-flight byte budgets under weighted deficit-round-robin.
        # Config arrives on heartbeat acks (MatocsRegisterReply.
        # qos_json: session->tenant map, weights, budget); unarmed
        # (or LZ_QOS=0) every data path pays two checks and nothing
        # else. Rebuild traffic enters as the "_rebuild" pseudo-tenant
        # so rebuilds and tenants cannot starve each other.
        self.qos_queue = qosmod.DrrByteQueue()
        self._qos_tenants: dict[int, str] = {}
        self._qos_raw = ""  # last applied qos_json (change detection)
        # fault injection for the SLO/flight-recorder e2e path: delays
        # every asyncio-plane read by this many ms (0 = off). The tweak
        # name survives as an ALIAS onto the general fault framework —
        # setting it arms (or clears, at 0) the equivalent serve_read
        # delay rule in runtime/faults.py, so `tweaks-set
        # debug_read_delay_ms N` and `faults-arm` steer the same engine.
        self._read_delay_ms = self.tweaks.register(
            "debug_read_delay_ms", 0, on_set=self._read_delay_alias
        )
        # sockets with a native stream in flight; shutdown() on stop so
        # blocked serve threads see EPIPE instead of waiting out their
        # deadline (a ThreadPoolExecutor joins its workers at exit)
        self._native_streams: set = set()
        # passive mirror links to NON-active configured masters (shadow
        # read replicas): addr -> {"conn", "cs_id", "rereg_at"}. The
        # shadow learns this server's part locations from them (volatile
        # state the changelog cannot carry) so replica locates have
        # locations to serve; the link carries registrations/heartbeats
        # only, never commands. LZ_SHADOW_READS=0 disables the plane.
        self._mirror: dict[tuple[str, int], dict] = {}
        # full part list re-report period (seconds): wholesale refresh
        # bounds shadow location drift (parts created by client writes
        # are recorded master-side only, never reported incrementally)
        self.mirror_reregister_interval = 60.0

    # --- lifecycle -----------------------------------------------------------

    async def setup(self) -> None:
        # standing derived chart (charts.cc "total traffic" analog)
        self.metrics.counter("bytes_read")
        self.metrics.counter("bytes_written")
        self.metrics.define("bytes_total", "bytes_read bytes_written ADD")
        await asyncio.to_thread(self.store.scan)
        for folder in self.store.damaged_folders:
            self.log.warning("data folder %s is damaged; skipping", folder)
        if self._want_native_plane and faultsmod.ACTIVE:
            # fault rules armed at startup: the C++ data plane cannot be
            # instrumented from Python, so it stands down and every data
            # byte flows through the hookable asyncio path. A documented
            # behavior change OF THE ARMED STATE ONLY — LZ_FAULTS unset
            # leaves the plane untouched (kill-switch discipline).
            self.log.info(
                "fault injection armed: native data plane standing down"
            )
            self._want_native_plane = False
        if self._want_native_plane:
            from lizardfs_tpu.chunkserver import native_serve

            if native_serve.available():
                # lz_serve_start can fail transiently (fd pressure /
                # ephemeral-port races under heavy test load): retry
                # before falling back to the asyncio data path
                for attempt in range(3):
                    try:
                        self.data_server = native_serve.DataPlaneServer(
                            [s.folder for s in self.store.stores], self.host
                        )
                        self.log.info(
                            "native data plane on %s:%d",
                            self.host, self.data_server.port,
                        )
                        break
                    except RuntimeError as e:
                        self.log.warning(
                            "native data plane start failed "
                            "(attempt %d/3): %s", attempt + 1, e,
                        )
                        await asyncio.sleep(0.2 * (attempt + 1))
        self.add_timer(self.heartbeat_interval, self._heartbeat)
        # mirror maintenance runs on its OWN timer: a sick shadow
        # (accepted connect, hung register — the 30 s call_ok bound)
        # must never stall the command-plane heartbeat to the active
        self.add_timer(self.heartbeat_interval, self._mirror_maintain)
        self.add_timer(60.0, self._test_chunks)

    async def start(self) -> None:
        await super().start()
        from lizardfs_tpu.core import native_io

        if native_io.available():
            # see native_io.prestart_executors: lazy thread spawn inside
            # submit() can block the loop under GIL pressure
            native_io.prestart_executors()
        if self.master_addr is not None:  # None = standalone (tests)
            await self._connect_master()

    async def teardown(self) -> None:
        # the debug_read_delay_ms alias rule is process-global state
        # armed on this daemon's behalf — it must not outlive the
        # daemon (in-process test clusters share one process)
        faultsmod.clear(alias="debug_read_delay_ms")
        if self.data_server is not None:
            await asyncio.to_thread(self.data_server.stop)
            self.data_server = None
        if self.master is not None:
            await self.master.close()
        for entry in list(self._mirror.values()):
            if entry.get("conn") is not None:
                await entry["conn"].close()
        self._mirror.clear()

    async def _connect_master(self) -> None:
        from lizardfs_tpu.proto.status import StatusError

        last: Exception | None = None
        for addr in self.master_addrs:
            try:
                await self._connect_master_at(addr)
                self.master_addr = addr
                return
            except (OSError, ConnectionError, StatusError, asyncio.TimeoutError) as e:
                last = e
                if self.master is not None:
                    await self.master.close()
                    self.master = None
        raise ConnectionError(f"no active master reachable: {last}")

    def _part_report(self) -> list[m.ChunkPartInfo]:
        return [
            m.ChunkPartInfo(
                chunk_id=cf.chunk_id, version=cf.version, part_id=cf.part_id
            )
            for cf in self.store.all_parts()
        ]

    async def _connect_master_at(self, addr: tuple[str, int]) -> None:
        self.master = await RpcConnection.connect(*addr)
        for cls, handler in (
            (m.MatocsCreateChunk, self._cmd_create),
            (m.MatocsDeleteChunk, self._cmd_delete),
            (m.MatocsSetVersion, self._cmd_set_version),
            (m.MatocsTruncateChunk, self._cmd_truncate),
            (m.MatocsReplicate, self._cmd_replicate),
            (m.MatocsDuplicateChunk, self._cmd_duplicate),
        ):
            self.master.on_push(cls, handler)
        total, used = self.store.space()
        reply = await self.master.call_ok(
            m.CstomaRegister,
            addr=m.Addr(host=self.host, port=self.port),
            label=self.label,
            chunks=self._part_report(),
            total_space=total,
            used_space=used,
            data_port=self.data_server.port if self.data_server else 0,
            # echo the highest epoch we have seen: a zombie ex-primary
            # answering this addr fences itself on it and refuses us
            epoch=self.cluster_epoch,
        )
        self.cs_id = reply.cs_id
        self.cluster_epoch = max(
            self.cluster_epoch, getattr(reply, "epoch", 0)
        )
        self.log.info(
            "registered with master as cs %d (epoch %d)",
            self.cs_id, self.cluster_epoch,
        )

    async def stop(self) -> None:
        import socket as _socket

        for sock in list(self._native_streams):
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
        await super().stop()

    async def _heartbeat(self) -> None:
        if self.master_addr is None:
            return
        if self.master is None or self.master.closed:
            try:
                await self._connect_master()
            except OSError:
                return
        total, used = self.store.space()
        self._last_space = (total, used)
        if self.data_server is not None:
            # fold native-plane counters into the metrics registry so
            # charts/admin/prometheus see one consistent view — incl.
            # the per-op disk/net time split (stats v2), which answers
            # "where does data-plane wall time go" without tracing
            s = self.data_server.stats()
            self.metrics.gauge("native_bytes_read").set(float(s["bytes_read"]))
            self.metrics.gauge("native_bytes_written").set(
                float(s["bytes_written"])
            )
            for key in (
                "read_ops", "write_ops", "read_disk_us", "read_net_us",
                "write_disk_us", "write_net_us",
            ):
                if key in s:
                    self.metrics.gauge(f"native_{key}").set(float(s[key]))
            # shm ring plane (native/shm_ring.h proactor): how many
            # same-host segments are mapped and how many bytes skipped
            # the socket copy — the same view Prometheus scrapes
            self.metrics.gauge(
                "native_qos_deferrals",
                help="native data-plane ops paced/deferred by the "
                     "per-session QoS byte budgets (proactor drains + "
                     "threaded read/write paths)",
            ).set(float(self.data_server.qos_deferrals()))
            shm = self.data_server.shm_stats()
            for key, help_txt in (
                ("segments_mapped", "shm ring segments negotiated on "
                 "the native data plane (memfd mappings created)"),
                ("desc_ops", "part writes landed from shm ring "
                 "descriptors on the native data plane"),
                ("bytes", "payload bytes landed via shm ring segments "
                 "(no socket copy)"),
                ("active_segments", "shm ring segments currently "
                 "mapped (released on peer disconnect)"),
            ):
                self.metrics.gauge(
                    f"native_shm_{key}", help=help_txt
                ).set(float(shm[key]))
            self._fold_native_trace()
        try:
            import json as _json

            reply = await self.master.call(
                m.CstomaHeartbeat,
                cs_id=self.cs_id,
                total_space=total,
                used_space=used,
                # health rollup input: this CS's SLO burn / stall /
                # span-drop / disk-error snapshot rides the heartbeat
                # (skew-tolerant trailing field; old masters ignore it)
                health_json=_json.dumps(self.health_snapshot()),
                # per-chunk heat fold for the master's cluster heat map
                # (skew-tolerant trailing field; "" when LZ_HEAT is off
                # so the wire stays byte-identical to the pre-heat tree)
                heat_json=self._heat_fold_json(),
                # max epoch observed on ANY link (incl. mirror refusals
                # from a freshly promoted shadow): the deposed primary
                # learns of the election from this echo and steps down
                epoch=self.cluster_epoch,
                timeout=5.0,
            )
            reply_epoch = getattr(reply, "epoch", 0)
            if reply_epoch and reply_epoch < self.cluster_epoch:
                # the acking master never applied the epoch_bump we saw
                # elsewhere — zombie ex-primary. Fence the command link:
                # drop it and let the next tick re-cycle the address
                # list to the elected active. Its commands after this
                # point would mutate a forked history.
                self.log.warning(
                    "fencing command link to stale master (epoch %d < %d)",
                    reply_epoch, self.cluster_epoch,
                )
                await self.master.close()
                self.master = None
                return
            self.cluster_epoch = max(self.cluster_epoch, reply_epoch)
            # QoS data-plane config refresh (skew-tolerant trailing
            # qos_json; old masters send "" = stay unthrottled)
            self._qos_apply(getattr(reply, "qos_json", ""))
        except (ConnectionError, asyncio.TimeoutError):
            pass

    async def _observe_mirror_epoch(self, epoch: int) -> None:
        """Mirror->command flip: a mirror-plane reply (ack or refusal)
        announcing a HIGHER cluster epoch means an election happened —
        the peer at that address was promoted, and our command link
        points at the deposed ex-primary. Adopt the epoch and fence the
        command link; the next heartbeat re-dials the address list and
        lands command-capable on the new active (the stale mirror entry
        for its addr is popped by the next mirror tick)."""
        if epoch <= self.cluster_epoch:
            return
        self.cluster_epoch = epoch
        if self.master is not None and not self.master.closed:
            self.log.warning(
                "cluster epoch %d announced on the mirror plane — "
                "fencing the command link and re-dialing", epoch,
            )
            await self.master.close()
            self.master = None

    async def _mirror_maintain(self) -> None:
        """Own-timer wrapper for _mirror_tick (never inline in the
        heartbeat: mirror-plane trouble must not cost the active its
        heartbeats)."""
        if self.master_addr is None:
            return
        total, used = self.store.space()
        await self._mirror_tick(total, used)

    async def _mirror_tick(self, total: int, used: int) -> None:
        """Maintain passive mirror links to every configured NON-active
        master address: shadow read replicas learn this server's part
        locations from the registration (volatile state the changelog
        cannot carry) so their locate replies have locations to serve.
        Mirror links carry registrations/heartbeats only — a shadow
        never commands a chunkserver. The full part list re-reports
        every ``mirror_reregister_interval`` seconds (wholesale
        replacement on the shadow) so locations drift-heals; between
        reports a lagging location set is caught by the client's
        read-retry path, which re-locates through the primary."""
        from lizardfs_tpu.constants import shadow_reads_enabled

        if (
            not shadow_reads_enabled()
            or not self.master_addrs
            or len(self.master_addrs) < 2
        ):
            return
        now = asyncio.get_running_loop().time()
        for addr in self.master_addrs:
            if addr == self.master_addr:
                # became (or is) the active command link: a leftover
                # mirror entry is stale
                entry = self._mirror.pop(addr, None)
                if entry is not None and entry.get("conn") is not None:
                    await entry["conn"].close()
                continue
            entry = self._mirror.get(addr)
            if entry is not None and entry.get("conn") is None:
                if now < entry["retry_at"]:
                    continue  # negative cache: peer refused recently
                entry = None
            if entry is not None and entry["conn"].closed:
                entry = None
            async def mirror_register(c):
                # ONE field list for initial registration and the 60 s
                # wholesale re-report — only the connection varies.
                # Plain `call`, not call_ok: a REFUSAL from a freshly
                # promoted master carries the NEW cluster epoch, and
                # that refusal is exactly how this chunkserver learns
                # to flip the address mirror->command (the flip itself
                # is _observe_mirror_epoch fencing the command link).
                reply = await c.call(
                    m.CstomaRegister,
                    addr=m.Addr(host=self.host, port=self.port),
                    label=self.label,
                    chunks=self._part_report(),
                    total_space=total,
                    used_space=used,
                    data_port=(
                        self.data_server.port if self.data_server else 0
                    ),
                    mirror=1,
                    epoch=self.cluster_epoch,
                    timeout=30.0,
                )
                await self._observe_mirror_epoch(
                    getattr(reply, "epoch", 0)
                )
                if getattr(reply, "status", 0) != 0:
                    raise st.StatusError(reply.status, "CstomaRegister")
                return reply

            conn = None  # a dial not yet handed to self._mirror
            try:
                if entry is None:
                    # bounded dial: this runs inside the heartbeat
                    # timer, and an unbounded connect to a blackholed
                    # shadow would stall command-plane heartbeats to
                    # the ACTIVE for the OS connect timeout
                    conn = await asyncio.wait_for(
                        RpcConnection.connect(*addr), timeout=5.0
                    )
                    reply = await mirror_register(conn)
                    self._mirror[addr] = {
                        "conn": conn, "cs_id": reply.cs_id,
                        "rereg_at": now + self.mirror_reregister_interval,
                    }
                    conn = None  # owned by the entry now
                    self.log.info(
                        "mirror-registered with shadow %s:%d", *addr
                    )
                elif now >= entry["rereg_at"]:
                    # wholesale part re-report on the SAME connection
                    # (the shadow replaces this server's recorded set)
                    reply = await mirror_register(entry["conn"])
                    entry["cs_id"] = reply.cs_id
                    entry["rereg_at"] = (
                        now + self.mirror_reregister_interval
                    )
                else:
                    await entry["conn"].call(
                        m.CstomaHeartbeat,
                        cs_id=entry["cs_id"],
                        total_space=total,
                        used_space=used,
                        health_json="",
                        # heat folds go to the ACTIVE only (shadows
                        # don't run the heat loop)
                        heat_json="",
                        timeout=5.0,
                    )
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    st.StatusError):
                # peer down, not a shadow, or refusing (e.g. the
                # ACTIVE master answers this addr, or its kill switch
                # is off): drop the link and back off
                if conn is not None:
                    # dialed but refused before it was stored
                    await conn.close()
                stale = self._mirror.pop(addr, None)
                if stale is not None and stale.get("conn") is not None:
                    await stale["conn"].close()
                elif entry is not None and entry.get("conn") is not None:
                    await entry["conn"].close()
                self._mirror[addr] = {"conn": None, "retry_at": now + 30.0}

    def _fold_native_trace(self) -> None:
        """Drain the native data plane's per-op trace ring into this
        daemon's SpanRing (the C side records receive/disk/send
        timestamps per traced op; here they become chunkserver-role
        spans dumps/merges understand)."""
        if self.data_server is None:
            return
        try:
            ops = self.data_server.trace_ops()
        except Exception:  # noqa: BLE001 — tracing must never hurt serving
            self.log.debug("native trace drain failed", exc_info=True)
            return
        for op in ops:
            # queue_us (lz_serve_trace3): QoS pacing wait inside the op
            # — the attribution engine splits the span's head into a
            # "queue" sub-interval so native backpressure is visible
            self.trace_ring.record(
                op["trace_id"], op["name"], op["t0"], op["t1"],
                role="chunkserver", bytes=op["bytes"],
                disk_us=op["disk_us"], net_us=op["net_us"],
                queue_us=op.get("queue_us", 0),
                chunk_id=op["chunk_id"],
            )
            # SLO accounting for the native plane rides the fold (the
            # C side has no objective engine): class by op name
            op_class = "read" if "read" in op["name"] else "write"
            self.slo.observe(
                op_class, max(op["t1"] - op["t0"], 0.0),
                trace_id=op["trace_id"], name=op["name"],
            )
            # the C plane parses the same trailing session_id the
            # asyncio plane reads (wire.h additive-tail convention;
            # lz_serve_trace2) — ops from legacy peers/stale .so land
            # on the "native" aggregate row so totals stay truthful
            self.session_ops.record(
                op.get("session_id") or "native", op_class,
                max(op["t1"] - op["t0"], 0.0),
                nbytes=op["bytes"], trace_id=op["trace_id"],
            )
            # native-plane ops heat the same per-chunk accumulator the
            # asyncio handlers charge — the master's heat map must not
            # go blind when the C++ data plane serves the bytes
            self._heat_charge(op["chunk_id"], op["bytes"])

    def trace_spans(self, trace_id: int | None = None) -> list[dict]:
        # pull whatever the native plane recorded since the last
        # heartbeat before dumping, so trace-dump is never stale
        self._fold_native_trace()
        return self.trace_ring.dump(trace_id)

    def _health_disk_errors(self) -> int:
        # damaged data folders + scrubber-found corrupt parts: either
        # degrades this daemon's health snapshot (runtime/slo.py)
        return len(self.store.damaged_folders) + self.chunks_damaged

    def _health_extra(self) -> dict:
        # reuse the space figures the heartbeat just computed instead
        # of re-statting every data folder (snapshot and heartbeat run
        # back to back; the fallback covers ad-hoc admin `health`)
        total, used = self._last_space or self.store.space()
        extra = {"cs_id": self.cs_id, "used_space": used,
                 "total_space": total}
        # per-session data-plane top-K rides the heartbeat health_json
        # (skew-tolerant: old masters ignore the key) so the master's
        # `top` rollup owns the cluster-wide byte attribution; empty
        # under LZ_TOP=0 — the heartbeat stays byte-identical
        sessions = self.session_ops.top(8)
        if sessions:
            extra["sessions"] = sessions
        # QoS data plane: which tenants are queued behind the byte
        # budget right now (health/`top` name throttled tenants)
        if self.qos_queue.armed:
            q = self.qos_queue.snapshot()
            extra["qos"] = {
                "waiting": q["waiting"],
                "throttle_waits": q["throttle_waits"],
            }
        return extra

    # --- per-chunk heat fold (master/heat.py input) -------------------------

    def _heat_charge(self, chunk_id: int, nbytes: int) -> None:
        """Charge one data-plane op against the chunk's heat row. Cheap
        enough for every read/write; gated so LZ_HEAT=off costs one
        env read and nothing else."""
        if not constants_mod.heat_enabled():
            return
        cell = self._heat.get(chunk_id)
        if cell is None:
            if len(self._heat) >= 1024:
                # full: keep charging known-hot chunks, drop newcomers
                # (the master's sketch only wants the heavy hitters)
                return
            cell = self._heat[chunk_id] = [0.0, 0.0]
        cell[0] += 1.0
        cell[1] += float(nbytes)

    def _heat_fold_json(self) -> str:
        """Top-K of the accumulator as heartbeat heat_json, then reset.
        Returns "" when LZ_HEAT is off or nothing charged — the
        heartbeat stays byte-identical to the pre-heat wire."""
        if not constants_mod.heat_enabled():
            self._heat.clear()
            return ""
        if not self._heat:
            return ""
        import json as _json

        top = sorted(
            self._heat.items(), key=lambda kv: kv[1][1], reverse=True
        )[:16]
        self._heat.clear()
        return _json.dumps({
            "chunks": [[cid, int(ops), int(nb)] for cid, (ops, nb) in top]
        })

    # --- multi-tenant QoS data plane ---------------------------------------

    def _qos_apply(self, text: str) -> None:
        """Install the master-pushed QoS config (heartbeat ack). Empty
        text disarms (master off/unconfigured: behavior reverts to the
        pre-QoS data plane). Idempotent per payload."""
        if text == self._qos_raw:
            return
        if not text:
            self._qos_raw = ""
            self._qos_tenants = {}
            self.qos_queue.configure({}, 0.0)
            self._qos_native_apply({})
            return
        import json as _json

        try:
            doc = _json.loads(text)
            tenants = {
                int(sid): str(t)
                for sid, t in (doc.get("tenants") or {}).items()
            }
            weights = {
                str(t): float(w)
                for t, w in (doc.get("weights") or {}).items()
            }
            weights[qosmod.REBUILD_TENANT] = float(
                doc.get("rebuild_weight", 1.0)
            )
            capacity = float(doc.get("inflight_mb", 0) or 0) * 2**20
        except (ValueError, TypeError):
            self.log.warning("bad qos_json from master; keeping previous")
            return
        self._qos_raw = text
        self._qos_tenants = tenants
        self.qos_queue.configure(weights, capacity)
        self._qos_native_apply(doc.get("session_bps") or {})

    def _qos_native_apply(self, session_bps: dict) -> None:
        """Per-session byte-rate budgets for the C++ data plane (epoll
        proactor descriptor drain + threaded reads). Best effort: a
        stale .so without the API simply stays unpaced — QoS fails
        open, never into a lockout."""
        if self.data_server is None:
            return
        try:
            self.data_server.qos_set({
                int(sid): int(bps) for sid, bps in session_bps.items()
            })
        except (AttributeError, ValueError, TypeError):
            pass

    def _qos_tenant(self, session_id) -> str:
        try:
            return self._qos_tenants.get(
                int(session_id or 0), qosmod.DEFAULT_TENANT
            )
        except (TypeError, ValueError):
            return qosmod.DEFAULT_TENANT

    async def _qos_admit(self, session_id, nbytes: int) -> "str | None":
        """Admit ``nbytes`` of data-plane work for the session's
        tenant. Returns the tenant token for :meth:`_qos_done`, or
        None when QoS is off/unarmed (the zero-cost path: these two
        checks and nothing else)."""
        if not constants_mod.qos_enabled() or not self.qos_queue.armed:
            return None
        tenant = (
            session_id if session_id == qosmod.REBUILD_TENANT
            else self._qos_tenant(session_id)
        )
        w0 = tracing.phase_t0()
        waited = await self.qos_queue.admit(tenant, nbytes)
        if waited:
            self.metrics.labeled_counter(
                "qos_throttle", {"tenant": tenant},
                help="data-plane ops that had to queue behind the "
                     "per-tenant in-flight byte budget (weighted DRR)",
            ).inc()
            # the wait itself is a labeled queue_wait timing + an
            # ambient-trace span, so DRR backpressure is attributable
            tracing.charge_queue_wait(
                self.metrics, self.trace_ring, "drr_disk", tenant, w0,
                role="chunkserver",
            )
        return tenant

    def _qos_done(self, tenant: "str | None", nbytes: int) -> None:
        if tenant is not None:
            self.qos_queue.done(tenant, nbytes)

    async def _test_chunks(self) -> None:
        """Chunk tester (hdd_test_chunk analog): rotate through every
        stored part, verifying up to ``test_budget_bytes`` per round —
        full-scrub coverage over time at bounded IO/CPU cost (the old
        fixed ``[:8]`` prefix re-scanned the same parts forever and, on
        big parts, read 8 x 64 MiB per round while holding part
        flocks against live writers)."""
        parts = self.store.all_parts()
        if not parts:
            return
        damaged = []
        tested_bytes = 0
        for _ in range(len(parts)):  # at most one full lap per round
            cf = parts[self._test_cursor % len(parts)]
            self._test_cursor += 1
            try:
                size = os.path.getsize(cf.path)
            except OSError:
                continue  # vanished mid-rotation (deleted chunk)
            ok = await asyncio.to_thread(self.store.test_part, cf)
            if not ok:
                damaged.append(
                    m.ChunkPartInfo(
                        chunk_id=cf.chunk_id, version=cf.version, part_id=cf.part_id
                    )
                )
            tested_bytes += size
            if tested_bytes >= self.test_budget_bytes:
                break
        self._test_cursor %= max(len(parts), 1)
        fresh = [
            info for info in damaged
            if (info.chunk_id, info.part_id) not in self._damaged_seen
        ]
        if fresh:
            self._damaged_seen.update(
                (info.chunk_id, info.part_id) for info in fresh
            )
            self.chunks_damaged += len(fresh)
            self.metrics.counter(
                "chunks_damaged",
                help="chunk parts the background scrubber found corrupt",
            ).inc(len(fresh))
        if damaged and self.master is not None and not self.master.closed:
            await self.master.send(
                m.CstomaChunkDamaged(cs_id=self.cs_id, chunks=damaged)
            )

    # --- master commands -------------------------------------------------------

    async def _ack(self, req_id: int, chunk_id: int, part_id: int, code: int):
        if self.master is not None and not self.master.closed:
            await self.master.send(
                m.CstomaChunkOpStatus(
                    req_id=req_id, status=code, chunk_id=chunk_id, part_id=part_id
                )
            )

    async def _run_job(self, msg, fn, *args):
        try:
            await asyncio.to_thread(fn, *args)
            code = st.OK
        except ChunkStoreError as e:
            code = e.code
        except Exception:
            self.log.exception("chunk op failed")
            code = st.EIO
        await self._ack(msg.req_id, msg.chunk_id, msg.part_id, code)

    async def _cmd_create(self, msg: m.MatocsCreateChunk):
        await self._run_job(
            msg, self.store.create, msg.chunk_id, msg.version, msg.part_id
        )

    async def _cmd_delete(self, msg: m.MatocsDeleteChunk):
        await self._run_job(
            msg, self.store.delete, msg.chunk_id, msg.version, msg.part_id
        )

    async def _cmd_set_version(self, msg: m.MatocsSetVersion):
        await self._run_job(
            msg,
            self.store.set_version,
            msg.chunk_id,
            msg.old_version,
            msg.new_version,
            msg.part_id,
        )

    async def _cmd_duplicate(self, msg: m.MatocsDuplicateChunk):
        await self._run_job(
            msg,
            self.store.duplicate,
            msg.src_chunk_id,
            msg.src_version,
            msg.part_id,
            msg.chunk_id,
            msg.version,
        )

    async def _cmd_truncate(self, msg: m.MatocsTruncateChunk):
        def job():
            cpt = geometry.ChunkPartType.from_id(msg.part_id)
            part_len = geometry.chunk_length_to_part_length(cpt, msg.chunk_length)
            self.store.set_version(
                msg.chunk_id, msg.old_version, msg.new_version, msg.part_id
            )
            self.store.truncate_part(
                msg.chunk_id, msg.new_version, msg.part_id, part_len
            )

        await self._run_job(msg, job)

    # --- replication (chunk_replicator.cc analog) -------------------------------

    async def _cmd_replicate(self, msg: m.MatocsReplicate):
        t0 = time.perf_counter()
        tw0 = time.time()
        # join the RebuildEngine's per-rebuild trace: the source reads
        # this replica issues carry the id into the peers' span rings,
        # and this executor span merges with the master's scheduler
        # span into one rebuild timeline
        tid = getattr(msg, "trace_id", 0)
        tracing.adopt_trace(tid)
        try:
            await self._replicate(msg)
            code = st.OK
        except (ChunkStoreError,) as e:
            code = e.code
        except Exception as e:
            self.log.warning("replication failed: %s", e)
            code = st.EIO
        finally:
            tracing.clear_trace()
        self.trace_ring.record(
            tid, "cs_replicate", tw0, time.time(), role="chunkserver",
            chunk_id=msg.chunk_id,
        )
        self.slo.observe(
            "replicate", time.perf_counter() - t0, trace_id=tid,
            name="cs_replicate",
        )
        await self._ack(msg.req_id, msg.chunk_id, msg.part_id, code)
        if code == st.OK and self.master is not None:
            cf = self.store.get(msg.chunk_id, msg.part_id)
            if cf is not None:
                new = m.CstomaChunkNew(
                    cs_id=self.cs_id,
                    chunks=[
                        m.ChunkPartInfo(
                            chunk_id=cf.chunk_id,
                            version=cf.version,
                            part_id=cf.part_id,
                        )
                    ],
                )
                await self.master.send(new)
                # shadow mirrors accept the same frame: a rebuilt part
                # becomes replica-locatable now instead of at the next
                # wholesale re-report (best-effort; the re-report
                # drift-heals a miss)
                for entry in self._mirror.values():
                    conn = entry.get("conn")
                    if conn is not None and not conn.closed:
                        try:
                            await conn.send(new)
                        except (ConnectionError, OSError, RuntimeError):
                            pass

    def _replicator_encoder(self):
        """The rebuild compute backend: try the encoder auto-ladder's
        mesh-sharded wide-stripe decoder (parallel/recovery.py) — it
        binds only on a real multi-device mesh with the
        LZ_SHARDED_RECOVERY switch open — and degrade to the configured
        single-chip encoder everywhere else."""
        if self._recovery_encoder is None:
            try:
                self._recovery_encoder = get_encoder("sharded")
                self.log.info(
                    "replicator: mesh-sharded recovery backend active"
                )
            except Exception:  # no mesh / no silicon / kill switch
                self._recovery_encoder = self.encoder
        return self._recovery_encoder

    async def _replicate(self, msg: m.MatocsReplicate) -> None:
        target = geometry.ChunkPartType.from_id(msg.part_id)
        slice_type = target.type
        # source availability: slice part index -> (addr, wire part id)
        locations: dict[int, tuple[tuple[str, int], int]] = {}
        for loc in msg.sources:
            cpt = geometry.ChunkPartType.from_id(loc.part_id)
            if int(cpt.type) == int(slice_type):
                locations.setdefault(
                    cpt.part, ((loc.addr.host, loc.addr.port), loc.part_id)
                )
        nblocks = geometry.number_of_blocks_in_part(target)
        if int(slice_type) == geometry.STANDARD:
            # plain copy of the same part (mode 1 of slice_recovery_planner)
            if 0 not in locations:
                raise ChunkStoreError(st.NO_CHUNK, "no source for copy")
            plan = plans.plan_for_standard(nblocks * MFSBLOCKSIZE)
        else:
            from lizardfs_tpu.core.cs_stats import GLOBAL_STATS

            planner = plans.SliceReadPlanner(
                slice_type, list(locations.keys()),
                scores={p: GLOBAL_STATS.score(a)
                        for p, (a, _) in locations.items()},
                encoder=self._replicator_encoder(),
            )
            if not planner.is_readable([target.part]):
                raise ChunkStoreError(st.NO_CHUNK, "not enough source parts")
            # per-part geometry lengths: trailing data parts hold one block
            # fewer than part 0 when the chunk doesn't stripe evenly
            part_sizes = {
                p: geometry.number_of_blocks_in_part(
                    geometry.ChunkPartType(slice_type, p)
                )
                * MFSBLOCKSIZE
                for p in range(slice_type.expected_parts)
            }
            plan = planner.build_plan([target.part], 0, nblocks, part_sizes)
        nbytes_needed = sum(op.request_size for op in plan.read_operations if op.wave == 0)
        self._repl_bucket.rate = float(self._repl_bps.value)
        self._repl_bucket.burst = max(self._repl_bucket.rate, 1.0)
        await self._repl_bucket.acquire(nbytes_needed)
        # rebuild traffic rides the SAME weighted data-plane queue as
        # client IO, as the "_rebuild" pseudo-tenant: a rebuild storm
        # is capped at its weight share, and a tenant flood cannot
        # starve rebuilds either (ROADMAP 4 both ways)
        qt = await self._qos_admit(qosmod.REBUILD_TENANT, nbytes_needed)
        try:
            data = await read_executor.execute_plan(
                plan,
                msg.chunk_id,
                msg.version,
                locations,
                wave_timeout=self.wave_timeout,
            )
        finally:
            self._qos_done(qt, nbytes_needed)
        self.metrics.counter("replications").inc()
        self.metrics.counter("replication_bytes").inc(float(len(data)))

        def write_part():
            if self.store.get(msg.chunk_id, msg.part_id) is None:
                self.store.create(msg.chunk_id, msg.version, msg.part_id)
            arr = np.asarray(data[: nblocks * MFSBLOCKSIZE])
            blocks = arr.reshape(nblocks, MFSBLOCKSIZE)
            crcs = self.encoder.checksum(blocks)
            for b in range(nblocks):
                self.store.write(
                    msg.chunk_id,
                    msg.version,
                    msg.part_id,
                    b,
                    0,
                    blocks[b].tobytes(),
                    int(crcs[b]),
                )

        await asyncio.to_thread(write_part)

    # --- serving ---------------------------------------------------------------

    @staticmethod
    def _chunk_session(sessions: dict, chunk_id: int):
        """Resolve a frame that predates part addressing (1211/1214) to
        the connection's sole write session for the chunk. Sessions key
        on (chunk_id, part_id) because the vectored client multiplexes
        several parts of one chunk over a single connection."""
        for (cid, _part), session in sessions.items():
            if cid == chunk_id:
                return session
        return None

    async def handle_connection(self, reader, writer) -> None:
        # (chunk_id, part_id) -> session; see _chunk_session
        sessions: dict[tuple[int, int], _WriteSession] = {}
        admin_state: dict = {}
        # shared-memory part ring negotiated on this connection (pure-
        # Python demux of the same descriptor frames serve_native.cpp's
        # proactor drains; the mapping is released on disconnect)
        shm_state: dict = {}
        # in-flight _finish_write tasks still owe status frames on this
        # writer; native streaming must not interleave with them
        pending_writes: set[asyncio.Task] = set()
        try:
            while True:
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if isinstance(msg, (m.AdminInfo, m.AdminCommand)):
                    await self._serve_admin(writer, msg, admin_state)
                elif isinstance(msg, m.CltocsPrefetch):
                    # fire-and-forget page-cache warmup
                    self.spawn(asyncio.to_thread(
                        self.store.prefetch, msg.chunk_id, msg.version,
                        msg.part_id, msg.offset, msg.size,
                    ))
                elif isinstance(msg, m.CltocsRead):
                    # native streaming needs exclusive use of the socket;
                    # in-flight pipelined writes still owe status frames
                    t0 = time.perf_counter()
                    tw0 = time.time()
                    await self._debug_read_delay()
                    await self._serve_read(
                        writer, msg,
                        native_ok=not sessions and not pending_writes,
                    )
                    dt = time.perf_counter() - t0
                    self.metrics.timing("read").record(dt)
                    self.trace_ring.record(
                        msg.trace_id, "cs_read", tw0, time.time(),
                        role="chunkserver", bytes=msg.size,
                    )
                    self.slo.observe(
                        "read", dt, trace_id=msg.trace_id, name="cs_read"
                    )
                    self.session_ops.record(
                        msg.session_id or "unattributed", "read", dt,
                        nbytes=msg.size, trace_id=msg.trace_id,
                    )
                    self._heat_charge(msg.chunk_id, msg.size)
                elif isinstance(msg, m.CltocsReadBulk):
                    t0 = time.perf_counter()
                    tw0 = time.time()
                    await self._debug_read_delay()
                    await self._serve_read_bulk(writer, msg)
                    dt = time.perf_counter() - t0
                    self.metrics.timing("read_bulk").record(dt)
                    self.trace_ring.record(
                        msg.trace_id, "cs_read_bulk", tw0, time.time(),
                        role="chunkserver", bytes=msg.size,
                    )
                    self.slo.observe(
                        "read", dt, trace_id=msg.trace_id,
                        name="cs_read_bulk",
                    )
                    self.session_ops.record(
                        msg.session_id or "unattributed", "read", dt,
                        nbytes=msg.size, trace_id=msg.trace_id,
                    )
                    self._heat_charge(msg.chunk_id, msg.size)
                elif isinstance(msg, m.CltocsWriteInit):
                    await self._serve_write_init(writer, msg, sessions)
                elif isinstance(msg, m.CltocsWriteData):
                    await self._serve_write_data(
                        writer, msg, sessions, pending_writes
                    )
                elif isinstance(msg, (m.CltocsWriteBulk,
                                      m.CltocsWriteBulkPart)):
                    await self._serve_write_bulk(writer, msg, sessions)
                elif isinstance(msg, m.CltocsShmInit):
                    await self._serve_shm_init(writer, msg, shm_state)
                elif isinstance(msg, m.CltocsShmWritePart):
                    await self._serve_shm_write(
                        writer, msg, sessions, shm_state
                    )
                elif isinstance(msg, m.CltocsWriteEnd):
                    # one End seals EVERY part session of the chunk on
                    # this connection (the vectored client sends one
                    # End per connection), answered by a single status
                    for key in [k for k in sessions
                                if k[0] == msg.chunk_id]:
                        session = sessions.pop(key)
                        if session.downstream is not None:
                            _, dw = session.downstream
                            await framing.send_message(dw, msg)
                        await session.close()
                    await framing.send_message(
                        writer,
                        m.CstoclWriteStatus(
                            req_id=msg.req_id,
                            chunk_id=msg.chunk_id,
                            write_id=0,
                            status=st.OK,
                        ),
                    )
                else:
                    self.log.warning("unexpected %s", type(msg).__name__)
                    break
        finally:
            for session in sessions.values():
                await session.close()
            mm = shm_state.pop("mm", None)
            if mm is not None:
                # peer gone (incl. SIGKILL): release the mapping now —
                # segments are owned by the connection, never leaked
                # across reconnects
                mm.close()

    async def _serve_shm_init(self, writer, msg: m.CltocsShmInit,
                              shm_state: dict) -> None:
        """Map the client's memfd ring segment (native/shm_ring.h).

        The asyncio plane reads frames through a StreamReader, which
        drops SCM_RIGHTS ancillary data, so the segment is opened via
        ``/proc/<pid>/fd/<n>`` instead — same-host only, and the kernel
        enforces the same same-uid gate the UDS SO_PEERCRED check does.
        Acked with a CstoclWriteStatus; any refusal leaves the
        connection on the socket-copy path."""
        import mmap as mmap_mod
        import socket as socket_mod

        # same-host contract: a remote peer must not be able to drive
        # the /proc fd mapping (or pin server-side segments).  Unix
        # sockets qualify outright; TCP only from a loopback peer —
        # pure-Python runs have no UDS data listener, so the demux's
        # legitimate callers arrive over 127.0.0.1 (the /proc open
        # still enforces the same-uid gate either way).
        sock = writer.get_extra_info("socket")
        peer = writer.get_extra_info("peername")
        if sock is not None and sock.family == socket_mod.AF_UNIX:
            same_host = True
        else:
            host = peer[0] if isinstance(peer, tuple) and peer else None
            same_host = host in ("127.0.0.1", "::1")
        code = st.OK
        if (
            not same_host
            or not native_io.shm_ring_enabled()
            or msg.seg_size <= 0
            or msg.seg_size > (1 << 30)
        ):
            code = st.EINVAL
        else:
            try:
                fd = os.open(
                    f"/proc/{msg.pid}/fd/{msg.mem_fd}", os.O_RDONLY
                )
                try:
                    if os.fstat(fd).st_size < msg.seg_size:
                        raise OSError("segment smaller than advertised")
                    mm = mmap_mod.mmap(
                        fd, msg.seg_size, prot=mmap_mod.PROT_READ
                    )
                finally:
                    os.close(fd)
                old = shm_state.pop("mm", None)
                if old is not None:
                    old.close()  # renegotiation replaces the mapping
                shm_state["mm"] = mm
                shm_state["size"] = msg.seg_size
                self.metrics.counter(
                    "shm_segments_mapped",
                    help="shm ring segments mapped from same-host "
                         "clients (asyncio data plane)",
                ).inc()
            except OSError:
                code = st.EINVAL
        await framing.send_message(
            writer,
            m.CstoclWriteStatus(
                req_id=msg.req_id, chunk_id=0, write_id=0, status=code
            ),
        )

    async def _serve_shm_write(self, writer, msg: m.CltocsShmWritePart,
                               sessions, shm_state: dict) -> None:
        """Land one ring descriptor: the payload is read straight out
        of the mapped segment; the wire carried only addressing + CRCs.
        Acked exactly like a CltocsWriteBulkPart (FIFO per connection),
        so the windowed client's ack collector is path-agnostic."""
        session = sessions.get((msg.chunk_id, msg.part_id))
        mm = shm_state.get("mm")

        async def ack(code):
            await framing.send_message(
                writer,
                m.CstoclWriteStatus(
                    req_id=msg.req_id, chunk_id=msg.chunk_id,
                    write_id=msg.write_id, status=code,
                ),
            )

        nblocks = -(-msg.length // MFSBLOCKSIZE)
        if (
            session is None
            or mm is None
            or msg.length == 0
            or msg.part_offset % MFSBLOCKSIZE != 0
            or msg.ring_off + msg.length > shm_state.get("size", 0)
            or len(msg.crcs) != nblocks
        ):
            await ack(st.EINVAL)
            return
        tw0 = time.time()
        t0 = time.perf_counter()
        data = bytes(mm[msg.ring_off : msg.ring_off + msg.length])

        def apply_all():
            pos = 0
            for crc in msg.crcs:
                piece = data[pos : pos + MFSBLOCKSIZE]
                # store.write verifies the piece against its wire CRC
                self.store.write(
                    msg.chunk_id, session.version, session.part_id,
                    (msg.part_offset + pos) // MFSBLOCKSIZE, 0,
                    piece, int(crc),
                )
                pos += len(piece)

        code = st.OK
        qt = await self._qos_admit(session.session_id, msg.length)
        try:
            await asyncio.to_thread(apply_all)
        except ChunkStoreError as e:
            code = e.code
        except Exception:
            self.log.exception("shm write failed")
            code = st.EIO
        finally:
            self._qos_done(qt, msg.length)
        self.metrics.counter("bytes_written").inc(float(msg.length))
        self.metrics.counter(
            "shm_desc_writes",
            help="part writes landed from shm ring descriptors "
                 "(asyncio data plane)",
        ).inc()
        self.trace_ring.record(
            session.trace_id, "cs_write_shm", tw0, time.time(),
            role="chunkserver", bytes=msg.length,
        )
        dt = time.perf_counter() - t0
        self.slo.observe(
            "write", dt, trace_id=session.trace_id, name="cs_write_shm"
        )
        self.session_ops.record(
            session.session_id or "unattributed", "write", dt,
            nbytes=msg.length, trace_id=session.trace_id,
        )
        self._heat_charge(msg.chunk_id, msg.length)
        await ack(code)

    @staticmethod
    def _read_delay_alias(ms) -> None:
        """``debug_read_delay_ms`` tweak setter: arm (or clear, at 0)
        the equivalent fault rule. Alias slot = one live rule max."""
        try:
            ms = int(ms)
        except (TypeError, ValueError):
            return
        if ms > 0:
            faultsmod.arm(
                f"chunkserver:serve_read delay={ms}",
                alias="debug_read_delay_ms",
            )
        else:
            faultsmod.clear(alias="debug_read_delay_ms")

    async def _debug_read_delay(self) -> None:
        """The ``serve_read`` fault choke point on the asyncio-plane
        read path (runtime/faults.py). The ``debug_read_delay_ms``
        tweak arms a delay rule here; LZ_FAULTS/admin-armed rules can
        also stall or abort the path, so SLO breach -> flight-record ->
        health-degrade stays drillable end to end."""
        if faultsmod.ACTIVE:
            await faultsmod.async_point(
                "serve_read", op="cs_read", role="chunkserver"
            )

    async def _serve_admin(self, writer, msg, state: dict | None = None) -> None:
        import json

        state = state if state is not None else {}
        if isinstance(msg, m.AdminCommand):
            reply = self.admin_gate(msg, state)
            if reply is not None:
                await framing.send_message(writer, reply)
                return
        if isinstance(msg, m.AdminInfo):
            total, used = self.store.space()
            await framing.send_message(
                writer,
                m.AdminInfoReply(
                    req_id=msg.req_id, status=st.OK,
                    json=json.dumps({
                        "cs_id": self.cs_id, "label": self.label,
                        "parts": len(self.store.all_parts()),
                        "total_space": total, "used_space": used,
                    }),
                ),
            )
            return
        reply = self.handle_admin_basics(msg)
        if reply is None:
            reply = m.AdminReply(req_id=msg.req_id, status=st.EINVAL, json="{}")
        await framing.send_message(writer, reply)

    async def _serve_read(
        self, writer, msg: m.CltocsRead, native_ok: bool = True
    ) -> None:
        if (
            native_ok
            and native_io.available()
            and msg.size >= native_io.NATIVE_READ_THRESHOLD
            # armed faults: the native load path bypasses store.read,
            # where the disk_pread choke point lives — serve through
            # the instrumented path (LZ_FAULTS unset: unchanged)
            and not faultsmod.ACTIVE
        ):
            served = await self._serve_read_native(writer, msg)
            if served:
                return
        # QoS: the disk phase holds per-tenant in-flight credits (the
        # send phase must not — a wedged consumer would pin the shared
        # pool; its connection already self-backpressures)
        qt = await self._qos_admit(msg.session_id, msg.size)
        try:
            pieces = await asyncio.to_thread(
                self.store.read,
                msg.chunk_id,
                msg.version,
                msg.part_id,
                msg.offset,
                msg.size,
            )
        except ChunkStoreError as e:
            await framing.send_message(
                writer,
                m.CstoclReadStatus(
                    req_id=msg.req_id, chunk_id=msg.chunk_id, status=e.code
                ),
            )
            return
        finally:
            self._qos_done(qt, msg.size)
        for off, data, crc in pieces:
            self.metrics.counter("bytes_read").inc(float(len(data)))
            await framing.send_message(
                writer,
                m.CstoclReadData(
                    req_id=msg.req_id,
                    chunk_id=msg.chunk_id,
                    offset=off,
                    crc=crc,
                    data=bytes(data),
                ),
            )
        await framing.send_message(
            writer,
            m.CstoclReadStatus(
                req_id=msg.req_id, chunk_id=msg.chunk_id, status=st.OK
            ),
        )

    async def _serve_read_bulk(self, writer, msg: m.CltocsReadBulk) -> None:
        """Asyncio fallback for the bulk read op (serve_native.cpp is
        the fast path): load pieces, reply with ONE frame whose CRCs the
        receiver verifies."""
        def reply_err(code):
            return framing.send_message(
                writer,
                m.CstoclReadBulkData(
                    req_id=msg.req_id, chunk_id=msg.chunk_id, status=code,
                    offset=msg.offset, crcs=[], data=b"",
                ),
            )

        if msg.offset % MFSBLOCKSIZE != 0 or msg.size == 0:
            await reply_err(st.EINVAL)
            return
        qt = await self._qos_admit(msg.session_id, msg.size)
        try:
            pieces = await asyncio.to_thread(
                self.store.read,
                msg.chunk_id, msg.version, msg.part_id, msg.offset, msg.size,
            )
        except ChunkStoreError as e:
            await reply_err(e.code)
            return
        finally:
            self._qos_done(qt, msg.size)
        self.metrics.counter("bytes_read").inc(float(msg.size))
        await framing.send_message(
            writer,
            m.CstoclReadBulkData(
                req_id=msg.req_id, chunk_id=msg.chunk_id, status=st.OK,
                offset=msg.offset,
                crcs=[crc for _, _, crc in pieces],
                data=b"".join(bytes(d) for _, d, _ in pieces),
            ),
        )

    async def _serve_read_native(self, writer, msg: m.CltocsRead) -> bool:
        """Stream the response via native/io_native.cpp — load + CRC
        verify under the chunk lock, then frame + send off the event
        loop with the lock released and the GIL dropped. Returns False
        to fall back to the per-piece asyncio path."""
        try:
            cf = self.store.require(msg.chunk_id, msg.version, msg.part_id)
        except ChunkStoreError as e:
            await framing.send_message(
                writer,
                m.CstoclReadStatus(
                    req_id=msg.req_id, chunk_id=msg.chunk_id, status=e.code
                ),
            )
            return True
        max_bytes = cf.max_blocks() * MFSBLOCKSIZE
        if msg.offset + msg.size > max_bytes:
            await framing.send_message(
                writer,
                m.CstoclReadStatus(
                    req_id=msg.req_id, chunk_id=msg.chunk_id, status=st.EINVAL
                ),
            )
            return True
        sock = writer.get_extra_info("socket")
        if sock is None:
            return False
        if not native_io.serve_slot_available():
            return False  # executor saturated (stalled clients): asyncio path

        def load():
            with cf.lock:
                return native_io.load_read_blocking(
                    cf.path, msg.offset, msg.size, cf.data_length()
                )

        native_io.serve_slot_acquire()
        try:
            return await self._serve_read_native_inner(
                writer, msg, cf, sock, load
            )
        finally:
            native_io.serve_slot_release()

    async def _serve_read_native_inner(
        self, writer, msg, cf, sock, load
    ) -> bool:
        # QoS in-flight credits cover the disk load (same contract as
        # the asyncio path; the stream phase self-backpressures)
        qt = await self._qos_admit(msg.session_id, msg.size)
        try:
            rc, buf, crcs = await native_io.run_serve(load)
        except FileNotFoundError:
            rc = st.NO_CHUNK  # file vanished between require() and open
        except OSError:
            rc = st.EIO  # transient local error (EMFILE, EACCES, ...)
        finally:
            self._qos_done(qt, msg.size)
        if rc != st.OK:
            self.log.warning(
                "native read of %016X:%d failed: %s",
                msg.chunk_id, msg.part_id, st.name(rc),
            )
            await framing.send_message(
                writer,
                m.CstoclReadStatus(
                    req_id=msg.req_id, chunk_id=msg.chunk_id, status=rc
                ),
            )
            return True
        self.metrics.counter("bytes_read").inc(float(msg.size))
        # raw fd sends must not jump ahead of queued transport bytes;
        # drain() only waits below the high-water mark, so under
        # sustained output the loaded buffer is streamed through the
        # transport instead of being thrown away for a second disk pass
        # lint: waive(unbounded-await): server->client read backpressure on the per-connection serve task — a wedged consumer parks only its own connection, reaped on disconnect; a timer would cut live slow readers
        await writer.drain()
        if writer.transport.get_write_buffer_size() != 0:
            await self._stream_pieces_asyncio(writer, msg, buf, crcs)
            return True
        try:
            # the streaming thread owns this dup: the connection task may
            # be cancelled (and the transport fd closed + reused) while
            # the thread is still sending
            fd = os.dup(sock.fileno())
        except OSError:
            await self._stream_pieces_asyncio(writer, msg, buf, crcs)
            return True
        # exactly one of {worker thread, cancellation handler} claims the
        # dup — a job cancelled while still queued never runs its
        # finally, so the loser of this race must not touch the fd
        claim = threading.Lock()

        def stream_job():
            if not claim.acquire(blocking=False):
                return -1  # cancelled before start; fd already closed
            return native_io.stream_read_blocking(
                fd, msg.chunk_id, msg.req_id, msg.offset, msg.size,
                buf, crcs,
            )

        self._native_streams.add(sock)
        try:
            rc = await native_io.run_serve(stream_job)
        except BaseException:
            # covers CancelledError and executor-rejected submissions
            # (RuntimeError after shutdown): close the dup iff the
            # worker never claimed it
            if claim.acquire(blocking=False):
                os.close(fd)
            raise
        finally:
            self._native_streams.discard(sock)
        if rc < 0:
            writer.close()  # socket died mid-stream; let the loop unwind
        return True

    async def _stream_pieces_asyncio(self, writer, msg, buf, crcs) -> None:
        """Send an already-loaded + verified range as normal framed
        messages (used when the transport still has queued bytes)."""
        pos = msg.offset
        end = msg.offset + msg.size
        idx = 0
        while pos < end:
            block_start = (pos // MFSBLOCKSIZE) * MFSBLOCKSIZE
            piece_end = min(end, block_start + MFSBLOCKSIZE)
            await framing.send_message(
                writer,
                m.CstoclReadData(
                    req_id=msg.req_id,
                    chunk_id=msg.chunk_id,
                    offset=pos,
                    crc=int(crcs[idx]),
                    data=bytes(buf[pos - msg.offset:piece_end - msg.offset]),
                ),
            )
            idx += 1
            pos = piece_end
        await framing.send_message(
            writer,
            m.CstoclReadStatus(
                req_id=msg.req_id, chunk_id=msg.chunk_id, status=st.OK
            ),
        )

    async def _serve_write_init(self, writer, msg: m.CltocsWriteInit, sessions):
        session = _WriteSession(
            msg.chunk_id, msg.version, msg.part_id, trace_id=msg.trace_id,
            session_id=msg.session_id,
        )
        code = st.OK
        try:
            if msg.create and self.store.get(msg.chunk_id, msg.part_id) is None:
                await asyncio.to_thread(
                    self.store.create, msg.chunk_id, msg.version, msg.part_id
                )
            else:
                self.store.require(msg.chunk_id, msg.version, msg.part_id)
        except ChunkStoreError as e:
            code = e.code
        if code == st.OK and msg.chain:
            # connect to the next server and forward the init with the
            # rest of the chain (WRITEFWD state analog). Both the dial
            # AND the init reply are deadline-bounded (unbounded-await
            # audit): a next-hop that accepts the connect but never
            # answers used to wedge this whole write chain forever.
            nxt = msg.chain[0]
            try:
                dr, dw = await retrymod.bounded_wait(
                    asyncio.open_connection(nxt.addr.host, nxt.addr.port),
                    5.0,
                )
                session.downstream = (dr, dw)
                await framing.send_message(
                    dw,
                    m.CltocsWriteInit(
                        req_id=msg.req_id,
                        chunk_id=msg.chunk_id,
                        version=msg.version,
                        part_id=nxt.part_id,
                        chain=msg.chain[1:],
                        create=msg.create,
                        trace_id=msg.trace_id,
                        session_id=msg.session_id,
                    ),
                )
                reply = await retrymod.bounded_wait(
                    framing.read_message(dr), self.CHAIN_INIT_TIMEOUT
                )
                if (
                    not isinstance(reply, m.CstoclWriteStatus)
                    or reply.status != st.OK
                ):
                    code = getattr(reply, "status", st.EIO)
                else:
                    session.relay_task = self.spawn(
                        self._relay_down_statuses(session)
                    )
            except asyncio.TimeoutError:
                code = st.TIMEOUT
            except OSError:
                code = st.DISCONNECTED
        if code == st.OK:
            sessions[(msg.chunk_id, msg.part_id)] = session
        else:
            await session.close()
        await framing.send_message(
            writer,
            m.CstoclWriteStatus(
                req_id=msg.req_id, chunk_id=msg.chunk_id, write_id=0, status=code
            ),
        )

    async def _relay_down_statuses(self, session: _WriteSession) -> None:
        dr, _ = session.downstream
        try:
            while True:
                msg = await framing.read_message(dr)
                if isinstance(msg, m.CstoclWriteStatus):
                    ev = session.down_event.get(msg.write_id)
                    if ev is None:
                        # late ack: the waiter already timed out (the
                        # 30 s down_ev bound) and popped its entries —
                        # storing a status nobody will ever consume
                        # would leak one dict entry per timed-out write
                        continue
                    session.down_status[msg.write_id] = msg.status
                    ev.set()
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            # downstream died: fail all waiting writes
            for wid, ev in session.down_event.items():
                session.down_status.setdefault(wid, st.DISCONNECTED)
                ev.set()

    async def _serve_write_data(
        self, writer, msg: m.CltocsWriteData, sessions, pending_writes
    ):
        """Forward downstream in-order, then complete the local write and
        the upstream ack in a background task — the connection loop keeps
        reading, so blocks pipeline through the chain instead of paying
        one chain round trip each (WRITEFWD pipelining)."""
        session = self._chunk_session(sessions, msg.chunk_id)
        if session is None:
            await framing.send_message(
                writer,
                m.CstoclWriteStatus(
                    req_id=msg.req_id,
                    chunk_id=msg.chunk_id,
                    write_id=msg.write_id,
                    status=st.EINVAL,
                ),
            )
            return
        down_ev = None
        if session.downstream is not None:
            down_ev = asyncio.Event()
            session.down_event[msg.write_id] = down_ev
            _, dw = session.downstream
            try:
                await framing.send_message(dw, msg)
            except (ConnectionError, OSError):
                session.down_status[msg.write_id] = st.DISCONNECTED
                down_ev.set()
        task = self.spawn(self._finish_write(writer, session, msg, down_ev))
        pending_writes.add(task)
        task.add_done_callback(pending_writes.discard)

    async def _finish_write(self, writer, session, msg, down_ev) -> None:
        code = st.OK
        qt = await self._qos_admit(session.session_id, len(msg.data))
        try:
            await asyncio.to_thread(self._local_write, session, msg)
        except ChunkStoreError as e:
            code = e.code
        except Exception:
            self.log.exception("local write failed")
            code = st.EIO
        finally:
            self._qos_done(qt, len(msg.data))
        if down_ev is not None:
            # bounded like the bulk path: a next-hop that accepted the
            # dial but never acks must fail this write with TIMEOUT,
            # not park the head's write task forever (the write-chain
            # cousin of the PR-8 blackholed-WriteInit fix)
            try:
                await asyncio.wait_for(down_ev.wait(), 30.0)
                down_code = session.down_status.pop(
                    msg.write_id, st.DISCONNECTED
                )
            except asyncio.TimeoutError:
                down_code = st.TIMEOUT
            session.down_event.pop(msg.write_id, None)
            session.down_status.pop(msg.write_id, None)
            if code == st.OK:
                code = down_code
        try:
            await framing.send_message(
                writer,
                m.CstoclWriteStatus(
                    req_id=msg.req_id,
                    chunk_id=msg.chunk_id,
                    write_id=msg.write_id,
                    status=code,
                ),
            )
        except (ConnectionError, OSError):
            pass

    async def _serve_write_bulk(self, writer, msg, sessions):
        """Asyncio fallback for the bulk write ops (serve_native.cpp is
        the fast path): apply the whole block-aligned range, forward the
        frame down the chain, single combined ack. Accepts both the
        chunk-addressed CltocsWriteBulk and the part-addressed
        CltocsWriteBulkPart (vectored clients multiplex several parts
        of one chunk over one connection)."""
        part_id = getattr(msg, "part_id", None)
        if part_id is not None:
            session = sessions.get((msg.chunk_id, part_id))
        else:
            session = self._chunk_session(sessions, msg.chunk_id)

        async def ack(code):
            await framing.send_message(
                writer,
                m.CstoclWriteStatus(
                    req_id=msg.req_id, chunk_id=msg.chunk_id,
                    write_id=msg.write_id, status=code,
                ),
            )

        if session is None or msg.part_offset % MFSBLOCKSIZE != 0:
            await ack(st.EINVAL)
            return
        tw0 = time.time()
        t0 = time.perf_counter()  # monotonic twin of tw0 for the SLO
        down_ok = st.OK
        down_ev = None
        if session.downstream is not None:
            # register the ack event BEFORE anything can fail, so a
            # downstream death during the local apply fails this write
            # promptly instead of timing out
            down_ev = asyncio.Event()
            session.down_event[msg.write_id] = down_ev
            _, dw = session.downstream
            try:
                await framing.send_message(dw, msg)
            except (ConnectionError, OSError):
                down_ok = st.DISCONNECTED

        def apply_all():
            data = np.frombuffer(msg.data, dtype=np.uint8)
            pos = 0
            for i, crc in enumerate(msg.crcs):
                piece = data[pos:pos + MFSBLOCKSIZE]
                self.store.write(
                    msg.chunk_id, session.version, session.part_id,
                    (msg.part_offset + pos) // MFSBLOCKSIZE, 0,
                    piece.tobytes(), int(crc),
                )
                pos += len(piece)

        code = st.OK
        qt = await self._qos_admit(session.session_id, len(msg.data))
        try:
            await asyncio.to_thread(apply_all)
        except ChunkStoreError as e:
            code = e.code
        except Exception:
            self.log.exception("bulk write failed")
            code = st.EIO
        finally:
            self._qos_done(qt, len(msg.data))
        self.metrics.counter("bytes_written").inc(float(len(msg.data)))
        if down_ev is not None:
            if code == st.OK and down_ok == st.OK:
                # no pre-set compensation needed: every path that
                # stores down_status sets the event in the same step
                try:
                    await asyncio.wait_for(down_ev.wait(), 30.0)
                    code = session.down_status.pop(
                        msg.write_id, st.DISCONNECTED
                    )
                except asyncio.TimeoutError:
                    code = st.TIMEOUT
            elif code == st.OK:
                code = down_ok
            session.down_event.pop(msg.write_id, None)
            session.down_status.pop(msg.write_id, None)
        self.trace_ring.record(
            session.trace_id, "cs_write_bulk", tw0, time.time(),
            role="chunkserver", bytes=len(msg.data),
        )
        dt = time.perf_counter() - t0
        self.slo.observe(
            "write", dt, trace_id=session.trace_id, name="cs_write_bulk"
        )
        self.session_ops.record(
            session.session_id or "unattributed", "write", dt,
            nbytes=len(msg.data), trace_id=session.trace_id,
        )
        self._heat_charge(msg.chunk_id, len(msg.data))
        await ack(code)

    def _local_write(self, session: _WriteSession, msg: m.CltocsWriteData) -> None:
        self.metrics.counter("bytes_written").inc(float(len(msg.data)))
        self.store.write(
            msg.chunk_id,
            session.version,
            session.part_id,
            msg.block,
            msg.offset,
            msg.data,
            msg.crc,
        )
