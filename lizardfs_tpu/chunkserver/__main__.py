"""Run a chunkserver: python -m lizardfs_tpu.chunkserver [config]

Config keys (mfschunkserver.cfg analog): DATA_PATH (comma-separated
folders allowed), HDD_CFG (file listing one data folder per line,
mfshdd.cfg analog; overrides DATA_PATH), LISTEN_HOST, LISTEN_PORT,
MASTER_HOST, MASTER_PORT, MASTER_ADDRS (host:port,host:port,... —
every master incl. shadows, for floating-IP-less failover: the
registration loop cycles until the ACTIVE master accepts; overrides
MASTER_HOST/PORT), LABEL, ENCODER (cpu|cpp|tpu|auto),
HEARTBEAT_INTERVAL (seconds; also the master-reconnect cadence),
NATIVE_DATA_PLANE (default true; false serves data ops from the
asyncio path — the fault-injection choke points live there, and a
server that starts with LZ_FAULTS rules armed stands the native plane
down on its own), ADMIN_PASSWORD (challenge-response auth for
privileged admin commands), LOG_LEVEL.

Fault injection: LZ_FAULTS="seed=N; role:site[:op[:peer]] action,..."
arms seeded fault rules at startup (runtime/faults.py; also steerable
live via `lizardfs-admin faults`); the debug_read_delay_ms tweak is an
alias arming the serve_read delay rule.
"""

import asyncio
import sys

from lizardfs_tpu.chunkserver.server import ChunkServer
from lizardfs_tpu.runtime.config import Config
from lizardfs_tpu.runtime.daemon import setup_logging


def _folders(cfg: Config) -> list[str]:
    hdd_cfg = cfg.get_str("HDD_CFG", "")
    if hdd_cfg:
        out = []
        with open(hdd_cfg) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if line:
                    out.append(line)
        if out:
            return out
    return [
        p.strip()
        for p in cfg.get_str("DATA_PATH", "./cs-data").split(",")
        if p.strip()
    ]


def main() -> None:
    cfg = Config(sys.argv[1] if len(sys.argv) > 1 else None)
    setup_logging("chunkserver", cfg.get_str("LOG_LEVEL", "INFO"))
    addrs_raw = cfg.get_str("MASTER_ADDRS", "")
    if addrs_raw:
        master_addr = []
        for item in addrs_raw.split(","):
            item = item.strip()
            if not item:
                continue  # tolerate trailing/double commas
            host, sep, port = item.rpartition(":")
            if not sep or not host or not port.isdigit():
                raise SystemExit(
                    f"MASTER_ADDRS: bad entry {item!r} "
                    "(expected host:port[,host:port...])"
                )
            master_addr.append((host, int(port)))
        if not master_addr:
            raise SystemExit("MASTER_ADDRS: no addresses given")
    else:
        master_addr = (
            cfg.get_str("MASTER_HOST", "127.0.0.1"),
            cfg.get_int("MASTER_PORT", 9420),
        )
    server = ChunkServer(
        data_folder=_folders(cfg),
        master_addr=master_addr,
        host=cfg.get_str("LISTEN_HOST", "127.0.0.1"),
        port=cfg.get_int("LISTEN_PORT", 0),
        label=cfg.get_str("LABEL", "_"),
        encoder_name=cfg.get_str("ENCODER", "cpu"),
        heartbeat_interval=cfg.get_float("HEARTBEAT_INTERVAL", 5.0, min_value=0.05),
        # off routes data ops through the asyncio server, where the
        # fault-injection choke points (disk_pread/serve_read/...) live
        native_data_plane=cfg.get_bool("NATIVE_DATA_PLANE", True),
        admin_password=cfg.get_str("ADMIN_PASSWORD", "") or None,
    )
    asyncio.run(server.run_forever())


if __name__ == "__main__":
    main()
