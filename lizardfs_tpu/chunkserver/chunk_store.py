"""On-disk chunk store — the hddspacemgr analog.

Disk layout mirrors the reference's header-file format (reference:
src/chunkserver/chunk.h:154-176 MooseFSChunk): each chunk part is one
file named ``chunk_<id:016X>_P<part:08X>_<version:08X>.liz`` inside 256
hash subfolders (``<low byte of id:02X>/``), containing:

  [1 KiB signature block][4 KiB CRC table][block data...]

  signature: magic "LIZTPU10" + chunk_id:u64 + version:u32 + part_id:u32
  CRC table: 1024 big-endian u32 slots (one per possible block)

Every 64 KiB block carries CRC32; reads verify, writes update. The store
is synchronous — the serving layer wraps calls in worker threads (the
bgjobs pool analog, src/chunkserver/bgjobs.h).
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import struct
import threading
import time

import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE, MFSBLOCKSINCHUNK
from lizardfs_tpu.core import geometry
from lizardfs_tpu.ops import crc32 as crc_mod
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import faults as _faults

MAGIC = b"LIZTPU10"
SIGNATURE_SIZE = 1024
CRC_TABLE_SIZE = 4 * MFSBLOCKSINCHUNK  # 4 KiB
HEADER_SIZE = SIGNATURE_SIZE + CRC_TABLE_SIZE
_SIG = struct.Struct(">8sQII")

# CRC of an empty (all-zero) block, used for sparse/unwritten slots.
EMPTY_BLOCK_CRC = crc_mod.crc32(b"\0" * MFSBLOCKSIZE)


@contextlib.contextmanager
def _flocked(f, exclusive: bool):
    """File lock shared with the native data plane: the C++ serving
    threads hold their own open file descriptions, so flock (not the
    in-process ChunkFile.lock) is what keeps block+CRC updates atomic
    across the two planes."""
    fcntl.flock(f.fileno(), fcntl.LOCK_EX if exclusive else fcntl.LOCK_SH)
    try:
        yield
    finally:
        # drain Python's userspace buffer while the lock is still held —
        # otherwise the trailing CRC-slot write lands after LOCK_UN and
        # a reader in the window sees new data with a stale CRC
        if exclusive:
            f.flush()
        fcntl.flock(f.fileno(), fcntl.LOCK_UN)


class ChunkStoreError(Exception):
    def __init__(self, code: int, msg: str = ""):
        self.code = code
        super().__init__(f"{st.name(code)}{(': ' + msg) if msg else ''}")


def _disk_fault(site: str, chunk_id: int, part_id: int):
    """Fault choke point for the block-IO layer (runtime/faults.py).
    Runs in worker threads, so delays are plain sleeps. Returns a
    Decision for the payload actions (flip/short) the caller applies;
    delay/error/drop resolve here. A disk op's role is always
    "chunkserver" — only chunkservers own stores."""
    dec = _faults.decide(
        site, op=f"{chunk_id:016X}:{part_id}", role="chunkserver"
    )
    if dec is None:
        return None
    if dec.action == "delay":
        time.sleep(dec.ms / 1e3)
        return None
    if dec.action in ("error", "drop"):
        raise ChunkStoreError(
            dec.code or st.EIO,
            f"fault injected: {dec.action} {site} "
            f"chunk {chunk_id:016X}:{part_id}",
        )
    return dec  # flip / short: payload actions, site-specific


def chunk_filename(chunk_id: int, part_id: int, version: int) -> str:
    """The part id is IN the name: a server may legitimately hold
    several parts of one chunk (more parts than servers, rebalancing),
    and omitting it made them collide on one path (data loss)."""
    return f"chunk_{chunk_id:016X}_P{part_id:08X}_{version:08X}.liz"


def parse_chunk_filename(name: str):
    """-> (chunk_id, part_id, version) or None. part_id is None for a
    legacy (pre-part-in-name) file — the scan migrates those using the
    part id stored in the signature."""
    if not (name.startswith("chunk_") and name.endswith(".liz")):
        return None
    parts = name[6:-4].split("_")
    try:
        if (len(parts) == 3 and len(parts[0]) == 16
                and parts[1][:1] == "P" and len(parts[1]) == 9
                and len(parts[2]) == 8):
            return int(parts[0], 16), int(parts[1][1:], 16), int(parts[2], 16)
        if len(parts) == 2 and len(parts[0]) == 16 and len(parts[1]) == 8:
            return int(parts[0], 16), None, int(parts[1], 16)
    except ValueError:
        pass
    return None


class ChunkFile:
    """One chunk part on disk."""

    __slots__ = ("chunk_id", "version", "part_id", "path", "lock")

    def __init__(self, chunk_id: int, version: int, part_id: int, path: str):
        self.chunk_id = chunk_id
        self.version = version
        self.part_id = part_id
        self.path = path
        self.lock = threading.Lock()

    @property
    def part_type(self) -> geometry.ChunkPartType:
        return geometry.ChunkPartType.from_id(self.part_id)

    def max_blocks(self) -> int:
        return geometry.number_of_blocks_in_part(self.part_type)

    def data_length(self) -> int:
        try:
            return max(0, os.path.getsize(self.path) - HEADER_SIZE)
        except OSError:
            return 0


class ChunkStore:
    """All chunk parts under one data folder (one mfshdd.cfg line)."""

    def __init__(self, folder: str):
        self.folder = folder
        self._chunks: dict[tuple[int, int], ChunkFile] = {}
        self._lock = threading.Lock()
        os.makedirs(folder, exist_ok=True)

    # --- scan (hddspacemgr.cc:986-1060 folder scan) ------------------------

    def scan(self) -> list[ChunkFile]:
        """Discover chunk files; newest version wins, stale versions are
        removed (the reference keeps one version per chunk part)."""
        found: dict[tuple[int, int], ChunkFile] = {}
        for sub in range(256):
            subdir = os.path.join(self.folder, f"{sub:02X}")
            if not os.path.isdir(subdir):
                continue
            for name in os.listdir(subdir):
                parsed = parse_chunk_filename(name)
                if parsed is None:
                    continue
                chunk_id, name_part, version = parsed
                path = os.path.join(subdir, name)
                try:
                    with open(path, "rb") as f:
                        sig = f.read(_SIG.size)
                    magic, sid, sver, part_id = _SIG.unpack(sig)
                    if magic != MAGIC or sid != chunk_id or sver != version:
                        continue  # damaged signature: skip (reported later)
                    if name_part is None:
                        # legacy name without the part id: migrate; if
                        # the rename fails (read-only folder), keep
                        # serving under the old path rather than
                        # dropping a healthy part
                        new_path = os.path.join(
                            subdir, chunk_filename(chunk_id, part_id, version)
                        )
                        try:
                            os.rename(path, new_path)
                            path = new_path
                        except OSError:
                            pass
                    elif name_part != part_id:
                        continue  # name/signature disagree: damaged
                except (OSError, struct.error):
                    continue
                cf = ChunkFile(chunk_id, version, part_id, path)
                key = (chunk_id, part_id)
                prev = found.get(key)
                if prev is None or prev.version < version:
                    if prev is not None:
                        try:
                            os.unlink(prev.path)
                        except OSError:
                            pass
                    found[key] = cf
        with self._lock:
            self._chunks = found
        return list(found.values())

    # --- lookup -------------------------------------------------------------

    def get(self, chunk_id: int, part_id: int) -> ChunkFile | None:
        with self._lock:
            return self._chunks.get((chunk_id, part_id))

    def require(self, chunk_id: int, version: int, part_id: int) -> ChunkFile:
        cf = self.get(chunk_id, part_id)
        if cf is None:
            raise ChunkStoreError(st.NO_CHUNK, f"chunk {chunk_id:016X}:{part_id}")
        if cf.version != version:
            raise ChunkStoreError(
                st.WRONG_VERSION,
                f"chunk {chunk_id:016X} has v{cf.version}, want v{version}",
            )
        return cf

    def all_parts(self) -> list[ChunkFile]:
        with self._lock:
            return list(self._chunks.values())

    def _path_for(self, chunk_id: int, part_id: int, version: int) -> str:
        subdir = os.path.join(self.folder, f"{chunk_id & 0xFF:02X}")
        os.makedirs(subdir, exist_ok=True)
        return os.path.join(subdir, chunk_filename(chunk_id, part_id, version))

    # --- chunk ops (hddspacemgr.h:153-161) -----------------------------------

    def create(self, chunk_id: int, version: int, part_id: int) -> ChunkFile:
        key = (chunk_id, part_id)
        with self._lock:
            if key in self._chunks:
                raise ChunkStoreError(st.EEXIST, f"chunk {chunk_id:016X}:{part_id}")
        path = self._path_for(chunk_id, part_id, version)
        with open(path, "wb") as f:
            f.write(_SIG.pack(MAGIC, chunk_id, version, part_id))
            f.write(b"\0" * (SIGNATURE_SIZE - _SIG.size))
            f.write(b"\0" * CRC_TABLE_SIZE)
        cf = ChunkFile(chunk_id, version, part_id, path)
        with self._lock:
            self._chunks[key] = cf
        return cf

    def delete(self, chunk_id: int, version: int, part_id: int) -> None:
        cf = self.require(chunk_id, version, part_id)
        with self._lock:
            self._chunks.pop((chunk_id, part_id), None)
        try:
            os.unlink(cf.path)
        except OSError:
            pass

    def duplicate(
        self, src_chunk_id: int, src_version: int, part_id: int,
        new_chunk_id: int, new_version: int,
    ) -> ChunkFile:
        """Local copy of a part under a new chunk id (COW duplicate,
        hdd duplicate op analog)."""
        src = self.require(src_chunk_id, src_version, part_id)
        key = (new_chunk_id, part_id)
        with self._lock:
            if key in self._chunks:
                raise ChunkStoreError(st.EEXIST, f"chunk {new_chunk_id:016X}")
        new_path = self._path_for(new_chunk_id, part_id, new_version)
        with src.lock, open(src.path, "rb") as fin, open(new_path, "wb") as fout:
            fin.seek(SIGNATURE_SIZE)
            fout.write(_SIG.pack(MAGIC, new_chunk_id, new_version, part_id))
            fout.write(b"\0" * (SIGNATURE_SIZE - _SIG.size))
            while True:
                buf = fin.read(1 << 20)
                if not buf:
                    break
                fout.write(buf)
        cf = ChunkFile(new_chunk_id, new_version, part_id, new_path)
        with self._lock:
            self._chunks[key] = cf
        return cf

    def set_version(self, chunk_id: int, old_version: int, new_version: int,
                    part_id: int) -> ChunkFile:
        cf = self.require(chunk_id, old_version, part_id)
        with cf.lock:
            new_path = self._path_for(chunk_id, part_id, new_version)
            with open(cf.path, "r+b") as f:
                f.write(_SIG.pack(MAGIC, chunk_id, new_version, part_id))
            os.rename(cf.path, new_path)
            cf.path = new_path
            cf.version = new_version
        return cf

    # --- block io (hddspacemgr.h:64-69 read/write with CRC) -----------------

    def _read_crc_slot(self, f, block: int) -> int:
        f.seek(SIGNATURE_SIZE + 4 * block)
        return struct.unpack(">I", f.read(4))[0]

    def _write_crc_slot(self, f, block: int, crc: int) -> None:
        f.seek(SIGNATURE_SIZE + 4 * block)
        f.write(struct.pack(">I", crc))

    def read(
        self, chunk_id: int, version: int, part_id: int, offset: int, size: int
    ) -> list[tuple[int, bytes, int]]:
        """Read [offset, offset+size) of a part.

        Returns a list of (part_offset, data, crc) pieces, one per
        touched block: full blocks carry their stored CRC (verified);
        partial pieces carry the CRC of the piece itself. Reads past the
        stored data return zero bytes (sparse semantics match the
        write-anywhere block store).
        """
        cf = self.require(chunk_id, version, part_id)
        max_bytes = cf.max_blocks() * MFSBLOCKSIZE
        if offset < 0 or size < 0 or offset + size > max_bytes:
            raise ChunkStoreError(st.EINVAL, f"read range {offset}+{size}")
        fault = (
            _disk_fault("disk_pread", chunk_id, part_id)
            if _faults.ACTIVE else None
        )
        pieces = []
        with cf.lock, open(cf.path, "rb") as f, _flocked(f, exclusive=False):
            data_len = cf.data_length()
            pos = offset
            end = offset + size
            while pos < end:
                block = pos // MFSBLOCKSIZE
                block_start = block * MFSBLOCKSIZE
                piece_end = min(end, block_start + MFSBLOCKSIZE)
                piece_len = piece_end - pos
                # load the whole block to verify its CRC
                f.seek(HEADER_SIZE + block_start)
                raw = f.read(MFSBLOCKSIZE)
                raw = raw + b"\0" * (MFSBLOCKSIZE - len(raw))
                stored = self._read_crc_slot(f, block)
                if block_start < data_len or stored != 0:
                    # slot 0 inside the data region = sparse hole => empty
                    # block CRC expected (recompute_crc_if_block_empty
                    # analog, crc.cc:235-243)
                    expected = stored if stored != 0 else EMPTY_BLOCK_CRC
                    if crc_mod.crc32(raw) != expected:
                        raise ChunkStoreError(
                            st.CRC_ERROR,
                            f"chunk {chunk_id:016X}:{part_id} block {block}",
                        )
                piece = raw[pos - block_start : pos - block_start + piece_len]
                if piece_len == MFSBLOCKSIZE:
                    crc = stored if stored != 0 else EMPTY_BLOCK_CRC
                else:
                    crc = crc_mod.crc32(piece)
                pieces.append((pos, piece, crc))
                pos = piece_end
        if fault is not None and pieces:
            if fault.action == "flip":
                # corrupt one bit of one piece AFTER the store's own CRC
                # verification, keeping the advertised CRC: the receiver
                # (client / replicator) must catch it — the degraded-
                # read CRC-reject drill
                idx = fault.rule.rand_index(len(pieces))
                pos0, piece, crc = pieces[idx]
                pieces[idx] = (
                    pos0, _faults.flip_bit(piece, fault.rule), crc
                )
            elif fault.action == "short":
                pieces.pop()  # short read: the final piece goes missing
        return pieces

    def write(
        self,
        chunk_id: int,
        version: int,
        part_id: int,
        block: int,
        offset_in_block: int,
        data: bytes,
        data_crc: int,
    ) -> None:
        """Write a piece into one block; verifies the piece CRC from the
        wire, patches the block, updates the stored block CRC."""
        cf = self.require(chunk_id, version, part_id)
        if block >= cf.max_blocks():
            raise ChunkStoreError(st.INDEX_TOO_BIG, f"block {block}")
        if offset_in_block + len(data) > MFSBLOCKSIZE:
            raise ChunkStoreError(st.EINVAL, "write crosses block boundary")
        if crc_mod.crc32(data) != data_crc:
            raise ChunkStoreError(st.CRC_ERROR, "piece crc mismatch on write")
        fault = (
            _disk_fault("disk_pwrite", chunk_id, part_id)
            if _faults.ACTIVE else None
        )
        with cf.lock, open(cf.path, "r+b") as f, _flocked(f, exclusive=True):
            block_start = block * MFSBLOCKSIZE
            if len(data) == MFSBLOCKSIZE:
                new_block = bytes(data)
                new_crc = data_crc
            else:
                f.seek(HEADER_SIZE + block_start)
                raw = bytearray(f.read(MFSBLOCKSIZE))
                raw.extend(b"\0" * (MFSBLOCKSIZE - len(raw)))
                raw[offset_in_block : offset_in_block + len(data)] = data
                new_block = bytes(raw)
                new_crc = crc_mod.crc32(new_block)
            if fault is not None and fault.action == "flip":
                # latent corruption: the block lands with a bit flipped
                # AFTER its CRC was computed, so the stored slot no
                # longer matches — a later read (or the scrubber)
                # raises CRC_ERROR
                new_block = _faults.flip_bit(new_block, fault.rule)
            f.seek(HEADER_SIZE + block_start)
            f.write(new_block)
            if fault is not None and fault.action == "short":
                return  # torn write: data landed, CRC slot never updated
            self._write_crc_slot(f, block, new_crc)

    def truncate_part(
        self, chunk_id: int, version: int, part_id: int, part_length: int
    ) -> None:
        """Truncate a part's data region to part_length bytes; the
        trailing partial block is zero-padded and its CRC refreshed."""
        cf = self.require(chunk_id, version, part_id)
        with cf.lock, open(cf.path, "r+b") as f, _flocked(f, exclusive=True):
            nblocks = (part_length + MFSBLOCKSIZE - 1) // MFSBLOCKSIZE
            f.truncate(HEADER_SIZE + part_length)
            if part_length % MFSBLOCKSIZE:
                last = nblocks - 1
                f.seek(HEADER_SIZE + last * MFSBLOCKSIZE)
                raw = f.read(MFSBLOCKSIZE)
                raw = raw + b"\0" * (MFSBLOCKSIZE - len(raw))
                self._write_crc_slot(f, last, crc_mod.crc32(raw))
            # clear CRC slots beyond the end
            for b in range(nblocks, MFSBLOCKSINCHUNK):
                self._write_crc_slot(f, b, 0)

    def prefetch(self, chunk_id: int, version: int, part_id: int,
                 offset: int, size: int) -> None:
        """Advise the kernel to cache a part range (hdd prefetch /
        posix_fadvise WILLNEED analog). Best-effort; never raises."""
        try:
            cf = self.require(chunk_id, version, part_id)
            with open(cf.path, "rb") as f:
                os.posix_fadvise(
                    f.fileno(), HEADER_SIZE + offset, size,
                    os.POSIX_FADV_WILLNEED,
                )
        except (ChunkStoreError, OSError, AttributeError):
            pass

    # --- chunk tester (hdd_test_chunk analog) --------------------------------

    def test_part(self, cf: ChunkFile) -> bool:
        """Verify all stored CRCs of one part; False = damaged."""
        try:
            with cf.lock, open(cf.path, "rb") as f, \
                    _flocked(f, exclusive=False):
                data_len = cf.data_length()
                nblocks = (data_len + MFSBLOCKSIZE - 1) // MFSBLOCKSIZE
                for b in range(nblocks):
                    f.seek(HEADER_SIZE + b * MFSBLOCKSIZE)
                    raw = f.read(MFSBLOCKSIZE)
                    raw = raw + b"\0" * (MFSBLOCKSIZE - len(raw))
                    stored = self._read_crc_slot(f, b)
                    if stored == 0:
                        continue  # sparse/unwritten slot
                    if crc_mod.crc32(raw) != stored:
                        return False
            return True
        except OSError:
            return False

    def space(self) -> tuple[int, int]:
        """(total_bytes, used_bytes) of the folder's filesystem."""
        s = os.statvfs(self.folder)
        total = s.f_blocks * s.f_frsize
        free = s.f_bavail * s.f_frsize
        return total, total - free


class MultiStore:
    """Several data folders behind the single-store API (mfshdd.cfg
    analog: one chunkserver, many disks — reference parses a folder
    list and scans each, hddspacemgr.cc).

    New parts land on the folder with the most free space; lookups fan
    out. A folder that fails to scan is marked damaged and its parts are
    reported so the master re-replicates elsewhere.
    """

    def __init__(self, folders: list[str]):
        if not folders:
            raise ValueError("at least one data folder required")
        self.stores = [ChunkStore(f) for f in folders]
        self.damaged_folders: list[str] = []

    # --- scan ---------------------------------------------------------------

    def scan(self) -> list[ChunkFile]:
        out: list[ChunkFile] = []
        for store in list(self.stores):
            try:
                out.extend(store.scan())
            except OSError:
                self.damaged_folders.append(store.folder)
                self.stores.remove(store)
        return out

    # --- lookup -------------------------------------------------------------

    def _store_of(self, chunk_id: int, part_id: int) -> ChunkStore | None:
        for store in self.stores:
            if store.get(chunk_id, part_id) is not None:
                return store
        return None

    def get(self, chunk_id: int, part_id: int) -> ChunkFile | None:
        store = self._store_of(chunk_id, part_id)
        return store.get(chunk_id, part_id) if store else None

    def require(self, chunk_id: int, version: int, part_id: int) -> ChunkFile:
        store = self._store_of(chunk_id, part_id)
        if store is None:
            raise ChunkStoreError(st.NO_CHUNK, f"chunk {chunk_id:016X}:{part_id}")
        return store.require(chunk_id, version, part_id)

    def all_parts(self) -> list[ChunkFile]:
        out: list[ChunkFile] = []
        for store in self.stores:
            out.extend(store.all_parts())
        return out

    # --- placement ----------------------------------------------------------

    def _emptiest(self) -> ChunkStore:
        def free(s: ChunkStore) -> int:
            total, used = s.space()
            return total - used

        return max(self.stores, key=free)

    def create(self, chunk_id: int, version: int, part_id: int) -> ChunkFile:
        if self._store_of(chunk_id, part_id) is not None:
            raise ChunkStoreError(st.EEXIST, f"chunk {chunk_id:016X}:{part_id}")
        return self._emptiest().create(chunk_id, version, part_id)

    def duplicate(self, src_chunk_id, src_version, part_id, new_chunk_id,
                  new_version) -> ChunkFile:
        store = self._store_of(src_chunk_id, part_id)
        if store is None:
            raise ChunkStoreError(st.NO_CHUNK, f"chunk {src_chunk_id:016X}")
        return store.duplicate(
            src_chunk_id, src_version, part_id, new_chunk_id, new_version
        )

    # --- delegated ops ------------------------------------------------------

    def _delegate(self, name, chunk_id, part_id, *args):
        store = self._store_of(chunk_id, part_id)
        if store is None:
            raise ChunkStoreError(st.NO_CHUNK, f"chunk {chunk_id:016X}:{part_id}")
        return getattr(store, name)(*args)

    def delete(self, chunk_id, version, part_id):
        return self._delegate("delete", chunk_id, part_id, chunk_id, version, part_id)

    def set_version(self, chunk_id, old_version, new_version, part_id):
        return self._delegate(
            "set_version", chunk_id, part_id, chunk_id, old_version,
            new_version, part_id,
        )

    def read(self, chunk_id, version, part_id, offset, size):
        return self._delegate(
            "read", chunk_id, part_id, chunk_id, version, part_id, offset, size
        )

    def write(self, chunk_id, version, part_id, block, offset_in_block, data,
              data_crc):
        return self._delegate(
            "write", chunk_id, part_id, chunk_id, version, part_id, block,
            offset_in_block, data, data_crc,
        )

    def truncate_part(self, chunk_id, version, part_id, part_length):
        return self._delegate(
            "truncate_part", chunk_id, part_id, chunk_id, version, part_id,
            part_length,
        )

    def prefetch(self, chunk_id, version, part_id, offset, size) -> None:
        store = self._store_of(chunk_id, part_id)
        if store is not None:
            store.prefetch(chunk_id, version, part_id, offset, size)

    def test_part(self, cf: ChunkFile) -> bool:
        for store in self.stores:
            if store.get(cf.chunk_id, cf.part_id) is cf:
                return store.test_part(cf)
        return ChunkStore.test_part(self.stores[0], cf)

    def space(self) -> tuple[int, int]:
        total = used = 0
        for store in self.stores:
            t, u = store.space()
            total += t
            used += u
        return total, used
