"""Lifecycle wrapper for the native C++ data-plane server.

``native/serve_native.cpp`` runs the chunkserver's data hot path —
accept loop, frame parsing, block IO with CRC maintenance, write-chain
forwarding — entirely in C++ threads (the network_worker_thread.cc
analog; reference src/chunkserver/network_worker_thread.cc:402-755).
The asyncio ``ChunkServer`` starts one listener here, registers its port
with the master as ``data_port``, and the master hands that address out
in part locations; the asyncio server on the control port remains the
portable fallback and the control plane.

Coherence with the Python ``ChunkStore``:
  * part files are created/deleted/versioned by the Python store on
    master commands; the C++ plane resolves paths per request, so
    renames (set_version) and deletes take effect immediately,
  * block reads/writes on BOTH planes take an ``flock`` on the chunk
    file (shared/exclusive), so the chunk tester never sees torn blocks.
"""

from __future__ import annotations

import ctypes

from lizardfs_tpu.core import native as _native_lib

_lib = _native_lib._load()
if _lib is not None:
    try:
        _lib.lz_serve_start.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int
        ]
        _lib.lz_serve_start.restype = ctypes.c_int
        _lib.lz_serve_port.argtypes = [ctypes.c_int]
        _lib.lz_serve_port.restype = ctypes.c_int
        _lib.lz_serve_stop.argtypes = [ctypes.c_int]
        _lib.lz_serve_stop.restype = None
        _lib.lz_serve_stats.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)
        ]
        _lib.lz_serve_stats.restype = None
        try:
            _lib.lz_serve_stats2.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)
            ]
            _lib.lz_serve_stats2.restype = None
            _lib.lz_serve_trace.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int
            ]
            _lib.lz_serve_trace.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: per-op timing/trace channel stays off
        try:
            _lib.lz_serve_trace2.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int
            ]
            _lib.lz_serve_trace2.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: session attribution rides trace as 0
        try:
            _lib.lz_serve_trace3.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int
            ]
            _lib.lz_serve_trace3.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: queue-wait slot drains as 0
        try:
            _lib.lz_serve_shm_stats.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)
            ]
            _lib.lz_serve_shm_stats.restype = None
        except AttributeError:
            pass  # stale .so: shm ring counters stay off
        try:
            _lib.lz_serve_qos_set.argtypes = [
                ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
            ]
            _lib.lz_serve_qos_set.restype = ctypes.c_int
            _lib.lz_serve_qos_deferrals.argtypes = [ctypes.c_int]
            _lib.lz_serve_qos_deferrals.restype = ctypes.c_uint64
        except AttributeError:
            pass  # stale .so: native plane stays unpaced (QoS fails open)
    except AttributeError:
        _lib = None


# lz_serve_trace3 flattens one op to 10 u64 slots (lz_serve_trace2
# serves 9, eliding queue_us; the legacy lz_serve_trace serves 8, also
# eliding session_id) — keep in sync with serve_native.cpp TraceOp
TRACE_OP_SLOTS = 10
_TRACE_KINDS = {1: "cs_read", 2: "cs_read_bulk", 4: "cs_write_bulk",
                5: "cs_write_shm"}


def available() -> bool:
    return _lib is not None


class DataPlaneServer:
    """One native data-plane listener bound to a set of data folders."""

    def __init__(self, folders: list[str], host: str = "127.0.0.1",
                 port: int = 0):
        if _lib is None:
            raise RuntimeError("native serve library unavailable")
        blob = "\n".join(folders).encode()
        self._handle = _lib.lz_serve_start(blob, host.encode(), port)
        if self._handle < 0:
            raise RuntimeError("lz_serve_start failed")
        self.port = _lib.lz_serve_port(self._handle)

    def stats(self) -> dict[str, int]:
        """v1 counters plus, when the .so exports stats v2, per-op
        accumulated disk/net microseconds per direction — the native
        per-op counters folded into the chunkserver's Metrics registry."""
        if hasattr(_lib, "lz_serve_stats2"):
            out = (ctypes.c_uint64 * 8)()
            _lib.lz_serve_stats2(self._handle, out)
            return {
                "bytes_read": out[0],
                "bytes_written": out[1],
                "read_ops": out[2],
                "write_ops": out[3],
                "read_disk_us": out[4],
                "read_net_us": out[5],
                "write_disk_us": out[6],
                "write_net_us": out[7],
            }
        out = (ctypes.c_uint64 * 4)()
        _lib.lz_serve_stats(self._handle, out)
        return {
            "bytes_read": out[0],
            "bytes_written": out[1],
            "read_ops": out[2],
            "write_ops": out[3],
        }

    def shm_stats(self) -> dict[str, int]:
        """Shared-memory ring plane counters (shm_ring.h proactor):
        segments mapped, descriptor ops landed, payload bytes moved via
        ring, and currently active mappings. Zeros on a stale .so."""
        if not hasattr(_lib, "lz_serve_shm_stats") or self._handle < 0:
            return {"segments_mapped": 0, "desc_ops": 0, "bytes": 0,
                    "active_segments": 0}
        out = (ctypes.c_uint64 * 4)()
        _lib.lz_serve_shm_stats(self._handle, out)
        return {
            "segments_mapped": out[0],
            "desc_ops": out[1],
            "bytes": out[2],
            "active_segments": out[3],
        }

    def trace_ops(self, max_ops: int = 1024) -> list[dict]:
        """Drain the native per-op trace ring: one dict per traced op
        with CLOCK_REALTIME second bounds (t0/t1), accumulated disk/net
        microseconds, the originating session id, and (trace3 .so) the
        op's QoS queue-wait microseconds, ready to fold into a
        SpanRing + per-session accounting."""
        if self._handle < 0:
            return []
        # version-skew tolerant drain: prefer the 10-slot channel (adds
        # queue_us), then the 9-slot one (session_id), then the legacy
        # 8-slot one on a stale .so
        if hasattr(_lib, "lz_serve_trace3"):
            slots, fn = TRACE_OP_SLOTS, _lib.lz_serve_trace3
        elif hasattr(_lib, "lz_serve_trace2"):
            slots, fn = 9, _lib.lz_serve_trace2
        elif hasattr(_lib, "lz_serve_trace"):
            slots, fn = 8, _lib.lz_serve_trace
        else:
            return []
        out = (ctypes.c_uint64 * (slots * max_ops))()
        n = fn(self._handle, out, max_ops)
        ops = []
        for i in range(n):
            s = out[slots * i : slots * (i + 1)]
            ops.append({
                "name": _TRACE_KINDS.get(int(s[0]), f"cs_op_{int(s[0])}"),
                "trace_id": int(s[1]),
                "chunk_id": int(s[2]),
                "bytes": int(s[3]),
                "t0": s[4] / 1e6,
                "t1": s[5] / 1e6,
                "disk_us": int(s[6]),
                "net_us": int(s[7]),
                "session_id": int(s[8]) if slots > 8 else 0,
                "queue_us": int(s[9]) if slots > 9 else 0,
            })
        return ops

    def qos_set(self, budgets: dict[int, int]) -> bool:
        """Replace the native plane's per-session byte-rate budget
        table (multi-tenant QoS; master-pushed via heartbeat acks).
        The epoll proactor defers over-budget descriptor drains and
        the threaded read/write paths pace with bounded sleeps. Returns
        False on a stale .so — the native plane then simply stays
        unpaced (QoS fails open, never into a lockout)."""
        if not hasattr(_lib, "lz_serve_qos_set") or self._handle < 0:
            return False
        n = len(budgets)
        sids = (ctypes.c_uint64 * max(n, 1))(*budgets.keys())
        bps = (ctypes.c_uint64 * max(n, 1))(*budgets.values())
        return _lib.lz_serve_qos_set(self._handle, sids, bps, n) == 0

    def qos_deferrals(self) -> int:
        """Data-plane ops paced/deferred by the QoS budgets."""
        if not hasattr(_lib, "lz_serve_qos_deferrals") or self._handle < 0:
            return 0
        return int(_lib.lz_serve_qos_deferrals(self._handle))

    def stop(self) -> None:
        if self._handle >= 0:
            _lib.lz_serve_stop(self._handle)
            self._handle = -1
