"""Chunkserver: disk store, serving state machine, replicator."""
