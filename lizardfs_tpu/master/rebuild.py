"""RebuildEngine: the master's explicit rebuild scheduler.

Elevates the endangered-FIFO -> replicator handoff (master/chunks.py
``health_work`` + chunkserver ``MatocsReplicate``) into a first-class
subsystem (reference analog: the replication limits + priority queues
of chunks.cc:1807-2200, made explicit):

  * **priority classes** — lost (one more failure loses data) >
    endangered (degraded but with margin) > rebalance (placement
    moves); higher classes always drain first,
  * **token-bucket throttle** — a cluster-wide rebuild bytes/s budget
    plus a concurrent-rebuild cap, both runtime-tunable through the
    tweaks registry (``rebuild_bps`` / ``rebuild_concurrency``, set via
    ``lizardfs-admin tweaks-set`` or SIGHUP-reloaded scripts), so a
    mass-rebuild after a server loss cannot starve client IO,
  * **progress/ETA accounting** — queued/active/completed/failed
    counts, bytes rebuilt, a sliding-window rebuild rate and the ETA it
    implies for the queued backlog,
  * **observability** — every rebuild carries a trace id (the
    executing chunkserver records its replication span under the same
    id, runtime/tracing.py) and lands in the ``replicate`` SLO class;
    the whole state is served by ``lizardfs-admin rebuild-status`` and
    the webui.

The engine schedules; the master executes (``_replicate_part`` /
``_move_part``) and reports back via :meth:`finished`.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from lizardfs_tpu.runtime.limiter import TokenBucket

PRIORITY_LOST = 0
PRIORITY_ENDANGERED = 1
PRIORITY_REBALANCE = 2
PRIORITY_NAMES = {
    PRIORITY_LOST: "lost",
    PRIORITY_ENDANGERED: "endangered",
    PRIORITY_REBALANCE: "rebalance",
}

# sliding window over which the rebuild byte rate (and so the ETA) is
# computed
RATE_WINDOW_S = 30.0


@dataclass
class Rebuild:
    """One scheduled rebuild (a part replication or a placement move)."""

    chunk_id: int
    part: int
    priority: int
    kind: str = "replicate"  # "replicate" | "move"
    bytes_est: int = 0
    src_cs: int = 0  # moves: the holder being drained
    dst_cs: int = 0  # moves: the target
    trace_id: int = 0
    queued_at: float = field(default_factory=time.monotonic)
    started_at: float = 0.0

    @property
    def key(self) -> tuple[int, int]:
        return (self.chunk_id, self.part)

    def to_dict(self, now: float) -> dict:
        return {
            "chunk_id": self.chunk_id,
            "part": self.part,
            "class": PRIORITY_NAMES.get(self.priority, "?"),
            "kind": self.kind,
            "bytes": self.bytes_est,
            "trace_id": self.trace_id,
            "running_s": round(now - self.started_at, 2)
            if self.started_at else 0.0,
        }


def classify(chunk, state) -> int:
    """Priority class of a repair work item from its redundancy state
    (master/chunks.py RedundancyState): a chunk whose NEXT failure
    loses data is 'lost'-class work; anything else degraded is
    'endangered'."""
    if not state.is_readable:
        return PRIORITY_LOST  # only stale-version/filerepair can help
    if not state.missing_parts or state.boost_only:
        # heat-boost copies (base goal already satisfied) are placement
        # work: they must never outrank real repairs in the queue
        return PRIORITY_REBALANCE
    from lizardfs_tpu.core import geometry

    t = geometry.SliceType(chunk.slice_type)
    if t.is_standard:
        # a single live copy under a multi-copy goal: one more loss is
        # data loss
        live = len(chunk.parts_by_index().get(0, []))
        return PRIORITY_LOST if live <= 1 and chunk.copies > 1 \
            else PRIORITY_ENDANGERED
    return PRIORITY_LOST if not state.is_safe else PRIORITY_ENDANGERED


class RebuildEngine:
    def __init__(self, metrics=None, tweaks=None):
        self.metrics = metrics
        # throttle knobs ride the daemon tweaks registry so they are
        # admin/SIGHUP tunable without a restart (0 bps = unlimited)
        if tweaks is not None:
            self._bps = tweaks.register("rebuild_bps", 0)
            self._max_active = tweaks.register("rebuild_concurrency", 8)
        else:  # unit tests / detached use
            class _V:  # noqa: N801 - tiny value cell
                def __init__(self, v):
                    self.value = v

            self._bps = _V(0)
            self._max_active = _V(8)
        self.bucket = TokenBucket(0.0)
        self.queues: dict[int, deque[Rebuild]] = {
            p: deque() for p in PRIORITY_NAMES
        }
        self._queued: dict[tuple[int, int], Rebuild] = {}
        self.active: dict[tuple[int, int], Rebuild] = {}
        self.recent: deque[dict] = deque(maxlen=32)
        self.completed = 0
        self.failed = 0
        self.bytes_rebuilt = 0
        self._rate_events: deque[tuple[float, int]] = deque()

    # --- scheduling ---------------------------------------------------------

    def submit(self, rb: Rebuild) -> bool:
        """Queue a rebuild; False when (chunk, part) is already queued
        or running (the endangered FIFO re-marks aggressively). A
        resubmission at a HIGHER priority class upgrades the queued
        entry in place — a chunk that degrades further while waiting
        (second server lost) must not sit behind the backlog of the
        class it no longer belongs to."""
        if rb.key in self.active:
            return False
        queued = self._queued.get(rb.key)
        if queued is not None:
            if rb.priority < queued.priority:
                self.queues[queued.priority].remove(queued)
                queued.priority = rb.priority
                self.queues[queued.priority].append(queued)
            return False
        self.queues[rb.priority].append(rb)
        self._queued[rb.key] = rb
        if self.metrics is not None:
            self.metrics.counter(
                "rebuilds_queued",
                help="rebuilds accepted by the RebuildEngine scheduler",
            ).inc()
        return True

    def next_batch(self) -> list[Rebuild]:
        """Pop launchable rebuilds: strict priority order, bounded by
        the concurrency cap. The caller launches each and MUST report
        via :meth:`finished`."""
        out: list[Rebuild] = []
        cap = max(int(self._max_active.value), 1)
        now = time.monotonic()
        for prio in sorted(self.queues):
            q = self.queues[prio]
            while q and len(self.active) + len(out) < cap:
                rb = q.popleft()
                self._queued.pop(rb.key, None)
                rb.started_at = now
                out.append(rb)
        for rb in out:
            self.active[rb.key] = rb
        return out

    async def throttle(self, nbytes: int) -> None:
        """Pace a rebuild's bytes against the cluster budget (awaits
        until the token bucket allows; rate 0 = unlimited). The rate is
        re-read from the tweak each time so tweaks-set applies to the
        next rebuild, not the next restart."""
        self.bucket.rate = float(self._bps.value)
        self.bucket.burst = max(self.bucket.rate, 1.0)
        await self.bucket.acquire(nbytes)

    def skipped(self, rb: Rebuild) -> None:
        """A launched rebuild that never attempted work (no eligible
        target, link gone, chunk re-locked): release the slot without
        counting a failure — the health tick resubmits when the
        condition clears, and a no-op must not page anyone via
        lizardfs_rebuilds_failed_total."""
        self.active.pop(rb.key, None)

    def finished(self, rb: Rebuild, ok: bool, nbytes: int = 0) -> None:
        self.active.pop(rb.key, None)
        now = time.monotonic()
        if ok:
            self.completed += 1
            n = nbytes or rb.bytes_est
            self.bytes_rebuilt += n
            self._rate_events.append((now, n))
            if self.metrics is not None:
                self.metrics.counter(
                    "rebuilds_completed",
                    help="rebuilds that wrote their part successfully",
                ).inc()
                self.metrics.counter(
                    "rebuild_bytes",
                    help="bytes of parts rebuilt by the engine",
                ).inc(float(n))
        else:
            self.failed += 1
            if self.metrics is not None:
                self.metrics.counter(
                    "rebuilds_failed",
                    help="rebuilds that errored or timed out",
                ).inc()
        self.recent.appendleft({
            "chunk_id": rb.chunk_id, "part": rb.part, "kind": rb.kind,
            "class": PRIORITY_NAMES.get(rb.priority, "?"),
            "ok": ok, "ms": round((now - rb.started_at) * 1e3, 1),
            "bytes": nbytes or rb.bytes_est, "trace_id": rb.trace_id,
        })

    # --- accounting ---------------------------------------------------------

    def rate_bps(self, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        while self._rate_events and \
                self._rate_events[0][0] < now - RATE_WINDOW_S:
            self._rate_events.popleft()
        total = sum(n for _, n in self._rate_events)
        return total / RATE_WINDOW_S

    def status(self) -> dict:
        """The ``rebuild-status`` document: queue depths by class,
        active rebuilds, throttle config, measured rate + backlog ETA,
        recent completions."""
        now = time.monotonic()
        pending_bytes = sum(
            rb.bytes_est for q in self.queues.values() for rb in q
        ) + sum(rb.bytes_est for rb in self.active.values())
        rate = self.rate_bps(now)
        eta = round(pending_bytes / rate, 1) if rate > 0 else None
        return {
            "queued": {
                PRIORITY_NAMES[p]: len(q) for p, q in self.queues.items()
            },
            "active": [rb.to_dict(now) for rb in self.active.values()],
            "throttle": {
                "rebuild_bps": int(self._bps.value),
                "rebuild_concurrency": int(self._max_active.value),
            },
            "completed": self.completed,
            "failed": self.failed,
            "bytes_rebuilt": self.bytes_rebuilt,
            "rate_bps": round(rate, 1),
            "pending_bytes": pending_bytes,
            "eta_s": eta,
            "recent": list(self.recent)[:16],
        }
