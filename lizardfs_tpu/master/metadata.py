"""MetadataStore: the replayable state machine behind the master.

Owns the FS tree + the *persistent* half of the chunk registry and
applies operation records. The live master builds an op, applies it,
and appends it to the changelog; shadows and crash recovery apply the
same records through the same code path — the restore.cc pattern, with
one implementation instead of two.
"""

from __future__ import annotations

from lizardfs_tpu.master.chunks import ChunkRegistry
from lizardfs_tpu.master.fs import FsError, FsTree
from lizardfs_tpu.master.locks import LockManager
from lizardfs_tpu.master.quotas import QuotaDatabase


class MetadataStore:
    def __init__(self):
        self.fs = FsTree()
        self.registry = ChunkRegistry()
        self.quotas = QuotaDatabase()
        # held file locks replicate through the changelog so a promoted
        # shadow still knows them (reference: LOCK section,
        # src/master/filesystem_store.cc:952-1180); pending waiters are
        # live-master-only state
        self.locks = LockManager()
        # session-id allocation replicates so a promoted shadow never
        # re-issues an id whose locks are still held (sessions.mfs
        # analog for the id space; live connection state stays local)
        self.next_session = 1

    # --- op application (the one true mutation path) -------------------------

    def apply(self, op: dict) -> None:
        fn = getattr(self, "_op_" + op["op"], None)
        if fn is None:
            raise ValueError(f"unknown op {op['op']!r}")
        fn(op)

    def _op_mknode(self, op):
        self.fs.apply_mknode(
            op["parent"], op["name"], op["inode"], op["ftype"], op["mode"],
            op["uid"], op["gid"], op["ts"], op["goal"], op["trash_time"],
            op.get("symlink_target", ""),
        )
        self.quotas.charge(op["uid"], op["gid"], 1, 0)

    def _op_unlink(self, op):
        node = self.fs.apply_unlink(op["parent"], op["name"], op["ts"], op["to_trash"])
        if node.nlink <= 0 and node.inode not in self.fs.trash:
            self.quotas.charge(node.uid, node.gid, -1, -node.length)
            for cid in node.chunks:
                if cid:
                    self.registry.release_chunk(cid)

    def _op_rmdir(self, op):
        parent = self.fs.dir_node(op["parent"])
        child = parent.children.get(op["name"])
        node = self.fs.nodes.get(child) if child else None
        self.fs.apply_rmdir(op["parent"], op["name"], op["ts"])
        if node is not None:
            self.quotas.charge(node.uid, node.gid, -1, 0)

    def _op_rename(self, op):
        # snapshot any destination entry that the rename will overwrite:
        # if it leaves the tree entirely (no trash), its chunk references
        # and quota charges must be released here — the fs layer knows
        # nothing about the registry or quotas
        pre = None
        pd = self.fs.nodes.get(op["parent_dst"])
        if pd is not None and pd.ftype == 2:
            existing = pd.children.get(op["name_dst"])
            if existing is not None:
                ex = self.fs.nodes.get(existing)
                if ex is not None:
                    pre = (ex.inode, ex.uid, ex.gid, ex.length,
                           list(ex.chunks), ex.ftype)
        self.fs.apply_rename(
            op["parent_src"], op["name_src"], op["parent_dst"], op["name_dst"],
            op["ts"],
        )
        if pre is not None and pre[0] not in self.fs.nodes:
            _, uid, gid, length, chunks, ftype = pre
            self.quotas.charge(uid, gid, -1, -length if ftype == 1 else 0)
            for cid in chunks:
                if cid:
                    self.registry.release_chunk(cid)

    def _op_link(self, op):
        self.fs.apply_link(op["inode"], op["parent"], op["name"], op["ts"])

    def _op_setattr(self, op):
        self.fs.apply_setattr(
            op["inode"], op["set_mask"], op["mode"], op["uid"], op["gid"],
            op["atime"], op["mtime"], op["ts"], op.get("trash_time", 0),
        )

    def _op_setgoal(self, op):
        self.fs.apply_setgoal(op["inode"], op["goal"], op["ts"])

    def _op_set_length(self, op):
        node = self.fs.file_node(op["inode"])
        delta = op["length"] - node.length
        removed = self.fs.apply_set_length(
            op["inode"], op["length"], op["ts"],
            drop_chunks=op.get("drop_chunks", True),
        )
        self.quotas.charge(node.uid, node.gid, 0, delta)
        for cid in removed:
            self.registry.release_chunk(cid)

    def _op_create_chunk(self, op):
        self.registry.create_chunk(
            op["slice_type"], chunk_id=op["chunk_id"], version=op["version"],
            copies=op.get("copies", 1), goal_id=op.get("goal_id", 0),
        )

    def _op_set_chunk(self, op):
        self.fs.apply_set_chunk(op["inode"], op["chunk_index"], op["chunk_id"])

    def _op_bump_chunk_version(self, op):
        self.registry.chunk(op["chunk_id"]).version = op["version"]

    def _op_delete_chunk(self, op):
        self.registry.delete_chunk(op["chunk_id"])

    def _op_purge_trash(self, op):
        node = self.fs.nodes.get(op["inode"])
        if node is not None:
            self.quotas.charge(node.uid, node.gid, -1, -node.length)
            for cid in node.chunks:
                if cid:
                    self.registry.release_chunk(cid)
        self.fs.apply_purge_trash(op["inode"])

    def _op_undelete(self, op):
        self.fs.apply_undelete(op["inode"], op["ts"])

    def _op_set_acl(self, op):
        self.fs.apply_set_acl(
            op["inode"], op.get("access"), op.get("default"), op["ts"]
        )

    def _op_set_rich_acl(self, op):
        self.fs.apply_set_rich_acl(op["inode"], op.get("acl"), op["ts"])

    def _op_set_xattr(self, op):
        self.fs.apply_set_xattr(op["inode"], op["name"], op["value"], op["ts"])

    def _op_set_quota(self, op):
        if op.get("remove"):
            self.quotas.remove(op["kind"], op["owner_id"])
        else:
            self.quotas.set_limits(
                op["kind"], op["owner_id"], op["soft_inodes"],
                op["hard_inodes"], op["soft_bytes"], op["hard_bytes"],
            )

    def _op_snapshot(self, op):
        shared = self.fs.apply_snapshot(
            op["src_inode"], op["dst_parent"], op["dst_name"],
            op["inode_map"], op["ts"],
        )
        for cid, delta in shared:
            chunk = self.registry.chunks.get(cid)
            if chunk is not None:
                chunk.refcount += delta
        # cloned nodes charge their owners
        src = self.fs.node(op["inode_map"][str(op["src_inode"])])
        wi, wb = self.fs._node_weight(src)
        self.quotas.charge(src.uid, src.gid, wi, wb)

    def _op_cow_chunk(self, op):
        """Copy-on-write: a file's shared chunk was duplicated; point the
        file at the private copy."""
        old = self.registry.chunks.get(op["old_chunk_id"])
        self.registry.create_chunk(
            op["slice_type"], chunk_id=op["new_chunk_id"],
            version=op["version"], copies=op.get("copies", 1),
            goal_id=op.get("goal_id", 0),
        )
        if old is not None:
            old.refcount -= 1
        self.fs.apply_set_chunk(op["inode"], op["chunk_index"], op["new_chunk_id"])

    def _op_lock_posix(self, op):
        self.locks.posix(
            op["inode"], op["sid"], op["token"], op["start"], op["end"],
            op["ltype"],
        )

    def _op_lock_flock(self, op):
        self.locks.flock(op["inode"], op["sid"], op["token"], op["ltype"])

    def _op_lock_release_session(self, op):
        self.locks.release_session(op["sid"])

    def _op_session_new(self, op):
        self.next_session = max(self.next_session, op["sid"] + 1)

    # --- persistence sections --------------------------------------------------

    def to_sections(self) -> dict:
        return {
            "fs": self.fs.to_dict(),
            "chunks": {
                "next_chunk_id": self.registry.next_chunk_id,
                "table": [
                    {"id": c.chunk_id, "version": c.version,
                     "slice_type": c.slice_type, "copies": c.copies,
                     "refcount": c.refcount, "goal_id": c.goal_id}
                    for c in self.registry.chunks.values()
                ],
            },
            "quotas": self.quotas.to_dict(),
            "next_session": self.next_session,
            "locks": {
                kind: {
                    str(inode): [
                        [r.start, r.end, r.ltype, r.owner.session_id,
                         r.owner.token]
                        for r in fl.ranges
                    ]
                    for inode, fl in table.items() if fl.ranges
                }
                for kind, table in (
                    ("posix", self.locks.posix_files),
                    ("flock", self.locks.flock_files),
                )
            },
        }

    def load_sections(self, doc: dict) -> None:
        self.fs = FsTree.from_dict(doc["fs"])
        self.registry = ChunkRegistry()
        ch = doc["chunks"]
        self.registry.next_chunk_id = ch["next_chunk_id"]
        for row in ch["table"]:
            c = self.registry.create_chunk(
                row["slice_type"], chunk_id=row["id"], version=row["version"],
                copies=row.get("copies", 1), goal_id=row.get("goal_id", 0),
            )
            c.refcount = row.get("refcount", 1)
        self.registry.next_chunk_id = ch["next_chunk_id"]
        self.quotas = QuotaDatabase.from_dict(doc.get("quotas", {}))
        self.locks = LockManager()
        self.next_session = int(doc.get("next_session", 1))
        from lizardfs_tpu.master.locks import FileLocks, Owner, Range

        for kind, table in (
            ("posix", self.locks.posix_files),
            ("flock", self.locks.flock_files),
        ):
            for inode_s, rows in doc.get("locks", {}).get(kind, {}).items():
                fl = table[int(inode_s)] = FileLocks()
                fl.ranges = [
                    Range(start, end, ltype, Owner(sid, token))
                    for start, end, ltype, sid, token in rows
                ]

    def checksum(self, cache_key: int | None = None) -> str:
        """Divergence-detection digest over FS + persistent chunk state.

        ``cache_key`` (the changelog version) memoizes the digest so
        repeated probes at the same version cost nothing; the full
        serialization still runs once per version — an incremental
        checksum (the reference's filesystem_checksum) is the scaling
        follow-up.
        """
        import hashlib
        import json

        if cache_key is not None and getattr(
            self, "_checksum_cache", (None, None)
        )[0] == cache_key:
            return self._checksum_cache[1]
        blob = json.dumps(self.to_sections(), sort_keys=True).encode()
        digest = hashlib.sha256(blob).hexdigest()
        if cache_key is not None:
            self._checksum_cache = (cache_key, digest)
        return digest
