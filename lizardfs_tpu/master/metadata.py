"""MetadataStore: the replayable state machine behind the master.

Owns the FS tree + the *persistent* half of the chunk registry and
applies operation records. The live master builds an op, applies it,
and appends it to the changelog; shadows and crash recovery apply the
same records through the same code path — the restore.cc pattern, with
one implementation instead of two.
"""

from __future__ import annotations

from lizardfs_tpu.master.chunks import ChunkRegistry
from lizardfs_tpu.master.fs import FsTree
from lizardfs_tpu.master.locks import LockManager
from lizardfs_tpu.master.quotas import QuotaDatabase


class MetadataStore:
    def __init__(self):
        self.fs = FsTree()
        self.registry = ChunkRegistry()
        self.quotas = QuotaDatabase()
        # held file locks replicate through the changelog so a promoted
        # shadow still knows them (reference: LOCK section,
        # src/master/filesystem_store.cc:952-1180); pending waiters are
        # live-master-only state
        self.locks = LockManager()
        # session-id allocation replicates so a promoted shadow never
        # re-issues an id whose locks are still held (sessions.mfs
        # analog for the id space; live connection state stays local)
        self.next_session = 1
        # cluster fencing epoch (uraft term analog): bumped by the
        # epoch_bump op a freshly elected master commits as its FIRST
        # write. Every register/heartbeat link carries it, so a zombie
        # ex-primary (deposed but still running) is refused by its own
        # former peers instead of having late writes merged. Replicated
        # through the changelog and persisted in the image.
        self.epoch = 0
        # tape-copy records (matotsserv analog): inode -> list of
        # {"label","length","mtime","gen","ts"} archival copies;
        # replicated through the changelog and persisted in the image
        self.tape_copies: dict[int, list[dict]] = {}
        # per-inode content generation: bumped by every content op, it
        # stamps tape copies so a same-second same-length rewrite still
        # reads as stale. Deterministic from the op stream (shadows
        # converge), so excluded from the digest like next_inode.
        self.content_gen: dict[int, int] = {}
        # lifecycle-demoted (tape-only) inodes: inode -> {"length",
        # "mtime", "gen"} content stamp at demote time. A demoted file
        # keeps its length/mtime but holds no chunks — reads/writes are
        # refused with TAPE_RECALL until a recall restores the bytes.
        # Replicated through the changelog (demote frees chunk refs, so
        # shadows must apply it identically) and persisted in the image.
        self.demoted: dict[int, dict] = {}
        # incremental metadata digest (see checksum())
        self._digest = 0
        self.reset_digest()

    # --- op application (the one true mutation path) -------------------------

    def apply(self, op: dict) -> None:
        fn = getattr(self, "_op_" + op["op"], None)
        if fn is None:
            raise ValueError(f"unknown op {op['op']!r}")
        # incremental digest (filesystem_checksum.cc analog): XOR out
        # the touched entities' pre-state hashes, apply, XOR in their
        # post-state hashes. _touched(op) must include every entity that
        # existed before AND may change; entities that appear only after
        # the op (post-only keys) hashed 0 before, so the union form is
        # exact for them. entity_hash(missing) == 0 by convention.
        keys = self._touched(op)
        delta = 0
        for key in keys:
            delta ^= self._entity_hash(key)
        fn(op)
        # unchanged keys cancel (h ^ h == 0); changed keys contribute
        # pre ^ post; post-only keys contribute their fresh hash once
        for key in keys | self._touched(op):
            delta ^= self._entity_hash(key)
        self._digest ^= delta

    def _op_mknode(self, op):
        self.fs.apply_mknode(
            op["parent"], op["name"], op["inode"], op["ftype"], op["mode"],
            op["uid"], op["gid"], op["ts"], op["goal"], op["trash_time"],
            op.get("symlink_target", ""),
        )
        self.quotas.charge(op["uid"], op["gid"], 1, 0)

    def _op_unlink(self, op):
        node = self.fs.apply_unlink(op["parent"], op["name"], op["ts"], op["to_trash"])
        if (
            node.nlink <= 0
            and node.inode not in self.fs.trash
            and node.inode not in self.fs.sustained
        ):
            self.quotas.charge(node.uid, node.gid, -1, -node.length)
            for cid in node.chunks:
                if cid:
                    self.registry.release_chunk(cid)

    def _op_rmdir(self, op):
        parent = self.fs.dir_node(op["parent"])
        child = parent.children.get(op["name"])
        node = self.fs.nodes.get(child) if child else None
        self.fs.apply_rmdir(op["parent"], op["name"], op["ts"])
        if node is not None:
            self.quotas.charge(node.uid, node.gid, -1, 0)

    def _op_rename(self, op):
        # snapshot any destination entry that the rename will overwrite:
        # if it leaves the tree entirely (no trash), its chunk references
        # and quota charges must be released here — the fs layer knows
        # nothing about the registry or quotas
        pre = None
        pd = self.fs.nodes.get(op["parent_dst"])
        if pd is not None and pd.ftype == 2:
            existing = pd.children.get(op["name_dst"])
            if existing is not None:
                ex = self.fs.nodes.get(existing)
                if ex is not None:
                    pre = (ex.inode, ex.uid, ex.gid, ex.length,
                           list(ex.chunks), ex.ftype)
        self.fs.apply_rename(
            op["parent_src"], op["name_src"], op["parent_dst"], op["name_dst"],
            op["ts"],
        )
        if pre is not None and pre[0] not in self.fs.nodes:
            _, uid, gid, length, chunks, ftype = pre
            self.quotas.charge(uid, gid, -1, -length if ftype == 1 else 0)
            for cid in chunks:
                if cid:
                    self.registry.release_chunk(cid)

    def _op_link(self, op):
        self.fs.apply_link(op["inode"], op["parent"], op["name"], op["ts"])

    def _op_setattr(self, op):
        self.fs.apply_setattr(
            op["inode"], op["set_mask"], op["mode"], op["uid"], op["gid"],
            op["atime"], op["mtime"], op["ts"], op.get("trash_time", 0),
        )

    def _op_setgoal(self, op):
        self.fs.apply_setgoal(op["inode"], op["goal"], op["ts"])

    def _op_seteattr(self, op):
        self.fs.apply_seteattr(op["inode"], op["eattr"], op["ts"])

    def _op_set_length(self, op):
        node = self.fs.file_node(op["inode"])
        delta = op["length"] - node.length
        removed = self.fs.apply_set_length(
            op["inode"], op["length"], op["ts"],
            drop_chunks=op.get("drop_chunks", True),
        )
        self.quotas.charge(node.uid, node.gid, 0, delta)
        for cid in removed:
            self.registry.release_chunk(cid)
        self.content_gen[op["inode"]] = \
            self.content_gen.get(op["inode"], 0) + 1

    def _op_create_chunk(self, op):
        self.registry.create_chunk(
            op["slice_type"], chunk_id=op["chunk_id"], version=op["version"],
            copies=op.get("copies", 1), goal_id=op.get("goal_id", 0),
        )

    def _op_set_chunk(self, op):
        self.fs.apply_set_chunk(op["inode"], op["chunk_index"], op["chunk_id"])
        self.content_gen[op["inode"]] = \
            self.content_gen.get(op["inode"], 0) + 1

    def _op_bump_chunk_version(self, op):
        self.registry.chunk(op["chunk_id"]).version = op["version"]

    def _op_delete_chunk(self, op):
        self.registry.delete_chunk(op["chunk_id"])

    def _op_goal_boost(self, op):
        """Heat-driven temporary goal boost: raise the chunk's wanted
        copy count by ``boost`` extra copies (master/heat.py adaptive
        replication). The live master decides thresholds/hysteresis
        OUTSIDE the op; apply is unconditional on a missing chunk being
        a no-op (the chunk may have been released between the heat
        decision and a shadow's replay)."""
        self.registry.set_boost(op["chunk_id"], op["boost"])

    def _op_goal_demote(self, op):
        """Heat decayed back under the demote threshold: drop the
        temporary boost (the redundant-copy path then sheds the extra
        replicas). No-op on a missing chunk, same as goal_boost."""
        self.registry.set_boost(op["chunk_id"], 0)

    def _op_purge_trash(self, op):
        node = self.fs.nodes.get(op["inode"])
        will_sustain = bool(self.fs.open_refs.get(op["inode"]))
        if node is not None and not will_sustain:
            # a sustained file keeps its chunks/quota until last close
            self.quotas.charge(node.uid, node.gid, -1, -node.length)
            for cid in node.chunks:
                if cid:
                    self.registry.release_chunk(cid)
        self.fs.apply_purge_trash(op["inode"])
        if op["inode"] not in self.fs.nodes:
            self.content_gen.pop(op["inode"], None)
            self.demoted.pop(op["inode"], None)

    def _op_undelete(self, op):
        self.fs.apply_undelete(op["inode"], op["ts"])

    def _op_set_acl(self, op):
        self.fs.apply_set_acl(
            op["inode"], op.get("access"), op.get("default"), op["ts"]
        )

    def _op_set_rich_acl(self, op):
        self.fs.apply_set_rich_acl(op["inode"], op.get("acl"), op["ts"])

    def _op_set_xattr(self, op):
        self.fs.apply_set_xattr(op["inode"], op["name"], op["value"], op["ts"])

    def _op_set_quota(self, op):
        if op.get("remove"):
            self.quotas.remove(op["kind"], op["owner_id"])
        else:
            self.quotas.set_limits(
                op["kind"], op["owner_id"], op["soft_inodes"],
                op["hard_inodes"], op["soft_bytes"], op["hard_bytes"],
            )

    def _op_snapshot(self, op):
        shared = self.fs.apply_snapshot(
            op["src_inode"], op["dst_parent"], op["dst_name"],
            op["inode_map"], op["ts"],
        )
        for cid, delta in shared:
            chunk = self.registry.chunks.get(cid)
            if chunk is not None:
                chunk.refcount += delta
        # cloned nodes charge their owners
        src = self.fs.node(op["inode_map"][str(op["src_inode"])])
        wi, wb = self.fs._node_weight(src)
        self.quotas.charge(src.uid, src.gid, wi, wb)

    def _op_append_chunks(self, op):
        dst = self.fs.file_node(op["inode_dst"])
        old_len = dst.length
        shared = self.fs.apply_append_chunks(
            op["inode_dst"], op["inode_src"], op["ts"]
        )
        for cid in shared:
            chunk = self.registry.chunks.get(cid)
            if chunk is not None:
                chunk.refcount += 1
        self.quotas.charge(dst.uid, dst.gid, 0, dst.length - old_len)
        self.content_gen[op["inode_dst"]] = \
            self.content_gen.get(op["inode_dst"], 0) + 1

    def _op_repair_zero_chunk(self, op):
        cid = self.fs.apply_repair_zero_chunk(
            op["inode"], op["chunk_index"], op["ts"]
        )
        if cid:
            self.registry.release_chunk(cid)
        self.content_gen[op["inode"]] = \
            self.content_gen.get(op["inode"], 0) + 1

    def _op_cow_chunk(self, op):
        """Copy-on-write: a file's shared chunk was duplicated; point the
        file at the private copy."""
        old = self.registry.chunks.get(op["old_chunk_id"])
        self.registry.create_chunk(
            op["slice_type"], chunk_id=op["new_chunk_id"],
            version=op["version"], copies=op.get("copies", 1),
            goal_id=op.get("goal_id", 0),
        )
        if old is not None:
            old.refcount -= 1
        self.fs.apply_set_chunk(op["inode"], op["chunk_index"], op["new_chunk_id"])

    # --- open-file registry / sustained files (reference: "reserved") ---

    def _op_acquire(self, op):
        self.fs.apply_acquire(op["inode"], op["sid"])

    def _release_one(self, inode: int, sid: int) -> None:
        node = self.fs.nodes.get(inode)
        if self.fs.apply_release(inode, sid) and node is not None:
            # last close of a sustained (nameless) file: free it now —
            # the purge_trash pattern, deferred to the final release
            self.quotas.charge(node.uid, node.gid, -1, -node.length)
            for cid in node.chunks:
                if cid:
                    self.registry.release_chunk(cid)
            self.fs.nodes.pop(inode, None)
            self.content_gen.pop(inode, None)
            self.demoted.pop(inode, None)

    def _op_release(self, op):
        self._release_one(op["inode"], op["sid"])

    def _op_release_session_opens(self, op):
        sid = op["sid"]
        for inode in [
            i for i, refs in list(self.fs.open_refs.items()) if sid in refs
        ]:
            while sid in self.fs.open_refs.get(inode, {}):
                self._release_one(inode, sid)

    def _op_lock_posix(self, op):
        self.locks.posix(
            op["inode"], op["sid"], op["token"], op["start"], op["end"],
            op["ltype"],
        )

    def _op_lock_flock(self, op):
        self.locks.flock(op["inode"], op["sid"], op["token"], op["ltype"])

    def _op_lock_release_session(self, op):
        self.locks.release_session(op["sid"])

    def _op_session_new(self, op):
        self.next_session = max(self.next_session, op["sid"] + 1)

    def _op_epoch_bump(self, op):
        """Fenced promotion (HA tentpole): a freshly elected master's
        first committed write claims the new cluster epoch. max() keeps
        replay monotone even if an old line is re-applied."""
        self.epoch = max(self.epoch, op["epoch"])

    # --- persistence sections --------------------------------------------------

    def to_sections(self) -> dict:
        return {
            "fs": self.fs.to_dict(),
            "chunks": {
                "next_chunk_id": self.registry.next_chunk_id,
                "table": [
                    {"id": c.chunk_id, "version": c.version,
                     "slice_type": c.slice_type, "copies": c.copies,
                     "refcount": c.refcount, "goal_id": c.goal_id,
                     "boost": c.boost}
                    for c in self.registry.chunks.values()
                ],
            },
            "quotas": self.quotas.to_dict(),
            "next_session": self.next_session,
            "epoch": self.epoch,
            "tape": {str(i): c for i, c in self.tape_copies.items() if c},
            "tape_gen": {str(i): g for i, g in self.content_gen.items()},
            "demoted": {str(i): d for i, d in self.demoted.items()},
            "locks": {
                kind: {
                    str(inode): [
                        [r.start, r.end, r.ltype, r.owner.session_id,
                         r.owner.token]
                        for r in fl.ranges
                    ]
                    for inode, fl in table.items() if fl.ranges
                }
                for kind, table in (
                    ("posix", self.locks.posix_files),
                    ("flock", self.locks.flock_files),
                )
            },
        }

    def load_sections(self, doc: dict) -> None:
        self.fs = FsTree.from_dict(doc["fs"])
        self.registry = ChunkRegistry()
        ch = doc["chunks"]
        self.registry.next_chunk_id = ch["next_chunk_id"]
        for row in ch["table"]:
            c = self.registry.create_chunk(
                row["slice_type"], chunk_id=row["id"], version=row["version"],
                copies=row.get("copies", 1), goal_id=row.get("goal_id", 0),
            )
            c.refcount = row.get("refcount", 1)
            self.registry.set_boost(c.chunk_id, row.get("boost", 0))
        self.registry.next_chunk_id = ch["next_chunk_id"]
        self.quotas = QuotaDatabase.from_dict(doc.get("quotas", {}))
        self.locks = LockManager()
        self.next_session = int(doc.get("next_session", 1))
        self.epoch = int(doc.get("epoch", 0))
        self.tape_copies = {
            int(i): list(c) for i, c in doc.get("tape", {}).items()
        }
        self.content_gen = {
            int(i): int(g) for i, g in doc.get("tape_gen", {}).items()
        }
        self.demoted = {
            int(i): dict(d) for i, d in doc.get("demoted", {}).items()
        }
        from lizardfs_tpu.master.locks import FileLocks, Owner, Range

        for kind, table in (
            ("posix", self.locks.posix_files),
            ("flock", self.locks.flock_files),
        ):
            for inode_s, rows in doc.get("locks", {}).get(kind, {}).items():
                fl = table[int(inode_s)] = FileLocks()
                fl.ranges = [
                    Range(start, end, ltype, Owner(sid, token))
                    for start, end, ltype, sid, token in rows
                ]
        self.reset_digest()

    # --- incremental checksum (filesystem_checksum.cc analog) ---------------
    #
    # The digest is the XOR of 128-bit hashes of every persistent entity:
    # nodes, trash entries, chunks, quota entries, per-inode lock tables,
    # and a misc tuple of allocator counters. apply() maintains it in
    # O(touched entities) per op; full_digest() recomputes from scratch
    # (used at load, by offline tools, and by the background verifier in
    # the image-dump child — the filesystem_checksum_background_updater
    # analog). Derived aggregates (directory stat_inodes/stat_bytes) are
    # excluded: they are recomputable and would make every write touch
    # its whole ancestor chain.

    def _h(self, *parts) -> int:
        import hashlib

        b = hashlib.blake2b(repr(parts).encode(), digest_size=16)
        return int.from_bytes(b.digest(), "big")

    def _entity_hash(self, key: tuple) -> int:
        kind = key[0]
        if kind == "node":
            n = self.fs.nodes.get(key[1])
            if n is None:
                return 0
            # children are hashed as separate ("edge", parent, name)
            # entities — otherwise every create in a directory would
            # hash the whole directory (O(children) per op); derived
            # stats are excluded as recomputable. Collections with
            # nondeterministic order (xattrs, acls) canonicalize.
            import json

            return self._h(
                "node", n.inode, n.ftype, n.mode, n.uid, n.gid, n.atime,
                n.mtime, n.ctime, n.goal, n.trash_time, n.nlink,
                tuple(n.parents),
                tuple(sorted(n.xattrs.items())) if n.xattrs else (),
                json.dumps(n.acl, sort_keys=True),
                json.dumps(n.default_acl, sort_keys=True),
                json.dumps(n.rich_acl, sort_keys=True),
                n.length, tuple(n.chunks) if n.chunks else (),
                n.symlink_target,
            )
        if kind == "edge":
            p = self.fs.nodes.get(key[1])
            if p is None or p.ftype != 2:
                return 0
            child = p.children.get(key[2])
            return 0 if child is None else self._h("edge", key[1], key[2],
                                                   child)
        if kind == "trash":
            entry = self.fs.trash.get(key[1])
            return 0 if entry is None else self._h("trash", key[1], tuple(entry))
        if kind == "chunk":
            c = self.registry.chunks.get(key[1])
            if c is None:
                return 0
            return self._h(
                "chunk", c.chunk_id, c.version, c.slice_type, c.copies,
                c.refcount, c.goal_id, c.boost,
            )
        if kind == "quota":
            e = self.quotas.entries.get((key[1], key[2]))
            if e is None:
                return 0
            import json

            return self._h("quota", key[1], key[2],
                           json.dumps(e.to_dict(), sort_keys=True))
        if kind == "locks":
            table = (self.locks.posix_files if key[1] == "posix"
                     else self.locks.flock_files)
            fl = table.get(key[2])
            if fl is None or not fl.ranges:
                return 0
            return self._h("locks", key[1], key[2], [
                (r.start, r.end, r.ltype, r.owner.session_id, r.owner.token)
                for r in fl.ranges
            ])
        if kind == "tape":
            copies = self.tape_copies.get(key[1])
            if not copies:
                return 0
            return self._h("tape", key[1], [
                (c["label"], c["length"], c["mtime"], c.get("gen", 0),
                 c["ts"])
                for c in copies
            ])
        if kind == "demoted":
            d = self.demoted.get(key[1])
            if d is None:
                return 0
            return self._h(
                "demoted", key[1], d["length"], d["mtime"], d.get("gen", 0)
            )
        if kind == "open":
            refs = self.fs.open_refs.get(key[1])
            if not refs:
                return 0
            return self._h("open", key[1], tuple(sorted(refs.items())))
        if kind == "sustained":
            if key[1] not in self.fs.sustained:
                return 0
            return self._h("sustained", key[1])
        if kind == "misc":
            # next_inode / next_chunk_id are EXCLUDED: the server
            # pre-reserves them outside apply() (alloc_inode, chunk-id
            # reservation), and apply maintains them monotonically via
            # max(), so shadows converge on them from the ops alone
            return self._h("misc", self.next_session, self.epoch)
        raise ValueError(f"unknown entity kind {kind!r}")

    def _op_synth_populate(self, op):
        """Storm-bench bulk load: deterministically create ``count``
        synthetic file nodes (each with one standard chunk whose parts
        sit on synthetic registry servers) in ONE changelog op, so an
        active master and its shadows converge on the same million-inode
        namespace without a million changelog lines.

        Digest discipline: this op maintains the incremental digest
        itself (``_touched`` would be O(count) twice; here each fresh
        entity hashes exactly once, plus pre/post for the parent and the
        uid/gid-0 usage rows), so shadow divergence detection still
        holds — test_scalability pins digest == full_digest after it."""
        parent = op["parent"]
        count = op["count"]
        base_inode = op["base_inode"]
        base_chunk = op["base_chunk"]
        n_servers = op.get("servers", 0)
        copies = op.get("copies", 1)
        length = op.get("length", 65536)
        ts = op["ts"]
        prefix = op.get("prefix", "sf")
        d = 0
        pre_keys = [("node", parent), ("quota", "user", 0),
                    ("quota", "group", 0)]
        for key in pre_keys:
            d ^= self._entity_hash(key)
        servers = [
            self.registry.register_server(
                "synth", 1 + j, "_", 1 << 40, 0
            )
            for j in range(n_servers)
        ]
        for i in range(count):
            inode = base_inode + i
            name = f"{prefix}{inode}"
            self.fs.apply_mknode(
                parent, name, inode, 1, 0o644, 0, 0, ts, 1, 0
            )
            node = self.fs.nodes[inode]
            cid = base_chunk + i
            node.length = length
            node.chunks = [cid]
            self.fs._add_stats(parent, 0, length)
            chunk = self.registry.create_chunk(
                0, chunk_id=cid, version=1, copies=copies
            )
            if servers:
                for r in range(copies):
                    srv = servers[(i + r) % len(servers)]
                    self.registry.record_part(chunk, srv.cs_id, 0)
            d ^= self._entity_hash(("node", inode))
            d ^= self._entity_hash(("edge", parent, name))
            d ^= self._entity_hash(("chunk", cid))
        self.quotas.charge(0, 0, count, count * length)
        for key in pre_keys:
            d ^= self._entity_hash(key)
        self._digest ^= d

    def _op_tape_copy(self, op):
        copies = self.tape_copies.setdefault(op["inode"], [])
        # one copy per tape-server label; a fresh copy replaces a stale
        # one from the same label
        copies[:] = [c for c in copies if c["label"] != op["label"]]
        copies.append({
            "label": op["label"], "length": op["length"],
            "mtime": op["mtime"], "gen": op.get("gen", 0), "ts": op["ts"],
        })

    def _op_tape_drop(self, op):
        self.tape_copies.pop(op["inode"], None)
        self.content_gen.pop(op["inode"], None)
        self.demoted.pop(op["inode"], None)

    def _op_tape_demote(self, op):
        """Demote to the tape tier: free the file's chunk data, record
        the content stamp the archival copy must match for recall. The
        live master only commits this with a fresh tape copy on hand;
        apply is unconditional (replay must not re-validate against
        volatile link state)."""
        inode = op["inode"]
        node = self.fs.file_node(inode)
        removed = self.fs.apply_demote(inode, op["ts"])
        for cid in removed:
            self.registry.release_chunk(cid)
        self.demoted[inode] = {
            "length": node.length, "mtime": node.mtime,
            "gen": self.content_gen.get(inode, 0),
        }

    def _op_tape_recall_done(self, op):
        """Recall finished: the archived bytes were written back. The
        restore writes bumped mtime/content_gen; put the original mtime
        back (a recall is not a modification) and re-stamp the tape
        copies that matched the demoted stamp to the CURRENT generation
        so the recall does not read as staleness (which would trigger a
        pointless re-archive of identical bytes)."""
        inode = op["inode"]
        stamp = self.demoted.pop(inode, None)
        node = self.fs.nodes.get(inode)
        if stamp is None or node is None:
            return
        if not op.get("restore", True):
            # a write raced the restore: the content is live again but
            # it is NOT the archived version — no mtime/stamp rewrite
            node.ctime = op["ts"]
            return
        node.mtime = stamp["mtime"]
        node.ctime = op["ts"]
        gen = self.content_gen.get(inode, 0)
        for c in self.tape_copies.get(inode, []):
            if (c["length"], c["mtime"], c.get("gen", 0)) == (
                stamp["length"], stamp["mtime"], stamp["gen"]
            ):
                c["gen"] = gen

    def _touched(self, op: dict) -> set[tuple]:
        """Entities whose state the op may change — evaluated against
        the CURRENT state (called both before and after apply; must be a
        superset of reality each time)."""
        t = op["op"]
        out: set[tuple] = {("misc",)}
        fs = self.fs

        def node_quota(inode):
            n = fs.nodes.get(inode)
            if n is not None:
                out.add(("quota", "user", n.uid))
                out.add(("quota", "group", n.gid))

        def node_chunks(inode):
            n = fs.nodes.get(inode)
            if n is not None:
                for cid in getattr(n, "chunks", ()):
                    if cid:
                        out.add(("chunk", cid))

        def child_of(parent, name):
            p = fs.nodes.get(parent)
            if p is not None and p.ftype == 2:
                c = p.children.get(name)
                if c is not None:
                    out.add(("node", c))
                    out.add(("trash", c))
                    out.add(("sustained", c))
                    node_quota(c)
                    node_chunks(c)

        if t == "mknode":
            out |= {("node", op["parent"]), ("node", op["inode"]),
                    ("edge", op["parent"], op["name"]),
                    ("quota", "user", op["uid"]),
                    ("quota", "group", op["gid"])}
        elif t in ("unlink", "rmdir"):
            out.add(("node", op["parent"]))
            out.add(("edge", op["parent"], op["name"]))
            child_of(op["parent"], op["name"])
        elif t == "rename":
            out |= {("node", op["parent_src"]), ("node", op["parent_dst"]),
                    ("edge", op["parent_src"], op["name_src"]),
                    ("edge", op["parent_dst"], op["name_dst"])}
            child_of(op["parent_src"], op["name_src"])
            child_of(op["parent_dst"], op["name_dst"])
        elif t == "link":
            out |= {("node", op["inode"]), ("node", op["parent"]),
                    ("edge", op["parent"], op["name"]),
                    ("sustained", op["inode"])}
        elif t in ("setattr", "setgoal", "seteattr", "set_chunk", "set_acl",
                   "set_rich_acl", "set_xattr"):
            out.add(("node", op["inode"]))
        elif t == "set_length":
            out.add(("node", op["inode"]))
            node_quota(op["inode"])
            node_chunks(op["inode"])
        elif t in ("create_chunk", "bump_chunk_version", "delete_chunk",
                   "goal_boost", "goal_demote"):
            out.add(("chunk", op["chunk_id"]))
        elif t in ("acquire", "release"):
            out |= {("open", op["inode"]), ("sustained", op["inode"]),
                    ("node", op["inode"]), ("demoted", op["inode"])}
            node_quota(op["inode"])
            node_chunks(op["inode"])
        elif t == "release_session_opens":
            for inode, refs in self.fs.open_refs.items():
                if op["sid"] in refs:
                    out |= {("open", inode), ("sustained", inode),
                            ("node", inode), ("demoted", inode)}
                    node_quota(inode)
                    node_chunks(inode)
        elif t in ("purge_trash", "undelete"):
            out |= {("node", op["inode"]), ("trash", op["inode"]),
                    ("sustained", op["inode"]), ("demoted", op["inode"])}
            node_quota(op["inode"])
            node_chunks(op["inode"])
            entry = fs.trash.get(op["inode"])
            if entry is not None:
                out.add(("node", entry[2]))  # restore target dir
            out.add(("node", 1))  # undelete falls back to the root
            n = fs.nodes.get(op["inode"])
            if n is not None:
                # the restored edge's name may have a collision suffix:
                # find it by child inode (post state; rare op)
                for p in n.parents:
                    out.add(("node", p))
                    pn = fs.nodes.get(p)
                    if pn is not None and pn.ftype == 2:
                        for name, child in pn.children.items():
                            if child == op["inode"]:
                                out.add(("edge", p, name))
        elif t in ("tape_copy", "tape_drop"):
            out.add(("tape", op["inode"]))
            if t == "tape_drop":
                out.add(("demoted", op["inode"]))
        elif t in ("tape_demote", "tape_recall_done"):
            out |= {("node", op["inode"]), ("demoted", op["inode"]),
                    ("tape", op["inode"])}
            node_chunks(op["inode"])
        elif t == "set_quota":
            out.add(("quota", op["kind"], op["owner_id"]))
        elif t == "snapshot":
            out.add(("node", op["dst_parent"]))
            out.add(("edge", op["dst_parent"], op["dst_name"]))
            for old_s, new in op["inode_map"].items():
                out |= {("node", int(old_s)), ("node", new)}
                node_chunks(int(old_s))
                node_chunks(new)
                node_quota(int(old_s))
                # cloned directories bring fresh edges (post-only keys)
                nn = fs.nodes.get(new)
                if nn is not None and nn.ftype == 2:
                    for name in nn.children:
                        out.add(("edge", new, name))
        elif t == "cow_chunk":
            out |= {("chunk", op["old_chunk_id"]),
                    ("chunk", op["new_chunk_id"]), ("node", op["inode"])}
        elif t == "append_chunks":
            out |= {("node", op["inode_dst"]), ("node", op["inode_src"])}
            node_chunks(op["inode_dst"])
            node_chunks(op["inode_src"])
            node_quota(op["inode_dst"])
        elif t == "repair_zero_chunk":
            out.add(("node", op["inode"]))
            node_chunks(op["inode"])
        elif t in ("lock_posix", "lock_flock"):
            kind = "posix" if t == "lock_posix" else "flock"
            out.add(("locks", kind, op["inode"]))
        elif t == "lock_release_session":
            sid = op["sid"]
            for kind, table in (("posix", self.locks.posix_files),
                                ("flock", self.locks.flock_files)):
                for inode, fl in table.items():
                    if any(r.owner.session_id == sid for r in fl.ranges):
                        out.add(("locks", kind, inode))
        elif t == "session_new":
            pass  # misc only
        elif t == "epoch_bump":
            pass  # misc only (the epoch rides the misc hash)
        return out

    def full_digest(self) -> int:
        """Recompute the digest from scratch (O(everything))."""
        d = self._entity_hash(("misc",))
        for inode, n in self.fs.nodes.items():
            d ^= self._entity_hash(("node", inode))
            if n.ftype == 2:
                for name in n.children:
                    d ^= self._entity_hash(("edge", inode, name))
        for inode in self.fs.trash:
            d ^= self._entity_hash(("trash", inode))
        for inode in self.fs.open_refs:
            d ^= self._entity_hash(("open", inode))
        for inode in self.fs.sustained:
            d ^= self._entity_hash(("sustained", inode))
        for cid in self.registry.chunks:
            d ^= self._entity_hash(("chunk", cid))
        for kind, oid in self.quotas.entries:
            d ^= self._entity_hash(("quota", kind, oid))
        for lkind, table in (("posix", self.locks.posix_files),
                             ("flock", self.locks.flock_files)):
            for inode in table:
                d ^= self._entity_hash(("locks", lkind, inode))
        for inode in self.tape_copies:
            d ^= self._entity_hash(("tape", inode))
        for inode in self.demoted:
            d ^= self._entity_hash(("demoted", inode))
        return d

    def checksum(self, cache_key: int | None = None) -> str:
        """Divergence-detection digest over the persistent metadata.

        Maintained INCREMENTALLY per applied op (the reference's
        filesystem_checksum.cc); a probe costs O(1) no matter the
        namespace size. ``cache_key`` is accepted for interface
        compatibility and ignored."""
        return f"{self._digest:032x}"

    def reset_digest(self) -> None:
        """Re-anchor the incremental digest to current state (after a
        bulk load or verified drift)."""
        self._digest = self.full_digest()
