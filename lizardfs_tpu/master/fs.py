"""In-memory file system tree with deterministic, replayable mutations.

The analog of the reference's FSNode tree + filesystem_operations
(reference: src/master/filesystem_node_types.h:88-320,
filesystem_operations.cc). The key architectural property carried over:
**every mutation is expressed as a deterministic operation record** —
all non-deterministic inputs (allocated inode numbers, timestamps) are
chosen once by the live master, serialized into the changelog, and the
same ``apply_*`` code path replays them on shadows/restore
(src/master/restore.h:28 pattern). The changelog is therefore exact by
construction.

Operation records are JSON objects with an ``op`` field; see OPS at the
bottom. File content geometry: a file's data is a list of chunk ids
indexed by chunk position (64 MiB each).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from lizardfs_tpu.constants import EATTR_LIFECYCLE, MFSCHUNKSIZE
from lizardfs_tpu.proto import status as st

ROOT_INODE = 1

TYPE_FILE = 1
TYPE_DIR = 2
TYPE_SYMLINK = 3


class FsError(Exception):
    def __init__(self, code: int, msg: str = ""):
        self.code = code
        super().__init__(f"{st.name(code)}{(': ' + msg) if msg else ''}")


@dataclass(slots=True)
class Node:
    """One inode. ``slots=True`` drops the per-instance __dict__: at
    1M synthetic files the master costs ~620 bytes/inode vs ~740
    without slots (see doc/migration.md "master RAM"), and attribute
    typos fail loudly instead of growing the namespace."""

    inode: int
    ftype: int
    mode: int = 0o644
    uid: int = 0
    gid: int = 0
    atime: int = 0
    mtime: int = 0
    ctime: int = 0
    goal: int = 1
    trash_time: int = 86400
    # extra-attribute flags (constants.py EATTR_*): noowner / nocache /
    # noentrycache — replicated via the "seteattr" changelog op
    eattr: int = 0
    # files
    length: int = 0
    chunks: list[int] = field(default_factory=list)  # chunk ids by index, 0 = hole
    # directories
    children: dict[str, int] = field(default_factory=dict)
    # symlinks
    symlink_target: str = ""
    # link count (parents holding an edge to this node)
    nlink: int = 0
    # parent directory inodes holding edges to this node (one entry per
    # edge; duplicates allowed for hardlinks in one dir). Directories
    # always have exactly one.
    parents: list[int] = field(default_factory=list)
    # extended attributes
    xattrs: dict[str, bytes] = field(default_factory=dict)
    # POSIX ACLs, stored as plain dicts (master/acl.py evaluates)
    acl: dict | None = None
    default_acl: dict | None = None
    # RichACL (NFSv4-style, master/richacl.py evaluates); when set it
    # takes precedence over the POSIX ACL for permission checks
    rich_acl: dict | None = None
    # directories: recursive subtree statistics (fsnodes statistics
    # analog) — counts include the directory itself
    stat_inodes: int = 1
    stat_bytes: int = 0

    def to_dict(self) -> dict:
        import base64

        d = {
            "inode": self.inode,
            "ftype": self.ftype,
            "mode": self.mode,
            "uid": self.uid,
            "gid": self.gid,
            "atime": self.atime,
            "mtime": self.mtime,
            "ctime": self.ctime,
            "goal": self.goal,
            "trash_time": self.trash_time,
            "nlink": self.nlink,
            "parents": self.parents,
        }
        if self.eattr:
            d["eattr"] = self.eattr
        if self.xattrs:
            d["xattrs"] = {
                k: base64.b64encode(v).decode() for k, v in self.xattrs.items()
            }
        if self.acl is not None:
            d["acl"] = self.acl
        if self.default_acl is not None:
            d["default_acl"] = self.default_acl
        if self.rich_acl is not None:
            d["rich_acl"] = self.rich_acl
        if self.ftype == TYPE_FILE:
            d["length"] = self.length
            d["chunks"] = self.chunks
        elif self.ftype == TYPE_DIR:
            d["children"] = self.children
            d["stat_inodes"] = self.stat_inodes
            d["stat_bytes"] = self.stat_bytes
        elif self.ftype == TYPE_SYMLINK:
            d["symlink_target"] = self.symlink_target
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        import base64

        n = cls(inode=d["inode"], ftype=d["ftype"])
        for k, v in d.items():
            if k == "children":
                n.children = {str(name): int(i) for name, i in v.items()}
            elif k == "xattrs":
                n.xattrs = {
                    key: base64.b64decode(val) for key, val in v.items()
                }
            elif hasattr(n, k):
                setattr(n, k, v)
        return n


class FsTree:
    """The namespace + attributes. No I/O here; pure data structure."""

    def __init__(self):
        self.nodes: dict[int, Node] = {}
        self.next_inode = ROOT_INODE + 1
        self.trash: dict[int, tuple[str, int]] = {}  # inode -> (name, del_ts)
        # open-file registry + sustained namespace (reference: "reserved"
        # files, filesystem_node_types.h trash & reserved namespaces):
        # inode -> {session_id: open count}; a file whose last name goes
        # away while open moves to `sustained` instead of dying, and is
        # freed at the last release. Replicated via acquire/release ops.
        self.open_refs: dict[int, dict[int, int]] = {}
        self.sustained: set[int] = set()
        # directories carrying the EATTR_LIFECYCLE marker bit (S3
        # lifecycle rules): maintained by apply_seteattr / apply_rmdir
        # and rebuilt on load, so the master's lifecycle scanner never
        # walks the whole namespace just to find its roots
        self.lifecycle_dirs: set[int] = set()
        root = Node(inode=ROOT_INODE, ftype=TYPE_DIR, mode=0o755, nlink=1)
        self.nodes[ROOT_INODE] = root

    # --- helpers -------------------------------------------------------------

    def node(self, inode: int) -> Node:
        n = self.nodes.get(inode)
        if n is None:
            raise FsError(st.ENOENT, f"inode {inode}")
        return n

    def dir_node(self, inode: int) -> Node:
        n = self.node(inode)
        if n.ftype != TYPE_DIR:
            raise FsError(st.ENOTDIR, f"inode {inode}")
        return n

    def file_node(self, inode: int) -> Node:
        n = self.node(inode)
        if n.ftype != TYPE_FILE:
            raise FsError(st.EISDIR if n.ftype == TYPE_DIR else st.EINVAL)
        return n

    def alloc_inode(self) -> int:
        inode = self.next_inode
        self.next_inode += 1
        return inode

    def _add_stats(self, dir_inode: int, d_inodes: int, d_bytes: int) -> None:
        """Propagate subtree statistic deltas up the directory chain
        (fsnodes_add_stats analog). Each edge counts once."""
        seen = 0
        cur = dir_inode
        while True:
            n = self.nodes.get(cur)
            if n is None or n.ftype != TYPE_DIR:
                return
            n.stat_inodes += d_inodes
            n.stat_bytes += d_bytes
            if cur == ROOT_INODE or not n.parents:
                return
            cur = n.parents[0]
            seen += 1
            if seen > 4096:  # corrupt parent chain guard
                return

    def _node_weight(self, n: Node) -> tuple[int, int]:
        """(inodes, bytes) a single edge to this node contributes."""
        if n.ftype == TYPE_DIR:
            return n.stat_inodes, n.stat_bytes
        if n.ftype == TYPE_FILE:
            return 1, n.length
        return 1, 0

    def path_of(self, inode: int) -> str:
        """Best-effort absolute path (first hardlink); operator-facing
        (tape archive naming, diagnostics) — not a lookup primitive."""
        parts: list[str] = []
        cur = inode
        for _ in range(4096):  # corrupt parent chain guard
            if cur == ROOT_INODE:
                return "/" + "/".join(reversed(parts))
            n = self.nodes.get(cur)
            if n is None or not n.parents:
                break
            parent = self.nodes.get(n.parents[0])
            if parent is None or parent.ftype != TYPE_DIR:
                break
            name = next(
                (nm for nm, ch in parent.children.items() if ch == cur), None
            )
            if name is None:
                break
            parts.append(name)
            cur = parent.inode
        return f"/.inode/{inode}"

    def lookup(self, parent: int, name: str) -> Node:
        p = self.dir_node(parent)
        inode = p.children.get(name)
        if inode is None:
            raise FsError(st.ENOENT, name)
        return self.node(inode)

    # --- deterministic mutations (replayed verbatim from the changelog) ------

    def apply_mknode(
        self,
        parent: int,
        name: str,
        inode: int,
        ftype: int,
        mode: int,
        uid: int,
        gid: int,
        ts: int,
        goal: int,
        trash_time: int,
        symlink_target: str = "",
    ) -> Node:
        p = self.dir_node(parent)
        if name in p.children:
            raise FsError(st.EEXIST, name)
        if not name or "/" in name or name in (".", ".."):
            raise FsError(st.EINVAL, repr(name))
        if len(name) > 255:
            raise FsError(st.NAME_TOO_LONG, name)
        n = Node(
            inode=inode,
            ftype=ftype,
            mode=mode,
            uid=uid,
            gid=gid,
            atime=ts,
            mtime=ts,
            ctime=ts,
            goal=goal,
            trash_time=trash_time,
            symlink_target=symlink_target,
            nlink=1,
            parents=[parent],
        )
        # POSIX default-ACL inheritance: a directory's default ACL
        # becomes the access ACL of new children (and propagates as the
        # default for child directories)
        if p.default_acl is not None:
            n.acl = dict(p.default_acl)
            if ftype == TYPE_DIR:
                n.default_acl = dict(p.default_acl)
        if p.rich_acl is not None:
            from lizardfs_tpu.master import richacl as richacl_mod

            inherited = richacl_mod.RichAcl.from_dict(p.rich_acl).inherited(
                ftype == TYPE_DIR
            )
            if inherited is not None:
                n.rich_acl = inherited.to_dict()
        self.nodes[inode] = n
        p.children[name] = inode
        p.mtime = p.ctime = ts
        self.next_inode = max(self.next_inode, inode + 1)
        self._add_stats(parent, 1, 0)
        return n

    def apply_unlink(self, parent: int, name: str, ts: int, to_trash: bool) -> Node:
        p = self.dir_node(parent)
        inode = p.children.get(name)
        if inode is None:
            raise FsError(st.ENOENT, name)
        n = self.node(inode)
        if n.ftype == TYPE_DIR:
            raise FsError(st.EPERM, "unlink of directory")
        del p.children[name]
        p.mtime = p.ctime = ts
        wi, wb = self._node_weight(n)
        self._add_stats(parent, -wi, -wb)
        if parent in n.parents:
            n.parents.remove(parent)
        n.nlink -= 1
        n.ctime = ts
        if n.nlink <= 0:
            if to_trash and n.ftype == TYPE_FILE and n.trash_time > 0:
                # keep the last parent+name so undelete can restore
                self.trash[inode] = (name, ts + n.trash_time, parent)
            elif self.open_refs.get(inode):
                # unlink-while-open (POSIX): the data outlives the last
                # name until the last close — the reference's "reserved"
                self.sustained.add(inode)
            else:
                del self.nodes[inode]
        return n

    def apply_rmdir(self, parent: int, name: str, ts: int) -> None:
        p = self.dir_node(parent)
        inode = p.children.get(name)
        if inode is None:
            raise FsError(st.ENOENT, name)
        n = self.node(inode)
        if n.ftype != TYPE_DIR:
            raise FsError(st.ENOTDIR, name)
        if n.children:
            raise FsError(st.ENOTEMPTY, name)
        del p.children[name]
        del self.nodes[inode]
        self.lifecycle_dirs.discard(inode)
        p.mtime = p.ctime = ts
        self._add_stats(parent, -1, 0)

    def apply_rename(
        self, parent_src: int, name_src: str, parent_dst: int, name_dst: str, ts: int
    ) -> None:
        ps = self.dir_node(parent_src)
        pd = self.dir_node(parent_dst)
        inode = ps.children.get(name_src)
        if inode is None:
            raise FsError(st.ENOENT, name_src)
        moving = self.node(inode)
        # validate EVERYTHING before mutating: a raise after a partial
        # mutation would diverge the live tree from the changelog
        if moving.ftype == TYPE_DIR:
            # cycle check: cannot move a directory under itself
            cur = parent_dst
            while cur != ROOT_INODE:
                if cur == inode:
                    raise FsError(st.EINVAL, "rename cycle")
                cur = self._parent_of_dir(cur)
        existing = pd.children.get(name_dst)
        if existing is not None:
            ex = self.node(existing)
            if ex.ftype == TYPE_DIR:
                if ex.children:
                    raise FsError(st.ENOTEMPTY, name_dst)
                del self.nodes[existing]
                del pd.children[name_dst]
                self._add_stats(parent_dst, -1, 0)
            else:
                self.apply_unlink(parent_dst, name_dst, ts, to_trash=True)
        wi, wb = self._node_weight(moving)
        del ps.children[name_src]
        self._add_stats(parent_src, -wi, -wb)
        if parent_src in moving.parents:
            moving.parents.remove(parent_src)
        pd.children[name_dst] = inode
        moving.parents.append(parent_dst)
        self._add_stats(parent_dst, wi, wb)
        ps.mtime = ps.ctime = ts
        pd.mtime = pd.ctime = ts
        moving.ctime = ts

    def _parent_of_dir(self, inode: int) -> int:
        n = self.nodes.get(inode)
        if n is not None and n.parents:
            return n.parents[0]
        return ROOT_INODE

    def apply_link(self, inode: int, parent: int, name: str, ts: int) -> Node:
        n = self.file_node(inode)
        p = self.dir_node(parent)
        if name in p.children:
            raise FsError(st.EEXIST, name)
        p.children[name] = inode
        n.nlink += 1
        n.parents.append(parent)
        n.ctime = ts
        p.mtime = p.ctime = ts
        self._add_stats(parent, 1, n.length)
        # re-linking a sustained (nameless-but-open) inode gives it a
        # name again: it is a normal file now — the last release must
        # NOT free it out from under the new directory entry
        self.sustained.discard(inode)
        return n

    def apply_setattr(
        self, inode: int, set_mask: int, mode: int, uid: int, gid: int,
        atime: int, mtime: int, ts: int, trash_time: int = 0,
    ) -> Node:
        n = self.node(inode)
        if set_mask & 1:
            n.mode = mode
        if set_mask & 2:
            n.uid = uid
        if set_mask & 4:
            n.gid = gid
        if set_mask & 8:
            n.atime = atime
        if set_mask & 16:
            n.mtime = mtime
        if set_mask & 32:
            n.trash_time = trash_time
        n.ctime = ts
        return n

    def apply_setgoal(self, inode: int, goal: int, ts: int) -> Node:
        n = self.node(inode)
        n.goal = goal
        n.ctime = ts
        return n

    def apply_seteattr(self, inode: int, eattr: int, ts: int) -> Node:
        n = self.node(inode)
        n.eattr = eattr & 0xFF
        n.ctime = ts
        if n.ftype == TYPE_DIR:
            if n.eattr & EATTR_LIFECYCLE:
                self.lifecycle_dirs.add(inode)
            else:
                self.lifecycle_dirs.discard(inode)
        return n

    def apply_set_chunk(self, inode: int, chunk_index: int, chunk_id: int) -> Node:
        """Attach a chunk id at a file position (write path)."""
        n = self.file_node(inode)
        while len(n.chunks) <= chunk_index:
            n.chunks.append(0)
        n.chunks[chunk_index] = chunk_id
        return n

    def apply_set_length(self, inode: int, length: int, ts: int,
                         drop_chunks: bool = True) -> list[int]:
        """Set file length; returns chunk ids dropped past the new end
        (the caller releases them in the chunk registry).

        ``drop_chunks=False`` is the write-path grow (WriteChunkEnd):
        concurrent chunk writes attach higher chunk indices before
        earlier chunks finish, so a length update for chunk N must never
        discard an already-attached chunk N+1 — only truncate drops."""
        n = self.file_node(inode)
        delta = length - n.length
        for parent in n.parents:
            self._add_stats(parent, 0, delta)
        n.length = length
        n.mtime = n.ctime = ts
        if not drop_chunks:
            return []
        nchunks = (length + MFSCHUNKSIZE - 1) // MFSCHUNKSIZE if length else 0
        removed = [c for c in n.chunks[nchunks:] if c]
        del n.chunks[nchunks:]
        return removed

    def apply_purge_trash(self, inode: int) -> None:
        self.trash.pop(inode, None)
        if self.open_refs.get(inode):
            # trash expiry with live openers: sustain instead of
            # breaking their handles; freed at the last release
            self.sustained.add(inode)
        else:
            self.nodes.pop(inode, None)

    def apply_acquire(self, inode: int, sid: int) -> None:
        self.node(inode)  # must exist
        refs = self.open_refs.setdefault(inode, {})
        refs[sid] = refs.get(sid, 0) + 1

    def apply_release(self, inode: int, sid: int) -> bool:
        """Drop one open ref. True when the LAST ref of a sustained file
        went away — the caller frees chunks/quota and the node."""
        refs = self.open_refs.get(inode)
        if not refs or sid not in refs:
            return False
        refs[sid] -= 1
        if refs[sid] <= 0:
            del refs[sid]
        if refs:
            return False
        del self.open_refs[inode]
        if inode in self.sustained:
            self.sustained.discard(inode)
            return True
        return False

    def apply_undelete(self, inode: int, ts: int) -> Node:
        """Restore a trashed file to its original directory (or the root
        if that directory is gone), resolving name collisions with a
        suffix (trash-restore analog)."""
        entry = self.trash.get(inode)
        if entry is None:
            raise FsError(st.ENOENT, f"inode {inode} not in trash")
        name, _, parent = entry
        p = self.nodes.get(parent)
        if p is None or p.ftype != TYPE_DIR:
            parent = ROOT_INODE
            p = self.dir_node(parent)
        final = name
        i = 1
        while final in p.children:
            final = f"{name}.restored.{i}"
            i += 1
        n = self.node(inode)
        p.children[final] = inode
        n.nlink = 1
        n.parents = [parent]
        n.ctime = ts
        p.mtime = p.ctime = ts
        del self.trash[inode]
        self._add_stats(parent, 1, n.length)
        return n

    def apply_set_acl(self, inode: int, access: dict | None,
                      default: dict | None, ts: int) -> None:
        n = self.node(inode)
        n.acl = dict(access) if access else None
        if n.ftype == TYPE_DIR:
            n.default_acl = dict(default) if default else None
        n.ctime = ts

    def apply_set_rich_acl(self, inode: int, acl: dict | None,
                           ts: int) -> None:
        n = self.node(inode)
        n.rich_acl = dict(acl) if acl else None
        n.ctime = ts

    def apply_set_xattr(self, inode: int, name: str, value_b64: str, ts: int) -> None:
        import base64

        n = self.node(inode)
        if value_b64 == "":
            if name not in n.xattrs:
                raise FsError(st.ENOATTR, name)
            del n.xattrs[name]
        else:
            if len(name) > 255:
                raise FsError(st.NAME_TOO_LONG, name)
            n.xattrs[name] = base64.b64decode(value_b64)
        n.ctime = ts

    def apply_append_chunks(
        self, inode_dst: int, inode_src: int, ts: int
    ) -> list[int]:
        """O(1)-per-chunk concatenation (append_file.cc analog): pad
        the destination to a chunk boundary, then share the source's
        chunk ids onto its tail. Returns the shared chunk ids (the
        caller bumps refcounts — COW on a later write keeps the files
        independent)."""
        dst = self.file_node(inode_dst)
        src = self.file_node(inode_src)
        if inode_dst == inode_src:
            raise FsError(st.EINVAL, "append onto itself")
        padded = (
            (dst.length + MFSCHUNKSIZE - 1) // MFSCHUNKSIZE * MFSCHUNKSIZE
        )
        pad_chunks = padded // MFSCHUNKSIZE
        if len(dst.chunks) > pad_chunks:
            # a chunk attached past the length boundary = a write in
            # flight (the master handler refuses CHUNK_BUSY before
            # committing, so apply/replay must never see this)
            raise FsError(st.CHUNK_BUSY, "append under in-flight write")
        while len(dst.chunks) < pad_chunks:
            dst.chunks.append(0)  # holes read as zeros
        shared = list(src.chunks)
        # a source shorter than its chunk count never happens, but a
        # trailing hole does: share slots verbatim (0 stays a hole)
        dst.chunks.extend(shared)
        new_length = padded + src.length
        delta = new_length - dst.length
        dst.length = new_length
        dst.mtime = dst.ctime = ts
        for parent in dst.parents:
            self._add_stats(parent, 0, delta)
        return [c for c in shared if c]

    def apply_demote(self, inode: int, ts: int) -> list[int]:
        """Tape-tier demote: drop the file's chunk list (the caller
        releases the ids in the registry) while KEEPING length and
        mtime — the content still exists on tape, stamped by exactly
        those fields, and stat must keep telling the truth about the
        object's size. Only ctime moves (a demote is a metadata
        event)."""
        n = self.file_node(inode)
        removed = [c for c in n.chunks if c]
        n.chunks = []
        n.ctime = ts
        return removed

    def apply_repair_zero_chunk(
        self, inode: int, chunk_index: int, ts: int
    ) -> int:
        """filerepair's last resort: zero-fill an unrecoverable chunk
        by turning its slot into a hole. Returns the released chunk id
        (0 when the slot was already a hole)."""
        n = self.file_node(inode)
        if chunk_index >= len(n.chunks):
            return 0
        cid = n.chunks[chunk_index]
        n.chunks[chunk_index] = 0
        n.mtime = n.ctime = ts
        return cid

    def apply_snapshot(
        self, src_inode: int, dst_parent: int, dst_name: str,
        inode_map: dict[str, int], ts: int,
    ) -> list[tuple[int, int]]:
        """Clone a subtree; files share chunk ids (COW happens at write
        time via chunk refcounts). ``inode_map`` assigns the new inode
        for every cloned source inode (chosen by the live master so
        replay is deterministic). Returns [(chunk_id, +1 refcount)]
        deltas for the registry."""
        src = self.node(src_inode)
        p = self.dir_node(dst_parent)
        if dst_name in p.children:
            raise FsError(st.EEXIST, dst_name)
        shared: list[tuple[int, int]] = []

        def clone(node: Node, parent_inode: int, name: str) -> None:
            new_inode = inode_map[str(node.inode)]
            new = Node(
                inode=new_inode, ftype=node.ftype, mode=node.mode,
                uid=node.uid, gid=node.gid, atime=ts, mtime=node.mtime,
                ctime=ts, goal=node.goal, trash_time=node.trash_time,
                length=node.length, chunks=list(node.chunks),
                symlink_target=node.symlink_target, nlink=1,
                parents=[parent_inode], xattrs=dict(node.xattrs),
            )
            # ACLs travel with the snapshot (dropping them while keeping
            # a setrichacl-lifted mode would widen access on the clone)
            new.acl = dict(node.acl) if node.acl else None
            new.default_acl = (
                dict(node.default_acl) if node.default_acl else None
            )
            new.rich_acl = dict(node.rich_acl) if node.rich_acl else None
            self.nodes[new_inode] = new
            self.nodes[parent_inode].children[name] = new_inode
            self.next_inode = max(self.next_inode, new_inode + 1)
            for cid in new.chunks:
                if cid:
                    shared.append((cid, 1))
            if node.ftype == TYPE_DIR:
                for child_name, child_inode in sorted(node.children.items()):
                    clone(self.node(child_inode), new_inode, child_name)
                new.stat_inodes = node.stat_inodes
                new.stat_bytes = node.stat_bytes

        clone(src, dst_parent, dst_name)
        wi, wb = self._node_weight(self.node(inode_map[str(src_inode)]))
        self._add_stats(dst_parent, wi, wb)
        p.mtime = p.ctime = ts
        return shared

    # --- persistence -----------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "next_inode": self.next_inode,
            "nodes": [n.to_dict() for n in self.nodes.values()],
            "trash": {str(i): list(v) for i, v in self.trash.items()},
            "open": {
                str(i): {str(s): c for s, c in refs.items()}
                for i, refs in self.open_refs.items() if refs
            },
            "sustained": sorted(self.sustained),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FsTree":
        fs = cls.__new__(cls)
        fs.nodes = {}
        fs.next_inode = d["next_inode"]
        fs.trash = {
            int(i): (v[0], int(v[1]), int(v[2]) if len(v) > 2 else ROOT_INODE)
            for i, v in d.get("trash", {}).items()
        }
        fs.open_refs = {
            int(i): {int(s): int(c) for s, c in refs.items()}
            for i, refs in d.get("open", {}).items()
        }
        fs.sustained = set(d.get("sustained", ()))
        fs.lifecycle_dirs = set()
        for nd in d["nodes"]:
            node = Node.from_dict(nd)
            fs.nodes[node.inode] = node
            if node.ftype == TYPE_DIR and node.eattr & EATTR_LIFECYCLE:
                fs.lifecycle_dirs.add(node.inode)
        if ROOT_INODE not in fs.nodes:
            raise ValueError("image missing root inode")
        return fs

    def checksum_data(self) -> str:
        """Stable digest of the whole tree — master/shadow divergence
        detection (filesystem_checksum analog)."""
        import hashlib

        blob = json.dumps(self.to_dict(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()
