"""In-memory file system tree with deterministic, replayable mutations.

The analog of the reference's FSNode tree + filesystem_operations
(reference: src/master/filesystem_node_types.h:88-320,
filesystem_operations.cc). The key architectural property carried over:
**every mutation is expressed as a deterministic operation record** —
all non-deterministic inputs (allocated inode numbers, timestamps) are
chosen once by the live master, serialized into the changelog, and the
same ``apply_*`` code path replays them on shadows/restore
(src/master/restore.h:28 pattern). The changelog is therefore exact by
construction.

Operation records are JSON objects with an ``op`` field; see OPS at the
bottom. File content geometry: a file's data is a list of chunk ids
indexed by chunk position (64 MiB each).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from lizardfs_tpu.constants import MFSCHUNKSIZE
from lizardfs_tpu.proto import status as st

ROOT_INODE = 1

TYPE_FILE = 1
TYPE_DIR = 2
TYPE_SYMLINK = 3


class FsError(Exception):
    def __init__(self, code: int, msg: str = ""):
        self.code = code
        super().__init__(f"{st.name(code)}{(': ' + msg) if msg else ''}")


@dataclass
class Node:
    inode: int
    ftype: int
    mode: int = 0o644
    uid: int = 0
    gid: int = 0
    atime: int = 0
    mtime: int = 0
    ctime: int = 0
    goal: int = 1
    trash_time: int = 86400
    # files
    length: int = 0
    chunks: list[int] = field(default_factory=list)  # chunk ids by index, 0 = hole
    # directories
    children: dict[str, int] = field(default_factory=dict)
    # symlinks
    symlink_target: str = ""
    # link count (parents holding an edge to this node)
    nlink: int = 0

    def to_dict(self) -> dict:
        d = {
            "inode": self.inode,
            "ftype": self.ftype,
            "mode": self.mode,
            "uid": self.uid,
            "gid": self.gid,
            "atime": self.atime,
            "mtime": self.mtime,
            "ctime": self.ctime,
            "goal": self.goal,
            "trash_time": self.trash_time,
            "nlink": self.nlink,
        }
        if self.ftype == TYPE_FILE:
            d["length"] = self.length
            d["chunks"] = self.chunks
        elif self.ftype == TYPE_DIR:
            d["children"] = self.children
        elif self.ftype == TYPE_SYMLINK:
            d["symlink_target"] = self.symlink_target
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        n = cls(inode=d["inode"], ftype=d["ftype"])
        for k, v in d.items():
            if k == "children":
                n.children = {str(name): int(i) for name, i in v.items()}
            elif hasattr(n, k):
                setattr(n, k, v)
        return n


class FsTree:
    """The namespace + attributes. No I/O here; pure data structure."""

    def __init__(self):
        self.nodes: dict[int, Node] = {}
        self.next_inode = ROOT_INODE + 1
        self.trash: dict[int, tuple[str, int]] = {}  # inode -> (name, del_ts)
        root = Node(inode=ROOT_INODE, ftype=TYPE_DIR, mode=0o755, nlink=1)
        self.nodes[ROOT_INODE] = root

    # --- helpers -------------------------------------------------------------

    def node(self, inode: int) -> Node:
        n = self.nodes.get(inode)
        if n is None:
            raise FsError(st.ENOENT, f"inode {inode}")
        return n

    def dir_node(self, inode: int) -> Node:
        n = self.node(inode)
        if n.ftype != TYPE_DIR:
            raise FsError(st.ENOTDIR, f"inode {inode}")
        return n

    def file_node(self, inode: int) -> Node:
        n = self.node(inode)
        if n.ftype != TYPE_FILE:
            raise FsError(st.EISDIR if n.ftype == TYPE_DIR else st.EINVAL)
        return n

    def alloc_inode(self) -> int:
        inode = self.next_inode
        self.next_inode += 1
        return inode

    def lookup(self, parent: int, name: str) -> Node:
        p = self.dir_node(parent)
        inode = p.children.get(name)
        if inode is None:
            raise FsError(st.ENOENT, name)
        return self.node(inode)

    # --- deterministic mutations (replayed verbatim from the changelog) ------

    def apply_mknode(
        self,
        parent: int,
        name: str,
        inode: int,
        ftype: int,
        mode: int,
        uid: int,
        gid: int,
        ts: int,
        goal: int,
        trash_time: int,
        symlink_target: str = "",
    ) -> Node:
        p = self.dir_node(parent)
        if name in p.children:
            raise FsError(st.EEXIST, name)
        if not name or "/" in name or name in (".", ".."):
            raise FsError(st.EINVAL, repr(name))
        if len(name) > 255:
            raise FsError(st.NAME_TOO_LONG, name)
        n = Node(
            inode=inode,
            ftype=ftype,
            mode=mode,
            uid=uid,
            gid=gid,
            atime=ts,
            mtime=ts,
            ctime=ts,
            goal=goal,
            trash_time=trash_time,
            symlink_target=symlink_target,
            nlink=1,
        )
        self.nodes[inode] = n
        p.children[name] = inode
        p.mtime = p.ctime = ts
        self.next_inode = max(self.next_inode, inode + 1)
        return n

    def apply_unlink(self, parent: int, name: str, ts: int, to_trash: bool) -> Node:
        p = self.dir_node(parent)
        inode = p.children.get(name)
        if inode is None:
            raise FsError(st.ENOENT, name)
        n = self.node(inode)
        if n.ftype == TYPE_DIR:
            raise FsError(st.EPERM, "unlink of directory")
        del p.children[name]
        p.mtime = p.ctime = ts
        n.nlink -= 1
        n.ctime = ts
        if n.nlink <= 0:
            if to_trash and n.ftype == TYPE_FILE and n.trash_time > 0:
                self.trash[inode] = (name, ts + n.trash_time)
            else:
                del self.nodes[inode]
        return n

    def apply_rmdir(self, parent: int, name: str, ts: int) -> None:
        p = self.dir_node(parent)
        inode = p.children.get(name)
        if inode is None:
            raise FsError(st.ENOENT, name)
        n = self.node(inode)
        if n.ftype != TYPE_DIR:
            raise FsError(st.ENOTDIR, name)
        if n.children:
            raise FsError(st.ENOTEMPTY, name)
        del p.children[name]
        del self.nodes[inode]
        p.mtime = p.ctime = ts

    def apply_rename(
        self, parent_src: int, name_src: str, parent_dst: int, name_dst: str, ts: int
    ) -> None:
        ps = self.dir_node(parent_src)
        pd = self.dir_node(parent_dst)
        inode = ps.children.get(name_src)
        if inode is None:
            raise FsError(st.ENOENT, name_src)
        moving = self.node(inode)
        # validate EVERYTHING before mutating: a raise after a partial
        # mutation would diverge the live tree from the changelog
        if moving.ftype == TYPE_DIR:
            # cycle check: cannot move a directory under itself
            cur = parent_dst
            while cur != ROOT_INODE:
                if cur == inode:
                    raise FsError(st.EINVAL, "rename cycle")
                cur = self._parent_of_dir(cur)
        existing = pd.children.get(name_dst)
        if existing is not None:
            ex = self.node(existing)
            if ex.ftype == TYPE_DIR:
                if ex.children:
                    raise FsError(st.ENOTEMPTY, name_dst)
                del self.nodes[existing]
                del pd.children[name_dst]
            else:
                self.apply_unlink(parent_dst, name_dst, ts, to_trash=True)
        del ps.children[name_src]
        pd.children[name_dst] = inode
        ps.mtime = ps.ctime = ts
        pd.mtime = pd.ctime = ts
        moving.ctime = ts

    def _parent_of_dir(self, inode: int) -> int:
        # directories have exactly one parent; linear scan is fine for the
        # rare rename-cycle check (the reference stores parent pointers)
        for i, n in self.nodes.items():
            if n.ftype == TYPE_DIR and inode in n.children.values():
                return i
        return ROOT_INODE

    def apply_link(self, inode: int, parent: int, name: str, ts: int) -> Node:
        n = self.file_node(inode)
        p = self.dir_node(parent)
        if name in p.children:
            raise FsError(st.EEXIST, name)
        p.children[name] = inode
        n.nlink += 1
        n.ctime = ts
        p.mtime = p.ctime = ts
        return n

    def apply_setattr(
        self, inode: int, set_mask: int, mode: int, uid: int, gid: int,
        atime: int, mtime: int, ts: int, trash_time: int = 0,
    ) -> Node:
        n = self.node(inode)
        if set_mask & 1:
            n.mode = mode
        if set_mask & 2:
            n.uid = uid
        if set_mask & 4:
            n.gid = gid
        if set_mask & 8:
            n.atime = atime
        if set_mask & 16:
            n.mtime = mtime
        if set_mask & 32:
            n.trash_time = trash_time
        n.ctime = ts
        return n

    def apply_setgoal(self, inode: int, goal: int, ts: int) -> Node:
        n = self.node(inode)
        n.goal = goal
        n.ctime = ts
        return n

    def apply_set_chunk(self, inode: int, chunk_index: int, chunk_id: int) -> Node:
        """Attach a chunk id at a file position (write path)."""
        n = self.file_node(inode)
        while len(n.chunks) <= chunk_index:
            n.chunks.append(0)
        n.chunks[chunk_index] = chunk_id
        return n

    def apply_set_length(self, inode: int, length: int, ts: int) -> list[int]:
        """Set file length; returns chunk ids dropped past the new end
        (the caller releases them in the chunk registry)."""
        n = self.file_node(inode)
        n.length = length
        n.mtime = n.ctime = ts
        nchunks = (length + MFSCHUNKSIZE - 1) // MFSCHUNKSIZE if length else 0
        removed = [c for c in n.chunks[nchunks:] if c]
        del n.chunks[nchunks:]
        return removed

    def apply_purge_trash(self, inode: int) -> None:
        self.trash.pop(inode, None)
        self.nodes.pop(inode, None)

    # --- persistence -----------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "next_inode": self.next_inode,
            "nodes": [n.to_dict() for n in self.nodes.values()],
            "trash": {str(i): list(v) for i, v in self.trash.items()},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FsTree":
        fs = cls.__new__(cls)
        fs.nodes = {}
        fs.next_inode = d["next_inode"]
        fs.trash = {int(i): (v[0], int(v[1])) for i, v in d.get("trash", {}).items()}
        for nd in d["nodes"]:
            node = Node.from_dict(nd)
            fs.nodes[node.inode] = node
        if ROOT_INODE not in fs.nodes:
            raise ValueError("image missing root inode")
        return fs

    def checksum_data(self) -> str:
        """Stable digest of the whole tree — master/shadow divergence
        detection (filesystem_checksum analog)."""
        import hashlib

        blob = json.dumps(self.to_dict(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()
