"""Append-only changelog + metadata image persistence.

The durability backbone, mirroring the reference's design (reference:
src/master/changelog.h:34-54 append/rotate, filesystem_store.cc
metadata image, restore.cc replay):

  * every metadata mutation appends one line ``<version>: <json-op>`` to
    ``changelog.0.log``; the version counter is the global metadata
    version,
  * a metadata image (``metadata.liz``) snapshots the whole state at
    some version; on startup the image is loaded and newer changelog
    lines are replayed on top (crash recovery, filesystem_store.h:38),
  * ``rotate()`` shifts changelog.N.log -> changelog.N+1.log after each
    image dump,
  * shadows/metaloggers receive the same lines over the wire and apply
    or archive them.

The image is a versioned JSON document — structured, explicit, and
diff-friendly; sections mirror the reference's tagged sections (NODE/
EDGE/CHUNKS/...).
"""

from __future__ import annotations

import json
import os

IMAGE_FORMAT = "lizardfs-tpu-metadata-1"
MAX_KEPT_LOGS = 2


class Changelog:
    def __init__(self, data_dir: str):
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.version = 0  # version of the last applied mutation
        self._file = None

    @property
    def path(self) -> str:
        return os.path.join(self.data_dir, "changelog.0.log")

    def open(self) -> None:
        self._file = open(self.path, "a", encoding="utf-8")

    def append(self, op: dict) -> int:
        """Assign the next version to ``op``, persist, return version."""
        self.version += 1
        if self._file is None:
            self.open()
        self._file.write(f"{self.version}: {json.dumps(op, sort_keys=True)}\n")
        self._file.flush()
        return self.version

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def rotate(self) -> None:
        """changelog.N -> changelog.N+1 after an image dump
        (changelog.h:41)."""
        self.close()
        for n in range(MAX_KEPT_LOGS, 0, -1):
            src = os.path.join(self.data_dir, f"changelog.{n - 1}.log")
            dst = os.path.join(self.data_dir, f"changelog.{n}.log")
            if os.path.exists(src):
                os.replace(src, dst)

    @staticmethod
    def parse_line(line: str) -> tuple[int, dict] | None:
        line = line.strip()
        if not line:
            return None
        version_s, _, payload = line.partition(": ")
        try:
            return int(version_s), json.loads(payload)
        except (ValueError, json.JSONDecodeError):
            raise ValueError(f"corrupt changelog line: {line[:120]!r}") from None

    def iter_entries(self, after_version: int):
        """Yield (version, op) with version > after_version from all kept
        logs in order (oldest first)."""
        files = []
        for n in range(MAX_KEPT_LOGS, -1, -1):
            p = os.path.join(self.data_dir, f"changelog.{n}.log")
            if os.path.exists(p):
                files.append(p)
        for p in files:
            with open(p, encoding="utf-8") as f:
                for line in f:
                    parsed = self.parse_line(line)
                    if parsed is None:
                        continue
                    version, op = parsed
                    if version > after_version:
                        yield version, op


def save_image(data_dir: str, version: int, sections: dict) -> str:
    """Atomically write the metadata image (fork-less MetadataDumper
    analog — the tree is small enough to serialize inline; background
    dumping can move to a thread when trees grow)."""
    path = os.path.join(data_dir, "metadata.liz")
    tmp = path + ".tmp"
    doc = {"format": IMAGE_FORMAT, "version": version, **sections}
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_image(data_dir: str) -> tuple[int, dict] | None:
    path = os.path.join(data_dir, "metadata.liz")
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("format") != IMAGE_FORMAT:
        raise ValueError(f"unknown metadata image format {doc.get('format')!r}")
    return doc["version"], doc
