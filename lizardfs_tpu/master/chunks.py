"""Chunk registry + chunkserver database + health engine.

The analog of the reference's chunk metadata engine (reference:
src/master/chunks.{h,cc}): per-chunk version and slice type, live part
locations (volatile — rebuilt from chunkserver registrations, never
persisted), redundancy evaluation (ChunkCopiesCalculator analog,
src/common/chunk_copies_calculator.h:41-95), an **endangered-first
priority queue** (chunks.cc:256-259), and the periodic health walk that
issues replicate/delete commands (chunks.cc:1807-2200).

Server selection is label-aware weighted-by-free-space choice
(get_servers_for_new_chunk.h:68-100 analog).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from lizardfs_tpu.core import geometry
from lizardfs_tpu.proto import status as st


@dataclass
class ChunkServerInfo:
    cs_id: int
    host: str
    port: int
    label: str
    total_space: int = 0
    used_space: int = 0
    connected: bool = True
    data_port: int = 0  # native data-plane port (0 = use control port)
    # True while the entry is fed by a PASSIVE mirror link (shadow
    # side): locations are servable but no command link exists — admin
    # tooling must not mistake a mirror-fed shadow for the active
    mirror: bool = False

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    @property
    def data_addr_port(self) -> int:
        """Port clients should use for data-plane ops."""
        return self.data_port or self.port

    @property
    def free_space(self) -> int:
        return max(self.total_space - self.used_space, 0)


@dataclass
class ChunkInfo:
    chunk_id: int
    version: int
    slice_type: int  # geometry slice type id
    copies: int = 1  # wanted copies per part (std goals: N-copy replication)
    goal_id: int = 0  # goal that created this chunk (label-aware repair)
    refcount: int = 1  # files referencing this chunk (snapshots share; COW
    #                    on write — chunk_goal_counters analog)
    # temporary heat-driven goal boost: extra wanted copies on top of
    # ``copies`` while the chunk is hot (master/heat.py adaptive
    # replication). Applied/cleared ONLY through the goal_boost /
    # goal_demote changelog ops so shadows and the image agree.
    boost: int = 0
    locked_until: float = 0.0
    # live locations: (cs_id, slice part index) set; volatile
    parts: set[tuple[int, int]] = field(default_factory=set)

    def parts_by_index(self) -> dict[int, list[int]]:
        out: dict[int, list[int]] = {}
        for cs_id, part in self.parts:
            out.setdefault(part, []).append(cs_id)
        return out


class RedundancyState:
    """ChunkCopiesCalculator verdict for one chunk."""

    def __init__(self, missing: list[int], redundant: list[tuple[int, int]],
                 safe: bool, readable: bool,
                 crowded: list[tuple[int, int]] | None = None,
                 boost_only: bool = False):
        self.missing_parts = missing  # slice part indices with no copy
        self.redundant = redundant  # (cs_id, part) copies beyond 1
        self.is_safe = safe  # can lose any single server w/o data loss
        self.is_readable = readable
        # (cs_id, part) pairs doubled up on a server that already holds
        # another part of this chunk — emergency placement that should
        # migrate off once a distinct server is available
        self.crowded = crowded or []
        # True when every missing copy is owed only to a heat-driven
        # goal boost (base goal satisfied): replication work, yes, but
        # never "endangered" on health surfaces or in priority queues
        self.boost_only = boost_only

    @property
    def is_endangered(self) -> bool:
        return self.is_readable and not self.is_safe

    @property
    def needs_work(self) -> bool:
        return bool(self.missing_parts or self.redundant)


class ChunkRegistry:
    def __init__(self):
        self.chunks: dict[int, ChunkInfo] = {}
        self.servers: dict[int, ChunkServerInfo] = {}
        # (host, port) -> ChunkServerInfo: registration at 10k-server
        # scale must not scan the whole server table per register (a
        # storm of N registrations was O(N^2); test_scalability pins
        # the bound). Maintained by register_server only — servers are
        # never removed, only marked disconnected.
        self._server_by_addr: dict[tuple[str, int], ChunkServerInfo] = {}
        self.next_chunk_id = 1
        self.next_cs_id = 1
        # endangered queue served before routine work (chunks.cc:2562):
        # FIFO + membership set, O(1) push/pop — NOT a scan cursor; the
        # routine walk below keeps its own cursor
        from collections import deque

        self.endangered: deque[int] = deque()
        self._endangered_set: set[int] = set()
        # stale-version parts kept as repair material: when a
        # chunkserver registers parts at the wrong version for a chunk
        # that is currently UNREADABLE, deleting them would destroy the
        # only bytes `filerepair` can version-fix from (the reference
        # keeps "wrong version" copies for repair too).
        # chunk_id -> {(cs_id, wire part_id): version}; volatile.
        self.stale_versions: dict[int, dict[tuple[int, int], int]] = {}
        # per-server part index: cs_id -> {(chunk_id, part): ChunkInfo}
        # — the reference keeps per-server chunk lists (matocsserv.cc
        # server entries) so a disconnect touches only that server's
        # parts, never the whole table. Values hold the chunk object so
        # the disconnect walk skips a dict lookup per part (6x cheaper
        # at 50k parts). Maintained by every parts mutation.
        self._server_parts: dict[int, dict[tuple[int, int], ChunkInfo]] = {}
        # persistent background-scan cursor (chunks.cc:1807-1830
        # ChunkWorker coroutine analog): the id list snapshots once per
        # full cycle instead of being rebuilt every tick
        self._scan_ids: list[int] = []
        self._scan_idx = 0
        # chunk-danger aggregates maintained BY the routine walk: each
        # full cursor cycle counts endangered/lost as a side effect of
        # the evaluations it already performs, and publishes the totals
        # at wrap — health/stats probes read the published aggregate
        # instead of walking the whole table (the O(all-chunks) sweeps
        # at server.py cluster_health/chunks-health were the master's
        # biggest per-probe stall at 1M chunks).
        # (endangered, lost, chunks_at_publish); scanned_monotonic
        # counts total evaluations so tests can assert progress.
        self.danger_counts: tuple[int, int, int] = (0, 0, 0)
        self._cycle_endangered = 0
        self._cycle_lost = 0
        self.danger_scanned_total = 0
        # bootstrap cursor: bounds time-to-first-publish after a
        # (re)start (see danger_bootstrap)
        self._boot_ids: list[int] = []
        self._boot_idx = 0
        self._boot_endangered = 0
        self._boot_lost = 0
        self._rebalance_ids: list[int] = []
        # chunks released from metadata whose on-disk parts still need
        # deleting on chunkservers (drained by the master's health tick;
        # bounded so an idle shadow doesn't grow it forever)
        self.pending_deletes: list[ChunkInfo] = []
        self._rebalance_cursor = 0
        self._rng = random.Random(0xEC)
        # chunks currently carrying a heat-driven goal boost (mirrors
        # ChunkInfo.boost > 0; maintained by set_boost so the heat tick
        # never scans the whole table to find its own boosts)
        self.boosted: set[int] = set()
        # observatory-driven placement (master/heat.py): cs_id -> load
        # score in [0, 1+] (heartbeat health + DRR queue depth + heat
        # share, maintained by the master's heat tick). Empty — the
        # LZ_HEAT-off state — means pure free-space weighting, the
        # pre-heat behavior, byte for byte.
        self.server_load: dict[int, float] = {}

    # --- chunkserver db -------------------------------------------------------

    def register_server(
        self, host: str, port: int, label: str, total: int, used: int,
        data_port: int = 0,
    ) -> ChunkServerInfo:
        # reconnection of the same host:port replaces the old entry —
        # O(1) via the addr index (a 10k-server registration storm was
        # O(N^2) when this scanned the table)
        srv = self._server_by_addr.get((host, port))
        if srv is not None:
            srv.connected = True
            srv.label = label
            srv.total_space = total
            srv.used_space = used
            srv.data_port = data_port
            return srv
        cs = ChunkServerInfo(
            self.next_cs_id, host, port, label, total, used,
            data_port=data_port,
        )
        self.next_cs_id += 1
        self.servers[cs.cs_id] = cs
        self._server_by_addr[(host, port)] = cs
        return cs

    def server_disconnected(self, cs_id: int) -> list[int]:
        """Mark server down, drop its parts; returns affected chunk ids
        (chunks.h:80 chunk_server_disconnected analog).

        O(parts on that server) via the per-server index — a bounce on
        a 10M-chunk master must not walk the whole table under the
        event loop (test_scalability.py pins the bound)."""
        srv = self.servers.get(cs_id)
        if srv is not None:
            srv.connected = False
        affected = self.reset_server_parts(cs_id)
        # a dead server's stale-version parts are gone with it
        for cid in list(self.stale_versions):
            entries = self.stale_versions[cid]
            for key in [k for k in entries if k[0] == cs_id]:
                del entries[key]
            if not entries:
                del self.stale_versions[cid]
        return affected

    def reset_server_parts(self, cs_id: int) -> list[int]:
        """Drop every part recorded for ``cs_id`` WITHOUT marking it
        disconnected — a mirror re-registration (shadow side) replaces
        the server's part set wholesale with the fresh report. Returns
        the affected chunk ids (the one part-drop loop both this and
        server_disconnected share)."""
        affected = []
        append = affected.append
        for (chunk_id, part), chunk in self._server_parts.pop(
            cs_id, {}
        ).items():
            chunk.parts.discard((cs_id, part))
            append(chunk_id)
        return affected

    def connected_servers(self) -> list[ChunkServerInfo]:
        return [s for s in self.servers.values() if s.connected]

    def server_at(self, host: str, port: int):
        """Addr-indexed lookup (O(1)): client damaged-part reports name
        holders by address — clients never learn cs_ids."""
        return self._server_by_addr.get((host, port))

    def audit_index(self) -> list[str]:
        """Consistency check (tests/debug): chunk.parts and the
        per-server index must describe the same (cs, chunk, part)
        triples. Returns human-readable discrepancies, [] when clean."""
        truth: set[tuple[int, int, int]] = {
            (cs, cid, part)
            for cid, chunk in self.chunks.items()
            for cs, part in chunk.parts
        }
        indexed: set[tuple[int, int, int]] = {
            (cs, cid, part)
            for cs, entries in self._server_parts.items()
            for (cid, part) in entries
        }
        return (
            [f"unindexed part {t}" for t in sorted(truth - indexed)]
            + [f"phantom index entry {t}" for t in sorted(indexed - truth)]
        )

    # --- chunk lifecycle --------------------------------------------------------

    def create_chunk(self, slice_type: int, chunk_id: int | None = None,
                     version: int = 1, copies: int = 1,
                     goal_id: int = 0) -> ChunkInfo:
        if chunk_id is None:
            chunk_id = self.next_chunk_id
        self.next_chunk_id = max(self.next_chunk_id, chunk_id + 1)
        chunk = ChunkInfo(chunk_id, version, slice_type, copies=copies,
                          goal_id=goal_id)
        self.chunks[chunk_id] = chunk
        return chunk

    def chunk(self, chunk_id: int) -> ChunkInfo:
        c = self.chunks.get(chunk_id)
        if c is None:
            raise KeyError(f"chunk {chunk_id}")
        return c

    def add_part(self, chunk_id: int, cs_id: int, part_id: int, version: int) -> bool:
        """Record a part reported by a chunkserver; False = stale/unknown
        (caller schedules deletion)."""
        chunk = self.chunks.get(chunk_id)
        if chunk is None or version != chunk.version:
            return False
        cpt = geometry.ChunkPartType.from_id(part_id)
        if int(cpt.type) != chunk.slice_type:
            return False
        self.record_part(chunk, cs_id, cpt.part)
        return True

    def record_part(self, chunk: ChunkInfo, cs_id: int, part: int) -> None:
        """The one write path for part locations: keeps chunk.parts and
        the per-server index in lockstep."""
        chunk.parts.add((cs_id, part))
        self._server_parts.setdefault(cs_id, {})[
            (chunk.chunk_id, part)
        ] = chunk

    def unregister_parts(
        self, chunk: ChunkInfo, stale: set[tuple[int, int]]
    ) -> None:
        """Drop a set of (cs_id, part) entries (e.g. holders that missed
        a version bump) keeping the per-server index in lockstep."""
        chunk.parts -= stale
        for cs_id, part in stale:
            idx = self._server_parts.get(cs_id)
            if idx is not None:
                idx.pop((chunk.chunk_id, part), None)

    def drop_part(self, chunk_id: int, cs_id: int, part_id: int) -> None:
        chunk = self.chunks.get(chunk_id)
        if chunk is None:
            return
        cpt = geometry.ChunkPartType.from_id(part_id)
        chunk.parts.discard((cs_id, cpt.part))
        idx = self._server_parts.get(cs_id)
        if idx is not None:
            idx.pop((chunk_id, cpt.part), None)

    def record_stale(
        self, chunk_id: int, cs_id: int, part_id: int, version: int
    ) -> None:
        """Remember a wrong-version part as repair material (see
        stale_versions). Bounded per chunk by construction (one entry
        per (server, part))."""
        self.stale_versions.setdefault(chunk_id, {})[
            (cs_id, part_id)
        ] = version

    def delete_chunk(self, chunk_id: int) -> ChunkInfo | None:
        self.stale_versions.pop(chunk_id, None)
        self.boosted.discard(chunk_id)
        chunk = self.chunks.pop(chunk_id, None)
        if chunk is not None and chunk.parts:
            for cs_id, part in chunk.parts:
                idx = self._server_parts.get(cs_id)
                if idx is not None:
                    idx.pop((chunk_id, part), None)
            self.pending_deletes.append(chunk)
            if len(self.pending_deletes) > 100_000:
                del self.pending_deletes[:-100_000]
        return chunk

    def set_boost(self, chunk_id: int, boost: int) -> None:
        """The one write path for heat goal boosts: keeps ChunkInfo.boost
        and the ``boosted`` set in lockstep (goal_boost / goal_demote op
        application and image load both come through here)."""
        chunk = self.chunks.get(chunk_id)
        if chunk is None:
            return
        chunk.boost = max(int(boost), 0)
        if chunk.boost:
            self.boosted.add(chunk_id)
        else:
            self.boosted.discard(chunk_id)

    def release_chunk(self, chunk_id: int) -> None:
        """Drop one file reference; physical deletion only at zero."""
        chunk = self.chunks.get(chunk_id)
        if chunk is None:
            return
        chunk.refcount -= 1
        if chunk.refcount <= 0:
            self.delete_chunk(chunk_id)

    # --- redundancy evaluation ----------------------------------------------------

    def evaluate(self, chunk: ChunkInfo) -> RedundancyState:
        t = geometry.SliceType(chunk.slice_type)
        expected = t.expected_parts
        by_index = chunk.parts_by_index()
        live = {
            p: [c for c in cs_list if self.servers.get(c) and self.servers[c].connected]
            for p, cs_list in by_index.items()
        }
        live = {p: cs for p, cs in live.items() if cs}
        if t.is_standard:
            ncopies = len(live.get(0, []))
            # under goal: each missing copy is a 'missing part 0' work
            # item; a heat boost raises the wanted count temporarily
            # (extra copies shed again through the redundant path once
            # the boost demotes)
            wanted = chunk.copies + max(chunk.boost, 0)
            missing = [0] * max(wanted - ncopies, 0)
            redundant = [
                (c, 0) for c in live.get(0, [])[wanted:]
            ]
            readable = ncopies >= 1
            # safety is judged against the BASE goal: a boost adds read
            # fan-out, it never redefines what counts as endangered
            safe = ncopies >= min(2, chunk.copies)
            return RedundancyState(
                missing, redundant, safe, readable,
                boost_only=bool(missing) and ncopies >= chunk.copies,
            )
        missing = [p for p in range(expected) if p not in live]
        redundant = []
        for p, cs_list in live.items():
            for c in cs_list[1:]:
                redundant.append((c, p))
        k = geometry.required_parts_to_recover(t)
        readable = len(live) >= k
        # safe: losing any one SERVER must still leave >= k distinct
        # parts. Counting servers (not parts) makes emergency doubled-up
        # placement (two parts on one server) honestly reduce safety.
        per_server: dict[int, list[int]] = {}
        for p, cs_list in live.items():
            per_server.setdefault(cs_list[0], []).append(p)
        nlive = len(live)
        worst_loss = max((len(ps) for ps in per_server.values()), default=0)
        safe = (nlive - worst_loss) >= k
        crowded = [
            (cs, p)
            for cs, ps in per_server.items() if len(ps) > 1
            for p in ps[1:]
        ]
        return RedundancyState(missing, redundant, safe, readable,
                               crowded=crowded)

    def mark_endangered(self, chunk_id: int) -> None:
        if chunk_id not in self._endangered_set:
            self._endangered_set.add(chunk_id)
            self.endangered.append(chunk_id)

    # --- server selection (get_servers_for_new_chunk analog) ----------------------

    def choose_servers(self, count: int, exclude: set[int] = frozenset(),
                       min_free: int = 0,
                       labels: list[str] | None = None) -> list[ChunkServerInfo]:
        """Label-aware weighted-by-free-space server choice
        (GetServersForNewChunk::chooseServersForLabels analog,
        get_servers_for_new_chunk.h:68-100).

        ``labels[i]`` constrains slot i: a concrete label must match the
        server's label; the wildcard "_" (or None) accepts any server.
        Distinct servers are preferred; repeats happen only when there
        are fewer eligible servers than slots. Labeled slots fall back
        to the wildcard pool if no labeled server exists (degraded but
        placed beats unplaced, matching the reference's behavior of
        preferring availability)."""
        candidates = [
            s
            for s in self.connected_servers()
            if s.cs_id not in exclude and s.free_space >= min_free
        ]
        if not candidates:
            raise ValueError("no chunkservers available")
        slot_labels = list(labels) if labels else ["_"] * count
        if len(slot_labels) < count:
            slot_labels += ["_"] * (count - len(slot_labels))

        def load_of(s: ChunkServerInfo) -> float:
            return max(self.server_load.get(s.cs_id, 0.0), 0.0)

        def pick_from(pool: list[ChunkServerInfo]) -> ChunkServerInfo | None:
            if not pool:
                return None
            # observed load scales the free-space weight down: a server
            # at load 1.0 competes with half its free space (load 0 —
            # the heat-off state — leaves the weight untouched)
            weights = [
                max(s.free_space, 1) / (1.0 + load_of(s)) for s in pool
            ]
            return pool[self._rng.choices(range(len(pool)), weights=weights)[0]]

        if count <= len(candidates):
            # one optimal distinct assignment: greedy label matching can
            # strand a constrained slot that a different pairing would
            # satisfy (linear_assignment_optimizer.h)
            from lizardfs_tpu.master import assignment

            idx = assignment.assign_slots(
                slot_labels[:count], candidates,
                jitter=lambda i, j: self._rng.randrange(100),
                load=lambda j: load_of(candidates[j]),
            )
            return [candidates[j] for j in idx]

        # fewer servers than slots: repeats are unavoidable — fill
        # constrained slots first, weighted-random by free space
        chosen: dict[int, ChunkServerInfo] = {}
        used: set[int] = set()
        order = sorted(range(count), key=lambda i: slot_labels[i] == "_")
        for i in order:
            want = slot_labels[i]
            labeled = [
                s for s in candidates
                if (want == "_" or s.label == want) and s.cs_id not in used
            ]
            s = pick_from(labeled)
            if s is None and want != "_":
                s = pick_from([c for c in candidates if c.cs_id not in used])
            if s is None:  # all distinct servers used: allow repeats
                pool = [c for c in candidates if want == "_" or c.label == want]
                s = pick_from(pool or candidates)
            chosen[i] = s
            used.add(s.cs_id)
        return [chosen[i] for i in range(count)]

    # --- health walk (ChunkWorker coroutine analog) --------------------------------

    # routine-scan evaluation budget per tick: bounds event-loop time
    # regardless of table size (the endangered queue is served first and
    # separately)
    SCAN_BUDGET = 256

    def _scan_batch(self, n: int) -> list[int]:
        """Next ``n`` chunk ids from the persistent cursor; the id list
        re-snapshots once per full cycle (O(all chunks) amortized over
        a whole sweep, never per tick). A wrap publishes the finished
        cycle's danger aggregate."""
        if self._scan_idx >= len(self._scan_ids):
            if self._scan_ids or not self.chunks:
                # a completed cycle (or an empty table) defines the
                # aggregate; a fresh registry's first wrap publishes 0s
                self.danger_counts = (
                    self._cycle_endangered, self._cycle_lost,
                    len(self._scan_ids),
                )
            self._cycle_endangered = 0
            self._cycle_lost = 0
            self._scan_ids = list(self.chunks.keys())
            self._scan_idx = 0
            if not self._scan_ids:
                return []
        batch = self._scan_ids[self._scan_idx : self._scan_idx + n]
        self._scan_idx += len(batch)
        return batch

    def danger_bootstrap(self, budget: int = 4096) -> None:
        """Bound time-to-first-publish of the danger aggregate.

        The routine walk publishes at cycle WRAP — after a master
        (re)start with 1M chunks that is a full sweep at
        SCAN_BUDGET/tick (~an hour), during which /health would report
        ``lost: 0`` for a table full of unreadable chunks. Until the
        first publish, each health tick also advances this count-only
        cursor (``budget`` evaluations, a few ms); whichever cursor
        completes first publishes. No-op once danger_counts carries a
        published cycle."""
        if self.danger_counts[2] or not self.chunks:
            if self._boot_ids:
                # routine walk published first: free the snapshot (1M
                # ids is ~40 MB — must not pin for the registry's life)
                self._boot_ids = []
                self._boot_idx = 0
            return
        if not self._boot_ids:
            self._boot_ids = list(self.chunks.keys())
            self._boot_idx = 0
            self._boot_endangered = 0
            self._boot_lost = 0
        end = min(self._boot_idx + budget, len(self._boot_ids))
        for cid in self._boot_ids[self._boot_idx:end]:
            chunk = self.chunks.get(cid)
            if chunk is None:
                continue
            state = self.evaluate(chunk)
            self.danger_scanned_total += 1
            if not state.is_readable:
                self._boot_lost += 1
            elif state.is_endangered or (
                state.missing_parts and not state.boost_only
            ):
                self._boot_endangered += 1
        self._boot_idx = end
        if end >= len(self._boot_ids):
            if not self.danger_counts[2]:
                self.danger_counts = (
                    self._boot_endangered, self._boot_lost,
                    len(self._boot_ids),
                )
            self._boot_ids = []

    def _count_danger(self, state: RedundancyState) -> None:
        self.danger_scanned_total += 1
        if not state.is_readable:
            self._cycle_lost += 1
        elif state.is_endangered or (
            state.missing_parts and not state.boost_only
        ):
            self._cycle_endangered += 1

    def _chunk_work(self, chunk: ChunkInfo, out: list,
                    state: RedundancyState | None = None) -> None:
        if state is None:
            state = self.evaluate(chunk)
        for p in state.missing_parts:
            out.append(("replicate", chunk, p))
        for cs_id, p in state.redundant:
            out.append(("delete", chunk, cs_id, p))
        if state.crowded and not state.missing_parts:
            # emergency doubled-up placement: migrate the extra part off
            # as soon as a distinct server is free (keeps the emergency
            # placement from becoming permanent degraded fault tolerance)
            holders = {cs for cs, _ in chunk.parts}
            spare = [
                s for s in self.connected_servers() if s.cs_id not in holders
            ]
            for (cs_id, p), dst in zip(state.crowded, spare):
                out.append(("move", chunk, cs_id, p, dst.cs_id))

    def health_work(self, limit: int = 64):
        """Yield up to ``limit`` work items: ('replicate', chunk, part),
        ('delete', chunk, cs_id, part) or ('move', chunk, src, part, dst).

        Endangered chunks drain FIRST from a real FIFO (items that don't
        fit this tick simply stay queued); the routine walk then resumes
        from its cursor with a bounded evaluation budget — one tick costs
        O(limit + SCAN_BUDGET) whatever the table size."""
        out = []
        # 1) priority: endangered queue. Evaluation-bounded too — after
        # a chunkserver bounce the whole table may be queued but mostly
        # healthy again, and popping it all in one tick would be an
        # O(all chunks) stall.
        pops = 0
        while self.endangered and len(out) < limit and pops < self.SCAN_BUDGET:
            pops += 1
            cid = self.endangered.popleft()
            self._endangered_set.discard(cid)
            chunk = self.chunks.get(cid)
            if chunk is None:
                continue
            self._chunk_work(chunk, out)
        # 2) routine: bounded cursor walk; if the tick fills up, rewind
        # the cursor over the unvisited remainder — next tick resumes
        # exactly there
        batch = self._scan_batch(self.SCAN_BUDGET)
        for i, cid in enumerate(batch):
            if len(out) >= limit:
                self._scan_idx -= len(batch) - i
                break
            chunk = self.chunks.get(cid)
            if chunk is None:
                continue
            state = self.evaluate(chunk)
            # danger aggregate rides the evaluation the walk already
            # pays for (rewound chunks are re-counted next tick, never
            # skipped: the cursor only rewinds over UNvisited ids)
            self._count_danger(state)
            self._chunk_work(chunk, out, state)
        if not out:
            move = self.rebalance_candidate()
            if move is not None:
                out.append(move)
        return out

    # fullness-gap threshold before a part is migrated (fraction)
    REBALANCE_GAP = 0.20

    def rebalance_candidate(self):
        """One ('move', chunk, src_cs, part, dst_cs) when the fullest and
        emptiest servers diverge by more than REBALANCE_GAP (the
        reference's continuous rebalancing, chunks.cc replication loop).
        Only healthy, unlocked chunks move; one migration at a time keeps
        the loop gentle."""
        servers = [s for s in self.connected_servers() if s.total_space > 0]
        if len(servers) < 2:
            return None
        fullest = max(servers, key=lambda s: s.used_space / s.total_space)
        emptiest = min(servers, key=lambda s: s.used_space / s.total_space)
        gap = (fullest.used_space / fullest.total_space
               - emptiest.used_space / emptiest.total_space)
        if gap < self.REBALANCE_GAP:
            return None
        now = time.monotonic()
        # bounded scan with a persistent cursor: never walk the whole
        # chunk table in one health tick (millions of chunks would stall
        # the event loop while the gap persists with no eligible chunk);
        # the id snapshot refreshes once per wrap, not per call
        if self._rebalance_cursor >= len(self._rebalance_ids):
            self._rebalance_ids = list(self.chunks.keys())
            self._rebalance_cursor = 0
        ids = self._rebalance_ids
        if not ids:
            return None
        start = self._rebalance_cursor
        budget = min(len(ids) - start, 512)
        for i in range(budget):
            cid = ids[start + i]
            self._rebalance_cursor = start + i + 1
            chunk = self.chunks.get(cid)
            if chunk is None or chunk.locked_until > now:
                continue
            holders = {cs for cs, _ in chunk.parts}
            if emptiest.cs_id in holders:
                continue
            for cs_id, part in sorted(chunk.parts):
                if cs_id == fullest.cs_id:
                    if self.evaluate(chunk).needs_work:
                        break  # unhealthy chunks are repair work, not moves
                    return ("move", chunk, cs_id, part, emptiest.cs_id)
        return None
