"""Incremental background metadata jobs — the TaskManager analog.

The reference executes long-running metadata work (recursive remove,
subtree setgoal/settrashtime, snapshots of huge trees) in small batches
from the event loop so client service never stalls (reference:
src/master/task_manager.h:141-150, recursive_remove_task.cc,
setgoal_task.cc). Same shape: a job yields work units; the manager runs
up to ``batch`` units per tick and reports progress/completion over the
admin protocol.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Iterator


@dataclass
class Task:
    task_id: int
    kind: str
    ops: Iterator[dict]  # yields op records to commit, one per unit
    done_units: int = 0
    finished: bool = False
    error: str = ""

    def to_dict(self) -> dict:
        return {
            "task_id": self.task_id, "kind": self.kind,
            "done_units": self.done_units, "finished": self.finished,
            "error": self.error,
        }


class TaskManager:
    def __init__(self, commit, batch: int = 64):
        """commit: callable(op_dict) — the master's one write path."""
        self._commit = commit
        self.batch = batch
        self._ids = itertools.count(1)
        self.tasks: dict[int, Task] = {}

    def submit(self, kind: str, ops: Iterator[dict]) -> Task:
        task = Task(next(self._ids), kind, ops)
        self.tasks[task.task_id] = task
        return task

    def tick(self) -> int:
        """Run one batch across all live tasks; returns units executed."""
        executed = 0
        for task in list(self.tasks.values()):
            if task.finished:
                continue
            for _ in range(self.batch):
                try:
                    op = next(task.ops)
                except StopIteration:
                    task.finished = True
                    break
                except Exception as e:  # noqa: BLE001
                    task.error = str(e)[:300]
                    task.finished = True
                    break
                try:
                    self._commit(op)
                except Exception as e:  # noqa: BLE001
                    # an op failing mid-job (e.g. concurrent mutation)
                    # records the error but doesn't kill the master
                    task.error = str(e)[:300]
                task.done_units += 1
                executed += 1
        # retire finished tasks after they have been visible for a while
        if len(self.tasks) > 256:
            for tid in sorted(self.tasks):
                if self.tasks[tid].finished:
                    del self.tasks[tid]
                if len(self.tasks) <= 128:
                    break
        return executed


# --- job generators ---------------------------------------------------------


def recursive_remove_ops(fs, parent: int, name: str, ts: int) -> Iterator[dict]:
    """Post-order removal of a subtree, one op per entry
    (recursive_remove_task analog). Validates eagerly; the tree is
    walked lazily, so concurrent changes surface as per-op errors."""
    root = fs.lookup(parent, name)  # raises before the task is submitted

    def one_file():
        yield {"op": "unlink", "parent": parent, "name": name, "ts": ts,
               "to_trash": True}

    if root.ftype != 2:
        return one_file()

    def walk(dir_inode: int):
        node = fs.nodes.get(dir_inode)
        if node is None:
            return
        for child_name, child in sorted(node.children.items()):
            cn = fs.nodes.get(child)
            if cn is not None and cn.ftype == 2:
                yield from walk(child)
                yield {"op": "rmdir", "parent": dir_inode, "name": child_name,
                       "ts": ts}
            else:
                yield {"op": "unlink", "parent": dir_inode,
                       "name": child_name, "ts": ts, "to_trash": True}

    def gen():
        yield from walk(root.inode)
        yield {"op": "rmdir", "parent": parent, "name": name, "ts": ts}

    return gen()


def subtree_setgoal_ops(fs, inode: int, goal: int, ts: int) -> Iterator[dict]:
    """Recursive setgoal (setgoal_task analog)."""
    fs.node(inode)  # eager validation

    def walk(i: int):
        node = fs.nodes.get(i)
        if node is None:
            return
        yield {"op": "setgoal", "inode": i, "goal": goal, "ts": ts}
        if node.ftype == 2:
            for child in sorted(node.children.values()):
                yield from walk(child)

    return walk(inode)


def subtree_settrashtime_ops(fs, inode: int, seconds: int, ts: int) -> Iterator[dict]:
    """Recursive settrashtime (settrashtime_task analog)."""
    fs.node(inode)  # eager validation

    def walk(i: int):
        node = fs.nodes.get(i)
        if node is None:
            return
        yield {
            "op": "setattr", "inode": i, "set_mask": 32, "mode": 0,
            "uid": 0, "gid": 0, "atime": 0, "mtime": 0, "ts": ts,
            "trash_time": seconds,
        }
        if node.ftype == 2:
            for child in sorted(node.children.values()):
                yield from walk(child)

    return walk(inode)
