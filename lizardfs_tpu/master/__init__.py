"""Master: metadata server — FS tree, chunk registry, changelog, health."""
