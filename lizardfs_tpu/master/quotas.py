"""Quota database: per-user / per-group / per-directory limits.

Mirror of the reference's QuotaDatabase (reference:
src/master/quota_database.h:30-90, filesystem_quota.cc): soft and hard
limits on inode count and byte usage, keyed by uid, gid, or directory
inode (directory quotas apply to the whole subtree via the FS tree's
recursive statistics). Hard limits reject the operation with
QUOTA_EXCEEDED; soft limits mark the entry "exceeded" in reports.

uid/gid usage is tracked incrementally here; directory usage reads the
tree's stat_inodes/stat_bytes directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field

KIND_USER = "user"
KIND_GROUP = "group"
KIND_DIR = "dir"

RES_INODES = "inodes"
RES_BYTES = "bytes"


@dataclass
class QuotaEntry:
    soft_inodes: int = 0  # 0 = unlimited
    hard_inodes: int = 0
    soft_bytes: int = 0
    hard_bytes: int = 0
    used_inodes: int = 0  # tracked for user/group only
    used_bytes: int = 0

    def to_dict(self) -> dict:
        return {
            "soft_inodes": self.soft_inodes, "hard_inodes": self.hard_inodes,
            "soft_bytes": self.soft_bytes, "hard_bytes": self.hard_bytes,
            "used_inodes": self.used_inodes, "used_bytes": self.used_bytes,
        }


class QuotaDatabase:
    def __init__(self):
        self.entries: dict[tuple[str, int], QuotaEntry] = {}

    def entry(self, kind: str, owner_id: int, create: bool = False) -> QuotaEntry | None:
        key = (kind, owner_id)
        e = self.entries.get(key)
        if e is None and create:
            e = self.entries[key] = QuotaEntry()
        return e

    def set_limits(
        self, kind: str, owner_id: int,
        soft_inodes: int, hard_inodes: int, soft_bytes: int, hard_bytes: int,
    ) -> None:
        e = self.entry(kind, owner_id, create=True)
        e.soft_inodes = soft_inodes
        e.hard_inodes = hard_inodes
        e.soft_bytes = soft_bytes
        e.hard_bytes = hard_bytes

    def remove(self, kind: str, owner_id: int) -> None:
        e = self.entries.get((kind, owner_id))
        if e is not None:
            # keep usage tracking for user/group entries with no limits
            if e.used_inodes or e.used_bytes:
                e.soft_inodes = e.hard_inodes = 0
                e.soft_bytes = e.hard_bytes = 0
            else:
                del self.entries[(kind, owner_id)]

    # --- incremental usage (user/group) -----------------------------------

    def charge(self, uid: int, gid: int, d_inodes: int, d_bytes: int) -> None:
        for kind, oid in ((KIND_USER, uid), (KIND_GROUP, gid)):
            e = self.entry(kind, oid, create=True)
            e.used_inodes = max(0, e.used_inodes + d_inodes)
            e.used_bytes = max(0, e.used_bytes + d_bytes)

    # --- enforcement -------------------------------------------------------

    def check(self, uid: int, gid: int, d_inodes: int, d_bytes: int) -> bool:
        """True iff the hard limits permit adding (d_inodes, d_bytes)."""
        for kind, oid in ((KIND_USER, uid), (KIND_GROUP, gid)):
            e = self.entries.get((kind, oid))
            if e is None:
                continue
            if e.hard_inodes and e.used_inodes + d_inodes > e.hard_inodes:
                return False
            if e.hard_bytes and e.used_bytes + d_bytes > e.hard_bytes:
                return False
        return True

    def check_dir(self, dir_stats: tuple[int, int], entry: QuotaEntry,
                  d_inodes: int, d_bytes: int) -> bool:
        used_i, used_b = dir_stats
        if entry.hard_inodes and used_i + d_inodes > entry.hard_inodes:
            return False
        if entry.hard_bytes and used_b + d_bytes > entry.hard_bytes:
            return False
        return True

    def dir_entries(self) -> list[tuple[int, QuotaEntry]]:
        return [
            (oid, e) for (kind, oid), e in self.entries.items() if kind == KIND_DIR
        ]

    # --- persistence -------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            f"{kind}:{oid}": e.to_dict() for (kind, oid), e in self.entries.items()
        }

    @classmethod
    def from_dict(cls, d: dict) -> "QuotaDatabase":
        db = cls()
        for key, row in d.items():
            kind, _, oid = key.partition(":")
            e = db.entry(kind, int(oid), create=True)
            for k, v in row.items():
                setattr(e, k, int(v))
        return db
