"""Run the master daemon: python -m lizardfs_tpu.master [config]

Config keys (KEY = VALUE, mfsmaster.cfg analog): DATA_PATH, LISTEN_HOST,
LISTEN_PORT, GOALS_CFG (path to mfsgoals.cfg-style file), IO_LIMIT_BPS
(global bytes/s budget), IO_LIMITS_CFG (mfsiolimits.cfg-style per-cgroup
budgets: `subsystem X` + `limit <group> <bps>` lines), QOS_CFG
(multi-tenant fair-share config: tenant match rules/weights, per-class
admission rates, data-plane budgets — doc/operations.md QoS runbook),
LOG_LEVEL,
HEALTH_INTERVAL, IMAGE_INTERVAL, LIFECYCLE_INTERVAL (s3 lifecycle
tiering scan period), PERSONALITY (master|shadow),
ACTIVE_MASTER (host:port, required for shadow), and optional election:
ELECTION_ID, ELECTION_LISTEN (host:port), ELECTION_PEERS
(id=host:port,id=host:port,...), PROMOTE_EXEC / DEMOTE_EXEC (shell
commands run on leadership transitions with LIZ_NODE_ID/LIZ_ROLE set —
the floating-IP helper glue).
"""

import asyncio
import sys

from lizardfs_tpu import constants
from lizardfs_tpu.master.server import MasterServer
from lizardfs_tpu.runtime.config import Config
from lizardfs_tpu.runtime.daemon import setup_logging


def _hostport(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return host, int(port)


async def _run(cfg: Config) -> None:
    personality = cfg.get_str("PERSONALITY", "master")
    active = cfg.get_str("ACTIVE_MASTER", "")
    config_paths = {
        key: path for key, path in (
            ("goals", cfg.get_str("GOALS_CFG", "")),
            ("exports", cfg.get_str("EXPORTS_CFG", "")),
            ("topology", cfg.get_str("TOPOLOGY_CFG", "")),
            ("iolimits", cfg.get_str("IO_LIMITS_CFG", "")),
            ("qos", cfg.get_str("QOS_CFG", "")),
        ) if path
    }
    server = MasterServer(
        data_dir=cfg.get_str("DATA_PATH", "./master-data"),
        host=cfg.get_str("LISTEN_HOST", "127.0.0.1"),
        port=cfg.get_int("LISTEN_PORT", 9420),
        health_interval=cfg.get_float("HEALTH_INTERVAL", 1.0, min_value=0.05),
        image_interval=cfg.get_float("IMAGE_INTERVAL", 300.0, min_value=1.0),
        personality=personality,
        active_addr=_hostport(active) if active else None,
        io_limit_bps=cfg.get_int("IO_LIMIT_BPS", 0),
        admin_password=cfg.get_str("ADMIN_PASSWORD", "") or None,
        lock_grace_seconds=cfg.get_float("LOCK_GRACE", 30.0, min_value=0.0),
        config_paths=config_paths,
        lifecycle_interval=cfg.get_float(
            "LIFECYCLE_INTERVAL", 30.0, min_value=0.1
        ),
    )
    # initial load runs the SAME code as SIGHUP reload, strictly: boot
    # fails loudly on a bad file instead of serving half a config
    server.reload(strict=True)
    controller = None
    # LZ_HA kill switch: off = no election socket, no vote traffic, no
    # automatic promotion — the daemon behaves exactly like the manual-
    # promotion tree even with ELECTION_* configured (promote-shadow
    # over the admin port still works)
    if cfg.get_str("ELECTION_ID", "") and constants.ha_enabled():
        from lizardfs_tpu.ha.controller import FailoverController

        peers = {}
        for item in cfg.get_str("ELECTION_PEERS", "").split(","):
            if item.strip():
                pid, _, addr = item.strip().partition("=")
                peers[pid] = _hostport(addr)
        # MASTER_PEERS (id=host:port,...): each node's master SERVICE
        # address, so followers can re-point their changelog stream at
        # whichever node currently leads (no floating IP required)
        service_addrs = {}
        for item in cfg.get_str("MASTER_PEERS", "").split(","):
            if item.strip():
                pid, _, addr = item.strip().partition("=")
                service_addrs[pid] = _hostport(addr)
        controller = FailoverController(
            server,
            cfg.get_str("ELECTION_ID"),
            _hostport(cfg.get_str("ELECTION_LISTEN", "127.0.0.1:0")),
            peers,
            promote_exec=cfg.get_str("PROMOTE_EXEC", "") or None,
            demote_exec=cfg.get_str("DEMOTE_EXEC", "") or None,
            service_addrs=service_addrs,
            # RTO tuning knobs (doc/operations.md failover runbook):
            # detect time is bounded by the randomized election timeout,
            # steady-state traffic by the heartbeat interval
            election_timeout=(
                cfg.get_float("ELECTION_TIMEOUT_MIN", 0.15, min_value=0.01),
                cfg.get_float("ELECTION_TIMEOUT_MAX", 0.30, min_value=0.02),
            ),
            heartbeat_interval=cfg.get_float(
                "HEARTBEAT_INTERVAL", 0.05, min_value=0.005
            ),
        )
        server.ha_controller = controller
    if controller is not None:
        await controller.start()
    try:
        await server.run_forever()
    finally:
        if controller is not None:
            await controller.stop()


def main() -> None:
    cfg = Config(sys.argv[1] if len(sys.argv) > 1 else None)
    setup_logging("master", cfg.get_str("LOG_LEVEL", "INFO"))
    asyncio.run(_run(cfg))


if __name__ == "__main__":
    main()
