"""Run the master daemon: python -m lizardfs_tpu.master [config]

Config keys (KEY = VALUE, mfsmaster.cfg analog): DATA_PATH, LISTEN_HOST,
LISTEN_PORT, GOALS_CFG (path to mfsgoals.cfg-style file), LOG_LEVEL,
HEALTH_INTERVAL, IMAGE_INTERVAL.
"""

import asyncio
import sys

from lizardfs_tpu.core import geometry
from lizardfs_tpu.master.server import MasterServer
from lizardfs_tpu.runtime.config import Config
from lizardfs_tpu.runtime.daemon import setup_logging


def main() -> None:
    cfg = Config(sys.argv[1] if len(sys.argv) > 1 else None)
    setup_logging("master", cfg.get_str("LOG_LEVEL", "INFO"))
    goals = geometry.default_goals()
    goals_path = cfg.get_str("GOALS_CFG", "")
    if goals_path:
        with open(goals_path) as f:
            goals = geometry.load_goal_config(f.read())
    server = MasterServer(
        data_dir=cfg.get_str("DATA_PATH", "./master-data"),
        host=cfg.get_str("LISTEN_HOST", "127.0.0.1"),
        port=cfg.get_int("LISTEN_PORT", 9420),
        goals=goals,
        health_interval=cfg.get_float("HEALTH_INTERVAL", 1.0),
        image_interval=cfg.get_float("IMAGE_INTERVAL", 300.0),
    )
    asyncio.run(server.run_forever())


if __name__ == "__main__":
    main()
