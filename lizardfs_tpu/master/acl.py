"""POSIX access control lists (IEEE 1003.1e draft semantics).

The reference stores POSIX/Rich ACLs per inode with conversion helpers
(reference: src/master/acl_storage.cc, src/common/richacl*). This is
the POSIX model: owner/group/other classes from the mode bits plus
named users, named groups, and a mask; directories can also carry a
*default* ACL inherited by new children as their access ACL.

Permission bits: r=4 w=2 x=1.
"""

from __future__ import annotations

from dataclasses import dataclass, field

R, W, X = 4, 2, 1


@dataclass
class Acl:
    named_users: dict[int, int] = field(default_factory=dict)   # uid -> perms
    named_groups: dict[int, int] = field(default_factory=dict)  # gid -> perms
    mask: int | None = None  # None = no mask entry (pure mode semantics)

    def to_dict(self) -> dict:
        return {
            "users": {str(k): v for k, v in self.named_users.items()},
            "groups": {str(k): v for k, v in self.named_groups.items()},
            "mask": self.mask,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Acl":
        return cls(
            named_users={int(k): int(v) for k, v in d.get("users", {}).items()},
            named_groups={int(k): int(v) for k, v in d.get("groups", {}).items()},
            mask=d.get("mask"),
        )

    @property
    def effective_mask(self) -> int:
        return 7 if self.mask is None else self.mask


def check_access(
    mode: int,
    owner_uid: int,
    owner_gid: int,
    acl: Acl | None,
    uid: int,
    gids: list[int],
    want: int,
) -> bool:
    """POSIX ACL evaluation order: owner, named user, owning/named
    groups (mask-limited), other. Root bypasses."""
    if uid == 0:
        return True
    owner_bits = (mode >> 6) & 7
    group_bits = (mode >> 3) & 7
    other_bits = mode & 7
    if uid == owner_uid:
        return (owner_bits & want) == want
    if acl is not None and uid in acl.named_users:
        return (acl.named_users[uid] & acl.effective_mask & want) == want
    group_candidates = []
    if owner_gid in gids:
        bits = group_bits
        if acl is not None and acl.mask is not None:
            bits &= acl.mask
        group_candidates.append(bits)
    if acl is not None:
        for gid, perms in acl.named_groups.items():
            if gid in gids:
                group_candidates.append(perms & acl.effective_mask)
    if group_candidates:
        # POSIX: access granted if ANY matching group entry grants it
        return any((bits & want) == want for bits in group_candidates)
    return (other_bits & want) == want
