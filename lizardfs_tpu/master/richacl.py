"""RichACLs: NFSv4-style allow/deny access-control entries.

The analog of the reference's RichACL support (reference:
src/common/richacl.h RichACL/Ace with ALLOW/DENY types, owner@/group@/
everyone@ special ids, inheritance flags; src/common/acl_converter.cc
POSIX<->Rich conversion). Entries are evaluated IN ORDER: each ACE may
allow or deny some of the still-undecided permission bits; evaluation
ends when every requested bit is decided (NFSv4 semantics — unlike
POSIX ACLs, a later allow cannot override an earlier deny).

Permission mask bits (the subset of NFSv4 masks the file system
serves): r=4 w=2 x=1, matching the POSIX want-masks used by
master/acl.py so the two models share the permission-check call sites.
"""

from __future__ import annotations

from dataclasses import dataclass, field

ALLOW = 0
DENY = 1

# ACE flags (richacl.h:Ace flag analogs)
FILE_INHERIT = 1    # new files under this dir inherit the ACE
DIR_INHERIT = 2     # new subdirs inherit the ACE (and keep inheriting)
INHERIT_ONLY = 4    # the ACE does not apply to this object itself
NO_PROPAGATE = 8    # inherit one level, strip inherit flags on the child

# special principals (richacl.h special ids)
OWNER = "owner@"
GROUP = "group@"
EVERYONE = "everyone@"


@dataclass
class Ace:
    ace_type: int          # ALLOW | DENY
    flags: int             # inheritance flags
    mask: int              # permission bits r|w|x
    who: str               # "owner@" / "group@" / "everyone@" / "u:UID" / "g:GID"

    def to_dict(self) -> dict:
        return {"t": self.ace_type, "f": self.flags, "m": self.mask,
                "w": self.who}

    @classmethod
    def from_dict(cls, d: dict) -> "Ace":
        who = str(d["w"])
        if who not in (OWNER, GROUP, EVERYONE):
            kind, _, ident = who.partition(":")
            if kind not in ("u", "g"):
                raise ValueError(f"bad principal {who!r}")
            who = f"{kind}:{int(ident)}"  # int() rejects garbage ids
        ace_type = int(d["t"])
        if ace_type not in (ALLOW, DENY):
            raise ValueError(f"bad ace type {ace_type}")
        return cls(ace_type, int(d["f"]), int(d["m"]) & 7, who)

    def matches(self, owner_uid: int, owner_gid: int, uid: int,
                gids: list[int]) -> bool:
        if self.who == OWNER:
            return uid == owner_uid
        if self.who == GROUP:
            return owner_gid in gids
        if self.who == EVERYONE:
            return True
        if self.who.startswith("u:"):
            return uid == int(self.who[2:])
        if self.who.startswith("g:"):
            return int(self.who[2:]) in gids
        return False


@dataclass
class RichAcl:
    aces: list[Ace] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"aces": [a.to_dict() for a in self.aces]}

    @classmethod
    def from_dict(cls, d: dict) -> "RichAcl":
        return cls([Ace.from_dict(a) for a in d.get("aces", [])])

    # --- evaluation (richacl.cc permission walk analog) --------------------

    def check_access(self, owner_uid: int, owner_gid: int, uid: int,
                     gids: list[int], want: int,
                     mode: int | None = None) -> bool:
        """NFSv4 walk: first decision per bit wins; undecided bits deny.

        When ``mode`` is given it acts as the Linux-richacl file masks:
        the mode's class bits BOUND what the ACEs can grant (so chmod
        restricts a RichACL'd file and an inherited ACL cannot exceed
        the create mode). setrichacl lifts the mode to the ACL's
        per-class unions (compute_max_masks), so a freshly set ACL is
        not immediately capped.
        """
        if uid == 0:
            return True
        # class membership is over ALL applicable ACEs — it must not be
        # truncated by the grant walk's early exit (a named-user ACE
        # after a deciding everyone@ ACE still puts the caller in the
        # group class for the mode masks)
        matched_class = any(
            ace.who != EVERYONE
            and not ace.flags & INHERIT_ONLY
            and ace.matches(owner_uid, owner_gid, uid, gids)
            for ace in self.aces
        )
        allowed = 0
        denied = 0
        for ace in self.aces:
            if ace.flags & INHERIT_ONLY:
                continue
            if not ace.matches(owner_uid, owner_gid, uid, gids):
                continue
            undecided = ace.mask & ~(allowed | denied)
            if ace.ace_type == ALLOW:
                allowed |= undecided
            else:
                denied |= undecided
            if (want & denied) or (want & ~(allowed | denied)) == 0:
                break
        if mode is not None:
            if uid == owner_uid:
                mask = (mode >> 6) & 7
            elif owner_gid in gids or matched_class:
                mask = (mode >> 3) & 7
            else:
                mask = mode & 7
            allowed &= mask
        return (want & allowed) == want and not (want & denied)

    def compute_max_masks(self, owner_uid: int) -> tuple[int, int, int]:
        """Per-class unions of the ALLOW grants (richacl_compute_max_
        masks analog): what mode bits setrichacl should publish."""
        owner = group = other = 0
        for ace in self.aces:
            if ace.ace_type != ALLOW or ace.flags & INHERIT_ONLY:
                continue
            if ace.who == OWNER or ace.who == f"u:{owner_uid}":
                owner |= ace.mask
            elif ace.who == EVERYONE:
                owner |= ace.mask
                group |= ace.mask
                other |= ace.mask
            else:
                owner |= ace.mask
                group |= ace.mask
        return owner, group, other

    # --- inheritance (richacl inheritance flag semantics) ------------------

    def inherited(self, is_dir: bool) -> "RichAcl | None":
        """The ACL a new child gets, or None if nothing inherits."""
        out = []
        for ace in self.aces:
            if is_dir and ace.flags & DIR_INHERIT:
                flags = ace.flags & ~INHERIT_ONLY
                if ace.flags & NO_PROPAGATE:
                    flags &= ~(FILE_INHERIT | DIR_INHERIT | NO_PROPAGATE)
                out.append(Ace(ace.ace_type, flags, ace.mask, ace.who))
            elif is_dir and ace.flags & FILE_INHERIT:
                # NFSv4: a file-only-inheritable ACE passes THROUGH a
                # subdirectory (inherit-only there) so files deeper in
                # the tree still inherit it
                if not ace.flags & NO_PROPAGATE:
                    out.append(Ace(ace.ace_type,
                                   FILE_INHERIT | INHERIT_ONLY,
                                   ace.mask, ace.who))
            elif not is_dir and ace.flags & FILE_INHERIT:
                # files never propagate further: strip inheritance flags
                out.append(Ace(ace.ace_type, 0, ace.mask, ace.who))
        return RichAcl(out) if out else None


def from_posix(mode: int, acl) -> RichAcl:
    """POSIX(mode [+ Acl]) -> equivalent RichACL (acl_converter.cc
    posixToRich analog).

    POSIX classes never fall through (a group-class member whose class
    grants nothing is denied even if "other" would allow), so every
    class is CLOSED with deny ACEs after its allows: owner first, then
    named users, then the whole group class (union of owning +
    named-group allows, then denies), then everyone.
    """
    owner_bits = (mode >> 6) & 7
    aces = [Ace(ALLOW, 0, owner_bits, OWNER),
            Ace(DENY, 0, 7 & ~owner_bits, OWNER)]
    emask = acl.effective_mask if acl is not None else 7
    if acl is not None:
        for uid, perms in sorted(acl.named_users.items()):
            aces.append(Ace(ALLOW, 0, perms & emask, f"u:{uid}"))
            aces.append(Ace(DENY, 0, 7, f"u:{uid}"))
    # group class: allow every matching entry (POSIX grants if ANY
    # matching group-class entry grants), then close the class
    group_members = [(GROUP, (mode >> 3) & 7 & emask)]
    if acl is not None:
        group_members += [
            (f"g:{gid}", perms & emask)
            for gid, perms in sorted(acl.named_groups.items())
        ]
    for who, perms in group_members:
        aces.append(Ace(ALLOW, 0, perms, who))
    for who, _ in group_members:
        aces.append(Ace(DENY, 0, 7, who))
    aces.append(Ace(ALLOW, 0, mode & 7, EVERYONE))
    return RichAcl(aces)
