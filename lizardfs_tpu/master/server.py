"""Master daemon: client service, chunkserver service, shadow stream,
health loop, persistence.

One asyncio daemon hosting all the reference's master-side network
modules (reference: src/master/matoclserv.cc client service,
matocsserv.cc chunkserver service, matomlserv.cc shadow/metalogger
stream) over the MetadataStore state machine. Connections self-identify
with their first message (register), then stay in a per-role loop.

Write-path protocol (fuse_write_chunk analog, matoclserv.cc:2938):
  WriteChunk -> create chunk (choose servers per part, command creates)
                or bump version on existing parts; lock; reply locations
  WriteChunkEnd -> set file length, unlock, changelog.

Health loop (ChunkWorker analog, chunks.cc:1807): every tick, serve the
endangered queue first, then walk chunks; replicate missing parts
(MatocsReplicate to a chosen server with source locations) and delete
redundant ones.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time

from lizardfs_tpu.core import geometry
from lizardfs_tpu.master import fs as fsmod
from lizardfs_tpu.master.changelog import Changelog, load_image, save_image
from lizardfs_tpu.master.chunks import ChunkServerInfo
from lizardfs_tpu.master.locks import LOCK_UNLOCK, MAX_OFFSET
from lizardfs_tpu.master.metadata import MetadataStore
from lizardfs_tpu.master.quotas import KIND_DIR, KIND_GROUP, KIND_USER
from lizardfs_tpu import constants as constants_mod
from lizardfs_tpu.constants import MFSBLOCKSIZE, MFSCHUNKSIZE
from lizardfs_tpu.master import heat as heatmod
from lizardfs_tpu.master import rebuild as rebuild_mod
from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import accounting
from lizardfs_tpu.runtime import qos as qosmod
from lizardfs_tpu.runtime import retry as retrymod
from lizardfs_tpu.runtime import tracing
from lizardfs_tpu.runtime.daemon import Daemon


# client RPC -> op class for the per-session accounting the `top` view
# aggregates: chunk-grant RPCs split read/write (the latency-critical
# classes), namespace traffic splits by mutation, session/control
# chatter stays out of the hot classes
_OP_CLASS_READ = frozenset({
    "CltomaLookup", "CltomaGetattr", "CltomaReaddir", "CltomaReadlink",
    "CltomaAccess", "CltomaStatFs", "CltomaGetXattr", "CltomaListXattr",
    "CltomaGetQuota", "CltomaGetAcl", "CltomaGetRichAcl",
    "CltomaTrashList", "CltomaTapeInfo",
})
_OP_CLASS_SESSION = frozenset({
    "CltomaRegister", "CltomaGoodbye", "CltomaIoLimitRequest",
    "CltomaSessionStats", "CltomaOpen", "CltomaRelease",
})


def _op_class_of(msg) -> str:
    name = type(msg).__name__
    if name == "CltomaReadChunk":
        return "read"
    if name in (
        "CltomaWriteChunk", "CltomaWriteChunkEnd", "CltomaWriteChunkEndBatch",
    ):
        return "write"
    if name in _OP_CLASS_READ:
        return "meta_read"
    if name in _OP_CLASS_SESSION:
        return "session"
    return "meta_write"


def _fork_safe() -> bool:
    """CoW-fork is only safe from an effectively single-threaded
    process. The reference forks its dumper from a single-threaded
    event loop (metadata_dumper.h:37); a process that has loaded a
    thread-heavy native runtime (XLA/torch spawn pools whose mutexes a
    forked child inherits locked) must not fork, or the child can
    deadlock before it ever reaches Python. The master itself never
    imports jax (tests/test_fork_safety.py pins this), so production
    masters always take the fast CoW path; colocated/test processes
    that did import jax fall back to on-loop serialization."""
    if not hasattr(os, "fork"):
        return False
    import sys

    return not any(
        mod in sys.modules for mod in ("jax", "jaxlib", "torch")
    )

CHUNK_LOCK_SECONDS = 30.0

# LZ_SHADOW_READS kill switch (shared across roles — constants.py)
from lizardfs_tpu.constants import shadow_reads_enabled  # noqa: E402


class _CsLink:
    """Server-side link to one registered chunkserver: lets the master
    send commands and await acks while reports flow in."""

    def __init__(self, master: "MasterServer", reader, writer):
        self.master = master
        self.reader = reader
        self.writer = writer
        self.cs_id = 0
        # disjoint from the chunkserver's own call ids (they start at 1):
        # both directions share one connection (see rpc.RpcConnection._pump)
        self._req_ids = iter(range(1 << 30, 1 << 62))
        self._pending: dict[int, asyncio.Future] = {}
        self._dead = False

    async def command(self, msg_cls, *, timeout: float = 20.0, **fields):
        if self._dead:
            # a coroutine that kept this link across an await while the
            # chunkserver dropped would otherwise park on a future
            # nothing resolves until the full timeout (rpc.py fast-fail
            # pattern — failover latency, not correctness)
            raise ConnectionError("chunkserver disconnected")
        req_id = next(self._req_ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            await framing.send_message(self.writer, msg_cls(req_id=req_id, **fields))
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(req_id, None)

    def dispatch_ack(self, msg) -> bool:
        fut = self._pending.get(msg.req_id)
        if fut is not None and not fut.done():
            fut.set_result(msg)
            return True
        return False

    def fail_all(self):
        self._dead = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("chunkserver disconnected"))
        self._pending.clear()


class MasterServer(Daemon):
    name = "master"

    def __init__(
        self,
        data_dir: str,
        host: str = "127.0.0.1",
        port: int = 0,
        goals: dict[int, geometry.Goal] | None = None,
        health_interval: float = 1.0,
        image_interval: float = 300.0,
        personality: str = "master",
        active_addr: tuple[str, int] | None = None,
        exports=None,
        topology=None,
        io_limit_bps: int = 0,
        io_limits: dict[str, int] | None = None,
        io_limit_subsystem: str = "",
        admin_password: str | None = None,
        lock_grace_seconds: float = 30.0,
        config_paths: dict[str, str] | None = None,
        lifecycle_interval: float = 30.0,
    ):
        super().__init__(host, port)
        self.admin_password = admin_password
        # a briefly-disconnected client keeps its file locks for this
        # long; reconnecting with the same session id reclaims them
        self.lock_grace_seconds = lock_grace_seconds
        self._lock_grace: dict[int, float] = {}  # sid -> release deadline
        self.data_dir = data_dir
        # flight-recorder incidents (breached-SLO trace captures) live
        # beside the metadata image
        self.slo.recorder.set_dir(os.path.join(data_dir, "incidents"))
        self.meta = MetadataStore()
        self.changelog = Changelog(data_dir)
        self.goals = goals or geometry.default_goals()
        self.cs_links: dict[int, _CsLink] = {}
        # last health snapshot each chunkserver folded into a heartbeat
        # (CstomaHeartbeat.health_json) — aggregated by cluster_health()
        self.cs_health: dict[int, dict] = {}
        # tape server links (matotsserv.cc analog): ts_id -> writer/label
        self.ts_links: dict[int, dict] = {}
        self._next_ts_id = 1
        # inodes whose tape copies are missing/stale: inode -> (length,
        # mtime, gen) content stamp at enqueue; live-master queue
        # (rebuilt by a scan when a tape server registers)
        self.tape_pending: dict[int, tuple[int, int, int]] = {}
        self._tape_inflight: set[int] = set()
        # lifecycle tiering (S3 gateway / ROADMAP 3): inodes the
        # lifecycle scanner wants archived even without a $tape goal —
        # _tape_missing_labels treats membership as one wildcard copy.
        # Derived state (the scanner re-queues each pass), not persisted.
        self.tape_force: set[int] = set()
        # demoted inodes mid-recall: inode -> Future resolving to a
        # status code. While an inode is here the demoted write guard
        # stands down FOR THE RECALLING TAPE SERVER'S SESSION only
        # (_recall_sids; 0 = legacy peer without a session id =
        # permissive); reads stay refused until recall completes.
        self._recall_inflight: dict[int, asyncio.Future] = {}
        self._recall_sids: dict[int, int] = {}
        self.shadow_writers: list[asyncio.StreamWriter] = []
        self.sessions: dict[int, dict] = {}
        # per-session op accounting (runtime/accounting.py): every
        # client RPC charges its originating session's labeled
        # latency/byte cells; `lizardfs-admin top` renders the rollup
        self.session_ops = accounting.SessionOps(self.metrics, "master")
        # gateway-pushed workload summaries (CltomaSessionStats):
        # sid -> {"ts": epoch, ...gateway stats doc}
        self.session_stats: dict[int, dict] = {}
        # orphaned lock owners (no live connection) first seen at ts;
        # released after _ORPHAN_LOCK_TIMEOUT (promotion leaves locks of
        # sessions that never reconnect)
        self._orphan_lock_seen: dict[int, float] = {}
        # pending (blocked) lock requests are live-master-only: entries
        # {kind, sid, token, start, end, ltype} keyed by inode; held
        # locks live in self.meta.locks (changelog-replicated)
        self._pending_locks: dict[int, list[dict]] = {}
        self._session_writers: dict[int, asyncio.StreamWriter] = {}
        # cache-invalidation watch set (matoclserv.cc analog): which
        # sessions recently located chunks OR read attrs/access
        # decisions of an inode; mutations — data writes, truncates,
        # and metadata changes (chmod/setattr/seteattr/ACLs) — push
        # MatoclCacheInvalidate to them, so cross-gateway permission
        # revocation doesn't wait out META_TTL_S.
        # inode -> {sid -> last watch refresh}
        self._read_watchers: dict[int, dict[int, float]] = {}
        # multi-tenant QoS (runtime/qos.py): sessions map to tenants at
        # registration (config-driven, QOS_CFG), the RPC loop sheds
        # over-budget tenants with transient BUSY replies, and the
        # data-plane config rides every heartbeat ack to chunkservers.
        # An unconfigured engine admits everything — QoS only bites on
        # clusters that armed rates/budgets (LZ_QOS=0 kills even that).
        self.qos_tenants = qosmod.TenantMap()
        self.qos = qosmod.FairShare()
        self.qos_doc: dict = {}  # the parsed QOS_CFG (admin-mutable)
        self._qos_cs_cache: tuple = ()  # (key, json) heartbeat-ack cache
        # per-class admission rates double as live tweaks (admin
        # `tweaks-set qos_locate_rate 2000` == admin `qos set`): the
        # hook writes through to the engine
        self._qos_rate_tweaks = {
            cls: self.tweaks.register(
                f"qos_{cls}_rate", 0.0,
                on_set=lambda v, c=cls: self.qos.set_rate(c, v),
            )
            for cls in qosmod.MASTER_RATE_CLASSES
        }
        # bumped whenever the session population (or a session's
        # tenant) changes: the heartbeat-ack qos push keys its cache on
        # (engine generation, this) instead of fingerprinting every
        # session per ack
        self._session_epoch = 0
        from lizardfs_tpu.master.exports import Exports, Topology

        self.exports = exports if exports is not None else Exports()
        self.topology = topology if topology is not None else Topology()
        self.health_interval = health_interval
        self.image_interval = image_interval
        self.lifecycle_interval = lifecycle_interval
        # lifecycle scan work caps: nodes visited / demotes committed
        # per tick — the scan must never own the loop. Oversized
        # buckets resume across ticks via the saved walk stacks.
        self.lifecycle_scan_budget = 10_000
        self.lifecycle_demote_budget = 256
        self._lifecycle_stacks: dict[int, list[int]] = {}
        # explicit rebuild scheduler (priority classes, token-bucket
        # throttle, progress/ETA) — the endangered FIFO feeds it, the
        # health tick launches what it admits (master/rebuild.py)
        self.rebuild = rebuild_mod.RebuildEngine(self.metrics, self.tweaks)
        # cluster heat map (master/heat.py): decayed per-chunk / inode /
        # server heavy-hitter sketch fed by client RPC charges, CS
        # heartbeat heat folds, and gateway stats pushes. The health
        # tick closes the loop: adaptive goal boosts (changelog ops),
        # load-weighted placement, and the SLO→QoS auto-arm below.
        self.heat = heatmod.HeatTracker(self.metrics, self.tweaks)
        # heat-armed QoS pressure: tenant -> (restore_weight, expiry).
        # The SLO breach hook halves an offender's fair-share weight;
        # the health tick restores it when the window expires.
        self._heat_qos_pressure: dict[str, tuple[float, float]] = {}
        self._slo_qos_last = 0.0  # rate limit on the auto-arm action
        # second auto-arm action beside the profiler (runtime/slo.py):
        # an SLO burn breach also squeezes the top-offending tenant
        self.slo.qos_arm = self._slo_qos_arm
        # repair-failure backoff: chunk_id -> monotonic deadline before
        # the next replicate attempt (a source at a stale version fails
        # fast, and retrying it at tick rate floods the log and the net)
        self._repl_fail_until: dict[int, float] = {}
        from lizardfs_tpu.master.tasks import TaskManager

        self.task_manager = TaskManager(self.commit)
        # global IO budget (bytes/s, 0 = unlimited) divided among the
        # sessions that renewed an allocation recently
        self.io_limit_bps = io_limit_bps
        # per-cgroup budgets (mfsiolimits.cfg analog, reference
        # src/mount/io_limit_group.cc + globaliolimits): group path ->
        # bytes/s; each group's budget is divided among the sessions
        # renewing UNDER that group. Takes precedence over io_limit_bps.
        self.io_limits = dict(io_limits or {})
        self.io_limit_subsystem = io_limit_subsystem
        # (sid, resolved group) -> last renew  (legacy global: group "")
        self._io_limited_sessions: dict[tuple[int, str], float] = {}
        # personality: "master" (active) or "shadow" (applies the
        # changelog stream from active_addr; promotable at runtime)
        # (src/master/personality.h:25-69 analog)
        self.personality = personality
        self.active_addr = active_addr
        self._shadow_task: asyncio.Task | None = None
        # shadow replication-lag tracking (active side): connected
        # shadows ack their applied changelog position (MltomaAck);
        # keyed by the stream writer so a dead link's entry dies with
        # its loop. Surfaced in cluster_health + the shadow_lag gauge.
        self.shadow_status: dict[int, dict] = {}
        # shadow side: True while the changelog follow link is up —
        # replica reads are refused without it (a cut-off shadow would
        # otherwise serve unbounded staleness behind a valid token)
        self._follow_connected = False
        self._last_shadow_ack = 0.0
        # passive chunkserver mirror connections (shadow side): closed
        # on promotion so chunkservers re-register command-capable
        self._mirror_cs_writers: set[asyncio.StreamWriter] = set()
        # cs_id -> the writer whose mirror loop currently owns that
        # server's registration (supersession guard for teardown)
        self._mirror_cs_owner: dict[int, asyncio.StreamWriter] = {}
        # autopilot failover: set by __main__ when this daemon runs an
        # ElectionNode (quorum membership); health/admin `ha` read it
        self.ha_controller = None
        # config file paths for SIGHUP / admin `reload` (cfg_reload
        # analog): keys "goals", "exports", "topology", "iolimits"
        self.config_paths = dict(config_paths or {})
        self.log = logging.getLogger("master")

    def reload(self, strict: bool = False) -> None:
        """SIGHUP / admin reload: re-read the runtime-reloadable config
        files (reference: cfg_reload + registered hooks — mfsgoals,
        mfsexports, mfstopology, iolimits). A file that fails to parse
        keeps its previous in-memory config (never half-apply).

        ``strict=True`` raises on the first bad file — the STARTUP
        loading path (__main__) runs the same code so boot and SIGHUP
        can never interpret a file differently."""
        reloaded, failed = [], []

        def attempt(key, fn):
            path = self.config_paths.get(key)
            if not path:
                return
            try:
                with open(path) as f:
                    fn(f.read())
                reloaded.append(key)
            except Exception:  # noqa: BLE001 — keep serving on bad config
                if strict:
                    raise
                self.log.exception("reload of %s (%s) failed", key, path)
                failed.append(key)

        def goals(text):
            self.goals = geometry.load_goal_config(text)

        def exports(text):
            from lizardfs_tpu.master.exports import Exports

            self.exports = Exports.load(text)

        def topology(text):
            from lizardfs_tpu.master.exports import Topology

            self.topology = Topology.load(text)

        def iolimits(text):
            from lizardfs_tpu.utils.io_limits import parse_limits_cfg

            self.io_limit_subsystem, self.io_limits = parse_limits_cfg(text)

        def qos_cfg(text):
            self._qos_apply_config(qosmod.parse_config(text))

        attempt("goals", goals)
        attempt("exports", exports)
        attempt("topology", topology)
        attempt("iolimits", iolimits)
        attempt("qos", qos_cfg)
        self._last_reload = {"reloaded": reloaded, "failed": failed}
        if reloaded or failed:
            self.log.info("config reload: ok=%s failed=%s", reloaded, failed)

    # --- lifecycle -----------------------------------------------------------

    async def setup(self) -> None:
        loaded = load_image(self.data_dir)
        start_version = 0
        if loaded is not None:
            start_version, doc = loaded
            self.meta.load_sections(doc)
            sess = doc.get("sessions", {})
            # legacy-image fallback only; the authoritative counter is
            # metadata's replicated next_session. O(1) digest fixup —
            # only the misc entity changes.
            old_misc = self.meta._entity_hash(("misc",))
            self.meta.next_session = max(
                self.meta.next_session, int(sess.get("next", 1))
            )
            self.meta._digest ^= old_misc ^ self.meta._entity_hash(("misc",))
            for sid, row in sess.get("known", {}).items():
                self.sessions[int(sid)] = {
                    "info": row.get("info", ""), "connected": False,
                }
        self.changelog.version = start_version
        replayed = 0
        for version, op in self.changelog.iter_entries(start_version):
            self.meta.apply(op)
            self.changelog.version = version
            replayed += 1
        if replayed:
            self.log.info("replayed %d changelog entries", replayed)
        self.changelog.open()
        self.add_timer(self.health_interval, self._health_tick)
        self.add_timer(self.image_interval, self._dump_image)
        self.add_timer(10.0, self._purge_trash)
        self.add_timer(0.05, self._task_tick)
        self.add_timer(1.0, self._lock_grace_sweep)
        self.add_timer(30.0, self._read_watcher_sweep)
        self.add_timer(1.0, self._tape_drain)
        # S3 lifecycle tiering scan (age-based demote to tape); the
        # kill switch is re-read per tick, so LZ_S3_LIFECYCLE=0 stops
        # new demotions without a restart
        self.add_timer(max(self.lifecycle_interval, 0.1),
                       self._lifecycle_tick)

    async def _task_tick(self) -> None:
        """Run a batch of background metadata jobs (TaskManager analog:
        long-running work in slices so client service never stalls)."""
        if self.is_active:
            self.task_manager.tick()

    shadow_verify_interval = 30.0

    async def start(self) -> None:
        await super().start()
        # standing derived chart: average chunk density across the fleet
        self.metrics.gauge("chunks")
        self.metrics.gauge("chunkservers_connected")
        self.metrics.define(
            "chunks_per_server", "chunks chunkservers_connected DIV"
        )
        if self.personality == "shadow":
            if self.active_addr is None:
                raise ValueError("shadow personality needs active_addr")
            self._shadow_task = self.spawn(self._shadow_follow())
            # divergence detection (filesystem_checksum analog): compare
            # whole-metadata digests with the active at equal versions.
            # spawn directly — add_timer only registers before start()
            self.spawn(self._run_timer(
                self.shadow_verify_interval, self._shadow_verify_checksum
            ))
            # periodic applied-position ack: an IDLE shadow at tip must
            # keep reporting (lag telemetry ages out otherwise — acks
            # also ride every applied line, throttled)
            self.spawn(self._run_timer(2.0, self._shadow_ack_tick))

    @property
    def is_active(self) -> bool:
        return self.personality == "master"

    async def teardown(self) -> None:
        await self._dump_image()
        self.changelog.close()

    # --- mutation helper --------------------------------------------------------

    def commit(self, op: dict) -> int:
        """Apply + changelog + broadcast to shadows. The one write path."""
        self.metrics.counter("metadata_ops").inc()
        self.metrics.counter(f"op.{op['op']}").inc()
        self.meta.apply(op)
        version = self.changelog.append(op)
        if self.shadow_writers:
            line = m.MatomlChangelogLine(version=version, line=json.dumps(op, sort_keys=True))
            dead = []
            for w in self.shadow_writers:
                try:
                    framing.write_message(w, line)
                except (ConnectionError, RuntimeError):
                    dead.append(w)
            for w in dead:
                self.shadow_writers.remove(w)
        self._tape_mark(op)
        return version

    async def _dump_image(self) -> None:
        version = self.changelog.version
        # persist session registry (sessions.mfs analog): ids survive a
        # master restart so reconnecting clients keep their session ids.
        # Only LIVE sessions are persisted — one-shot CLI sessions would
        # otherwise accumulate in every image forever.
        sessions_section = {
            "known": {
                str(sid): {"info": s.get("info", "")}
                for sid, s in self.sessions.items()
                if s.get("connected")
            },
        }
        # MetadataDumper analog (metadata_dumper.h:37): fork and let the
        # CHILD serialize the copy-on-write snapshot — the master's loop
        # blocks only for the fork itself (page-table copy), not for the
        # O(namespace) serialization. The fork happens synchronously
        # here, so the snapshot is consistent with `version`.
        ok = False
        try:
            pid = os.fork() if _fork_safe() else -1
        except OSError:
            pid = -1
        inc_digest = self.meta._digest
        if pid == 0:
            code = 1
            try:
                sections = self.meta.to_sections()
                sections["sessions"] = sessions_section
                save_image(self.data_dir, version, sections)
                # background checksum verification on the CO-W snapshot
                # (filesystem_checksum_background_updater analog): the
                # full recompute costs the child, not the serving loop
                code = 3 if self.meta.full_digest() != inc_digest else 0
            finally:
                os._exit(code)
        elif pid > 0:
            rc = await self._wait_child(pid, timeout=600.0)
            ok = rc in (0, 3)
            if rc == 3:
                self._handle_digest_drift(version)
            elif not ok:
                self.log.error("forked metadata dump failed (v%d)", version)
        else:
            # no fork (jax/torch threads live, or exotic platform):
            # serialize on the loop thread's snapshot, write off-loop.
            # The digest-drift verification the forked child performs
            # runs here too, at the same consistent point as the
            # serialization — but only on every Nth fallback dump: the
            # full recompute is a second O(namespace) stall on top of
            # to_sections(), and this path never serves production
            # masters (which stay jax-free and fork).
            sections = self.meta.to_sections()
            sections["sessions"] = sessions_section
            self._fallback_dump_n = getattr(self, "_fallback_dump_n", 0) + 1
            drifted = (
                self._fallback_dump_n % 8 == 1
                and self.meta.full_digest() != inc_digest
            )
            await asyncio.to_thread(save_image, self.data_dir, version, sections)
            ok = True
            if drifted:
                self._handle_digest_drift(version)
        if ok:
            self.changelog.rotate()
            self.changelog.open()

    def _handle_digest_drift(self, version: int) -> None:
        """Incremental digest no longer matches a full recompute: state
        was corrupted outside apply() or the incremental update has a
        bug. Log, count, and re-anchor to the full value."""
        self.log.error(
            "incremental metadata digest drift detected (v%d); "
            "re-anchoring", version,
        )
        self.metrics.counter("digest_drift").inc()
        self.meta.reset_digest()

    async def _wait_child(self, pid: int, timeout: float) -> int:
        """Reap a forked worker with a deadline: a child deadlocked by a
        lock some other thread held at fork time (the classic fork+
        threads hazard) must not stall dumps forever. Returns the exit
        code, or -1 on timeout/kill."""
        import signal

        deadline = time.monotonic() + timeout
        while True:
            wpid, status = os.waitpid(pid, os.WNOHANG)
            if wpid == pid:
                return os.waitstatus_to_exitcode(status)
            if time.monotonic() >= deadline:
                self.log.error("forked worker %d hung; killing", pid)
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
                await asyncio.to_thread(os.waitpid, pid, 0)
                return -1
            await asyncio.sleep(0.05)

    async def _lock_grace_sweep(self) -> None:
        """Release locks of sessions whose grace window expired without
        a reconnect (lock retention across brief disconnects)."""
        if not self.is_active:
            return
        now = time.monotonic()
        for sid, deadline in list(self._lock_grace.items()):
            if now < deadline:
                continue
            if self._session_writers.get(sid) is not None:
                # reconnected; shouldn't happen (register clears it)
                del self._lock_grace[sid]
                continue
            del self._lock_grace[sid]
            held = self.meta.locks.session_inodes(sid)
            if held:
                self.commit({"op": "lock_release_session", "sid": sid})
                for inode in held:
                    self._grant_pending_locks(inode)
            self._release_session_opens(sid)

    def _release_session_opens(self, sid: int) -> None:
        """Drop a departed session's open handles (freeing any sustained
        files it was the last holder of)."""
        if any(sid in refs for refs in self.meta.fs.open_refs.values()):
            self.commit({"op": "release_session_opens", "sid": sid})

    _ORPHAN_LOCK_TIMEOUT = 60.0

    async def _purge_trash(self) -> None:
        if not self.is_active:
            return
        now = int(time.time())
        expired = [
            i for i, entry in self.meta.fs.trash.items() if entry[1] <= now
        ]
        for inode in expired:
            self.commit({"op": "purge_trash", "inode": inode})
        # retire disconnected sessions (the in-memory registry would
        # otherwise grow with every one-shot CLI invocation)
        dead = [
            sid for sid, s in self.sessions.items()
            if not s.get("connected") and sid not in self._session_writers
        ]
        for sid in dead:
            del self.sessions[sid]
            # per-session accounting follows the session registry's
            # lifetime: rate windows + pushed gateway stats retire with
            # the session (labeled counters keep their totals)
            self.session_ops.retire(sid)
            self.session_stats.pop(sid, None)
        if dead:
            self._session_epoch += 1
        # release locks AND open handles whose owning session has no
        # live connection and never reconnected (orphans from a
        # promotion or client crash)
        owners = set()
        for table in (self.meta.locks.posix_files, self.meta.locks.flock_files):
            for fl in table.values():
                owners.update(r.owner.session_id for r in fl.ranges)
        for refs in self.meta.fs.open_refs.values():
            owners.update(refs)
        live = set(self._session_writers)
        now_f = time.time()
        for sid in owners - live:
            if sid in self._lock_grace:
                continue  # the grace sweep owns this session's fate
            first_seen = self._orphan_lock_seen.setdefault(sid, now_f)
            if now_f - first_seen >= self._ORPHAN_LOCK_TIMEOUT:
                held = self.meta.locks.session_inodes(sid)
                if held:
                    self.commit({"op": "lock_release_session", "sid": sid})
                self._release_session_opens(sid)
                self._orphan_lock_seen.pop(sid, None)
                for inode in held:
                    self._grant_pending_locks(inode)
        for sid in list(self._orphan_lock_seen):
            if sid in live or sid not in owners:
                del self._orphan_lock_seen[sid]

    # --- connection dispatch ------------------------------------------------------

    async def handle_connection(self, reader, writer) -> None:
        try:
            first = await framing.read_message(reader)
        except (asyncio.IncompleteReadError, ConnectionError):
            return
        if isinstance(first, m.CltomaRegister):
            await self._client_loop(reader, writer, first)
        elif isinstance(first, m.CstomaRegister):
            await self._cs_loop(reader, writer, first)
        elif isinstance(first, m.TstomaRegister):
            await self._ts_loop(reader, writer, first)
        elif isinstance(first, m.MltomaRegister):
            await self._shadow_loop(reader, writer, first)
        elif isinstance(first, (m.AdminInfo, m.AdminCommand)):
            admin_state: dict = {}
            await self._admin_message(writer, first, admin_state)
            while True:
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                await self._admin_message(writer, msg, admin_state)
        else:
            self.log.warning("unexpected first message %s", type(first).__name__)

    # --- client service (matoclserv analog) -----------------------------------------

    def _stamp_token(self, reply) -> None:
        """Stamp the consistency token (applied changelog position) on
        any reply carrying a trailing ``meta_version`` field — directly,
        or on its nested Attr (MatoclAttrReply's token rides the Attr
        tail). Read AFTER the op was handled, so a mutation's ack
        carries the version that includes it (read-your-writes through
        replicas)."""
        if reply is None:
            return
        target = reply if hasattr(reply, "meta_version") else getattr(
            reply, "attr", None
        )
        if target is not None and hasattr(target, "meta_version") \
                and not target.meta_version:
            target.meta_version = self.changelog.version

    async def _client_loop(self, reader, writer, first: m.CltomaRegister) -> None:
        if not self.is_active:
            if (
                getattr(first, "replica_ok", 0)
                and first.session_id
                and self.personality == "shadow"
                and shadow_reads_enabled()
            ):
                await self._replica_loop(reader, writer, first)
                return
            # clients cycle through master addresses until they find the
            # active one (modern replacement for the floating-IP dance)
            await framing.send_message(
                writer,
                m.MatoclRegister(
                    req_id=first.req_id, status=st.NOT_POSSIBLE, session_id=0
                ),
            )
            return
        if getattr(first, "replica_ok", 0):
            # replica-mode registrations must never become command
            # sessions (mirror of the mirror=1 guard on the cs side): a
            # promoted shadow would otherwise adopt a client's replica
            # REDIAL as the session's push link — superseding the real
            # primary writer, whose connection has the push handlers —
            # and lock-grant/invalidation pushes would be lost. Refuse;
            # the client's replica dial treats non-OK as "no replica
            # here" and its primary link is unaffected.
            await framing.send_message(
                writer,
                m.MatoclRegister(
                    req_id=first.req_id, status=st.NOT_POSSIBLE, session_id=0
                ),
            )
            return
        if self.observe_peer_epoch(getattr(first, "epoch", 0)):
            # the client has seen a newer master than us — we just
            # stepped down; refuse so it redials the address list
            await framing.send_message(
                writer,
                m.MatoclRegister(
                    req_id=first.req_id, status=st.NOT_POSSIBLE, session_id=0
                ),
            )
            return
        peer = writer.get_extra_info("peername") or ("127.0.0.1", 0)
        rule = self.exports.match(peer[0], getattr(first, "password", ""))
        if rule is None:
            await framing.send_message(
                writer,
                m.MatoclRegister(
                    req_id=first.req_id, status=st.EACCES, session_id=0
                ),
            )
            return
        root_inode = self._resolve_export_root(rule)
        if root_inode is None:
            await framing.send_message(
                writer,
                m.MatoclRegister(
                    req_id=first.req_id, status=st.ENOENT, session_id=0
                ),
            )
            return
        session_id = first.session_id or self.meta.next_session
        # replicate the allocation: a promoted shadow must never re-issue
        # an id whose locks are still held (and whose disconnect would
        # then release a stranger's locks)
        self.commit({"op": "session_new", "sid": session_id})
        self.sessions[session_id] = {
            "info": first.info, "connected": True, "ip": peer[0],
            "readonly": rule.readonly, "maproot": rule.maproot,
            "root": root_inode,
            # tenant identity is decided at registration (and
            # re-resolved when the QoS config reloads): admission, the
            # data-plane push, health, and `top` all read this label
            "tenant": self.qos_tenants.tenant_of(first.info, rule.path),
            "export": rule.path,
        }
        self._session_epoch += 1
        self._session_writers[session_id] = writer
        # reconnect within the grace window: the session keeps its locks
        self._lock_grace.pop(session_id, None)
        await framing.send_message(
            writer,
            m.MatoclRegister(
                req_id=first.req_id, status=st.OK, session_id=session_id,
                # seeds the client's monotonic-reads floor: a replica
                # must be at least this caught up to serve this client
                meta_version=self.changelog.version,
                # cluster fencing epoch: the client echoes its highest
                # observed value on every redial, so a zombie ex-primary
                # it lands on learns of the election and steps down
                epoch=self.meta.epoch,
            ),
        )
        try:
            while True:
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if not self.is_active:
                    # fenced/demoted mid-session (observe_peer_epoch or
                    # a lost election): stop serving writes NOW and
                    # close, so the client's redial loop finds the new
                    # active instead of a zombie merging late mutations
                    break
                # fair-share admission: an over-budget tenant's op is
                # shed with transient BUSY + retry hint BEFORE it costs
                # handler work. Off/unconfigured = these two checks.
                if constants_mod.qos_enabled() and self.qos.armed:
                    busy = self._qos_shed(session_id, msg)
                    if busy is not None:
                        await framing.send_message(writer, busy)
                        continue
                t0 = time.perf_counter()
                tw0 = time.time()
                try:
                    reply = await self._handle_client(msg, session_id)
                except fsmod.FsError as e:
                    reply = self._error_reply(msg, e.code)
                except Exception:
                    self.log.exception("client op %s failed", type(msg).__name__)
                    reply = self._error_reply(msg, st.EIO)
                # request_log.h analog: per-op-type latency histograms
                dt = time.perf_counter() - t0
                self.metrics.timing(type(msg).__name__).record(dt)
                # request-scoped tracing: RPCs carrying a trace id land
                # in the span ring (dumped via admin `trace-dump`)
                tid = getattr(msg, "trace_id", 0)
                self.trace_ring.record(
                    tid, type(msg).__name__, tw0, time.time(), role="master",
                )
                # per-session accounting: the same op charged to its
                # originating session (the `top` rollup's master leg)
                self.session_ops.record(
                    session_id, _op_class_of(msg), dt, trace_id=tid,
                )
                # SLO accounting: chunk grant/locate RPCs are the
                # master's latency-critical class — a slow one breaches
                # the "locate" objective and flight-records its trace
                if isinstance(msg, (m.CltomaReadChunk, m.CltomaWriteChunk,
                                    m.CltomaWriteChunkEnd,
                                    m.CltomaWriteChunkEndBatch)):
                    self.slo.observe(
                        "locate", dt, trace_id=tid,
                        name=type(msg).__name__,
                    )
                    # heat map, inode kind: the master-leg RPC charge
                    # carries latency + trace id so the hottest cell's
                    # heat_hot_ops histogram gets a drill-down exemplar
                    if constants_mod.heat_enabled():
                        inode = getattr(msg, "inode", 0)
                        if inode:
                            self.heat.charge(
                                "inode", inode, seconds=dt, trace_id=tid,
                            )
                if reply is not None:
                    self._stamp_token(reply)
                    await framing.send_message(writer, reply)
        finally:
            # a reconnected client may have superseded this connection
            # under the same session id — only the CURRENT connection may
            # tear the session down (otherwise the stale loop would
            # release locks the reconnected client still holds)
            if self._session_writers.get(session_id) is writer:
                self.sessions.get(session_id, {})["connected"] = False
                self._session_epoch += 1
                self._session_writers.pop(session_id, None)
                if self._stopping.is_set():
                    # master shutdown, not client departure: locks must
                    # survive the restart (the image is dumped next);
                    # the client reconnects with the same session id
                    return
                held = self.meta.locks.session_inodes(session_id)
                # queued (blocked) requests die with the connection —
                # there is nobody to push the grant to
                queued = [
                    i for i, q in self._pending_locks.items()
                    if any(p["sid"] == session_id for p in q)
                ]
                for q in self._pending_locks.values():
                    q[:] = [p for p in q if p["sid"] != session_id]
                for inode in queued:
                    self._grant_pending_locks(inode)
                clean = self.sessions.get(session_id, {}).get("clean_close")
                if held and clean:
                    # clean goodbye: release now
                    self.commit(
                        {"op": "lock_release_session", "sid": session_id}
                    )
                    for inode in held:
                        self._grant_pending_locks(inode)
                has_opens = any(
                    session_id in refs
                    for refs in self.meta.fs.open_refs.values()
                )
                if (held or has_opens) and not clean:
                    # abrupt disconnect: HELD locks and open handles get
                    # a grace window — a client that reconnects with its
                    # session id (network blip, failover) keeps them;
                    # the sweep releases both if it never comes back
                    self._lock_grace[session_id] = (
                        time.monotonic() + self.lock_grace_seconds
                    )
                if clean:
                    # open handles die with a clean goodbye
                    self._release_session_opens(session_id)

    # read-mostly RPCs a shadow replica serves; everything else gets
    # NOT_POSSIBLE so the client routes it to the primary. Mutations are
    # structurally impossible here: none of these handlers commit.
    # ONLY ops whose reply types carry a meta_version token belong here
    # (MatoclAttrReply/Readdir/Readlink/StatusReply/ReadChunk): a
    # tokenless reply can never pass the client's monotonic-reads floor
    # and would count a spurious stale retry on every call.
    _REPLICA_SERVABLE = (
        "CltomaLookup", "CltomaGetattr", "CltomaReaddir", "CltomaReadlink",
        "CltomaAccess", "CltomaReadChunk",
    )

    def _resolve_export_root(self, rule) -> "int | None":
        """Export subtree root inode for ``rule``, or None when the
        path does not (yet) resolve. ONE implementation shared by the
        primary client loop and the shadow replica loop — their views
        of the export subtree must never diverge."""
        if rule.path in ("/", ""):
            return fsmod.ROOT_INODE
        try:
            node = self.meta.fs.node(fsmod.ROOT_INODE)
            for comp in rule.path.strip("/").split("/"):
                node = self.meta.fs.lookup(node.inode, comp)
            return node.inode
        except fsmod.FsError:
            return None

    # --- multi-tenant QoS (fair-share admission) ---------------------------

    # completion/session verbs are never shed: WriteChunkEnd[Batch]
    # releases the chunk lock a granted write holds (shedding it would
    # convert admission pressure into lock pressure), and session
    # control fires once per mount, not on the request path
    _QOS_NEVER_SHED = frozenset({
        "CltomaWriteChunkEnd", "CltomaWriteChunkEndBatch", "CltomaGoodbye",
        "CltomaRegister", "CltomaIoLimitRequest", "CltomaSessionStats",
        "CltomaOpen", "CltomaRelease",
    })

    def _qos_admission_class(self, msg) -> "str | None":
        """Admission op class of a client RPC (one vocabulary with the
        chunkserver data plane), or None for ops QoS never sheds."""
        name = type(msg).__name__
        if name in self._QOS_NEVER_SHED:
            return None
        if name == "CltomaReadChunk":
            return "locate"
        if name == "CltomaWriteChunk":
            return "write"
        if name == "CltomaLockOp" and getattr(msg, "ltype", -1) == \
                LOCK_UNLOCK:
            # lock RELEASES are never shed (same reason as
            # WriteChunkEnd: shedding a release converts admission
            # pressure into lock pressure for every waiter, including
            # other tenants — cross-tenant priority inversion)
            return None
        if name in _OP_CLASS_READ:
            return "meta_read"
        return "meta_write"

    def _qos_apply_config(self, doc: dict) -> None:
        """Install a parsed QoS config (startup, SIGHUP, admin `qos`):
        tenant mapping + admission engine + the doc the heartbeat-ack
        push to chunkservers is built from. Tweak mirrors stay in sync
        so `tweaks` output never lies about a live rate."""
        self.qos_doc = doc
        self.qos_tenants = qosmod.TenantMap.from_config(doc)
        self.qos.configure(doc)
        self._qos_cs_cache = ()
        for cls, tweak in self._qos_rate_tweaks.items():
            tweak.value = self.qos.rates.get(cls, 0.0)
        # re-resolve live sessions against the NEW match rules: a
        # SIGHUP that moves a client between tenants must bite without
        # waiting for that client to reconnect
        for sess in self.sessions.values():
            sess["tenant"] = self.qos_tenants.tenant_of(
                str(sess.get("info", "")), str(sess.get("export", ""))
            )
        self._session_epoch += 1

    def _qos_shed(self, session_id: int, msg) -> "m.MatoclStatusReply | None":
        """Admission check for one client RPC: None = admitted, else
        the BUSY reply to send (shed, with the backoff hint). The
        LZ_QOS=0 / unconfigured path is the caller's two checks and
        nothing else."""
        cls = self._qos_admission_class(msg)
        if cls is None:
            return None
        tenant = self.sessions.get(session_id, {}).get(
            "tenant", qosmod.DEFAULT_TENANT
        )
        retry_ms = self.qos.admit(tenant, cls)
        if retry_ms is None:
            return None
        self.metrics.labeled_counter(
            "qos_shed", {"tenant": tenant, "op": cls},
            help="client RPCs shed with BUSY by fair-share admission, "
                 "by tenant and op class",
        ).inc()
        return m.MatoclStatusReply(
            req_id=getattr(msg, "req_id", 0), status=st.BUSY,
            retry_after_ms=retry_ms,
        )

    def _qos_cs_json(self) -> str:
        """The QoS data-plane config chunkservers apply, refreshed on
        every heartbeat ack: session->tenant map, tenant weights, the
        in-flight byte budget, and optional per-session native-plane
        pacing. Empty string when QoS is off/unconfigured (the ack is
        byte-identical to the pre-QoS one). Cached until the engine
        generation or session population changes."""
        if not constants_mod.qos_enabled():
            return ""
        doc = self.qos_doc
        inflight_mb = float(doc.get("data_inflight_mb", 0) or 0)
        data_bps = float(doc.get("data_bps", 0) or 0)
        if inflight_mb <= 0 and data_bps <= 0:
            return ""
        key = (self.qos.generation, self._session_epoch)
        if self._qos_cs_cache and self._qos_cs_cache[0] == key:
            return self._qos_cs_cache[1]
        tenants = {
            sid: s.get("tenant", qosmod.DEFAULT_TENANT)
            for sid, s in self.sessions.items() if s.get("connected")
        }
        weights = dict(self.qos.weights)
        out = {
            "gen": self.qos.generation,
            "tenants": {str(sid): t for sid, t in tenants.items()},
            "weights": weights,
            "inflight_mb": inflight_mb,
            "rebuild_weight": float(doc.get("rebuild_weight", 1.0)),
        }
        if data_bps > 0:
            # approximate native-plane pacing: the total data rate
            # split by tenant weight across connected tenants, each
            # session paced at its tenant's share (the asyncio DRR is
            # the precise enforcement; this bounds the C++ fast path)
            active = {tenants[sid] for sid in tenants}
            total_w = sum(
                weights.get(t, 1.0) for t in active
            ) or 1.0
            out["session_bps"] = {
                str(sid): int(
                    data_bps * weights.get(t, 1.0) / total_w
                )
                for sid, t in tenants.items()
            }
        text = json.dumps(out, sort_keys=True)
        self._qos_cs_cache = (key, text)
        return text

    # --- cluster heat loop (master/heat.py) --------------------------------

    def _heat_tick(self) -> None:
        """The heat loop's control leg, riding the health tick: decay
        the sketch, commit goal boosts/demotes for chunks crossing the
        thresholds (hysteresis lives in heat.boost_decisions), refresh
        the load-weighted placement inputs, and expire heat-armed QoS
        pressure."""
        registry = self.meta.registry
        now = time.monotonic()
        enabled = constants_mod.heat_enabled()
        # expire armed QoS pressure even when the switch just went off:
        # LZ_HEAT=0 must never leave a tenant squeezed forever
        for tenant, (restore, until) in list(
            self._heat_qos_pressure.items()
        ):
            if now >= until or not enabled:
                del self._heat_qos_pressure[tenant]
                self.qos.set_weight(tenant, restore)
        if not enabled:
            if registry.server_load:
                # revert placement to pure free-space weighting
                registry.server_load = {}
            return
        self.heat.tick(now)
        # observatory-driven placement: new-chunk server selection
        # weighs observed load — per-server heat share + heartbeat
        # health status + DRR queue depth (queued data-plane bytes)
        waiting: dict[int, float] = {}
        for cs_id, snap in self.cs_health.items():
            q = (snap or {}).get("qos") or {}
            w = q.get("waiting")
            if isinstance(w, dict):
                waiting[cs_id] = float(sum(w.values()))
            elif w:
                try:
                    waiting[cs_id] = float(w)
                except (TypeError, ValueError):
                    pass
        registry.server_load = self.heat.server_loads(
            self.cs_health, waiting
        )
        # adaptive replication: boost chunks whose decayed heat crossed
        # heat_boost_bytes, demote once it falls below heat_demote_bytes
        # — via digest-covered changelog ops so shadows and the image
        # agree; the extra copies are made/shed by the ordinary
        # RebuildEngine machinery under its token-bucket budget
        boosted = {
            cid: registry.chunks[cid].boost
            for cid in registry.boosted if cid in registry.chunks
        }
        to_boost, to_demote = self.heat.boost_decisions(boosted)
        for cid in to_demote:
            self.commit({"op": "goal_demote", "chunk_id": cid})
            self.log.info("heat: goal demote chunk %d", cid)
        for cid, copies in to_boost:
            if cid not in registry.chunks:
                continue
            self.commit({
                "op": "goal_boost", "chunk_id": cid, "boost": copies,
            })
            # wake the health walk on it now, not a cursor cycle later
            registry.mark_endangered(cid)
            self.log.info(
                "heat: goal boost chunk %d (+%d copies)", cid, copies
            )

    def _slo_qos_arm(self, op_class: str, trace_id: int) -> None:
        """Second SLO auto-arm action (beside the profiler): burn-rate
        breach → squeeze the top-offending tenant's fair-share weight
        for a window. Rate-limited, reversible (the health tick
        restores the weight), and inert unless both LZ_HEAT and LZ_QOS
        are on and QoS is actually armed."""
        if not constants_mod.heat_enabled():
            return
        if not constants_mod.qos_enabled() or not self.qos.armed:
            return
        now = time.monotonic()
        if now - self._slo_qos_last < 30.0:
            return
        # top offender: the highest-rate session's tenant right now
        tenant = ""
        for row in self.session_ops.top(4):
            label = row["session"]
            if not label.startswith("s"):
                continue  # "other"/aggregate rows have no tenant
            try:
                sid = int(label[1:])
            except ValueError:
                continue
            tenant = self.sessions.get(sid, {}).get("tenant", "")
            if tenant:
                break
        if not tenant or tenant in self._heat_qos_pressure:
            return
        self._slo_qos_last = now
        current = self.qos.weights.get(tenant, 1.0)
        self._heat_qos_pressure[tenant] = (current, now + 30.0)
        self.qos.set_weight(tenant, current / 2.0)
        self.metrics.labeled_counter(
            "slo_qos_armed", {"tenant": tenant, "op": op_class},
            help="SLO burn-rate breaches that auto-armed QoS pressure "
                 "(halved fair-share weight for a window), by offending "
                 "tenant and breaching op class",
        ).inc()
        self.log.warning(
            "slo breach (%s, trace 0x%x): qos pressure armed on tenant "
            "%s for 30s", op_class, trace_id, tenant,
        )

    def _replica_ready(self) -> bool:
        """A shadow serves replica reads only while its changelog follow
        link is live — a partitioned shadow would otherwise serve
        unbounded staleness behind a formally valid token."""
        return (
            self.personality == "shadow"
            and self._follow_connected
            and shadow_reads_enabled()
        )

    async def _replica_loop(
        self, reader, writer, first: m.CltomaRegister
    ) -> None:
        """Shadow-side client service: consistency-tokened read replica.

        The session id was issued (and committed) by the primary — the
        shadow accepts it without a commit of its own (shadows never
        write the changelog) and serves ONLY _REPLICA_SERVABLE ops, each
        reply stamped with the applied changelog position. The client
        enforces monotonic reads against that token and retries through
        the primary on staleness (client/client.py _call_read)."""
        peer = writer.get_extra_info("peername") or ("127.0.0.1", 0)
        rule = self.exports.match(peer[0], getattr(first, "password", ""))
        if rule is None or not self._replica_ready():
            await framing.send_message(
                writer,
                m.MatoclRegister(
                    req_id=first.req_id,
                    status=st.EACCES if rule is None else st.NOT_POSSIBLE,
                    session_id=0,
                ),
            )
            return
        root_inode = self._resolve_export_root(rule)
        if root_inode is None:
            # the exported subtree may not have replicated yet —
            # refuse; the client stays primary-only and retries the
            # replica link later
            await framing.send_message(
                writer,
                m.MatoclRegister(
                    req_id=first.req_id, status=st.ENOENT, session_id=0
                ),
            )
            return
        session_id = first.session_id
        entry = {
            "info": first.info, "connected": True, "ip": peer[0],
            "readonly": True, "maproot": rule.maproot, "root": root_inode,
            "replica": True,
            # the client appends "/replica" to its info; prefix rules
            # still match, so both legs land on the same tenant
            "tenant": self.qos_tenants.tenant_of(first.info, rule.path),
        }
        self.sessions[session_id] = entry
        self._session_epoch += 1
        await framing.send_message(
            writer,
            m.MatoclRegister(
                req_id=first.req_id, status=st.OK, session_id=session_id,
                meta_version=self.changelog.version,
                # shadow's replayed fencing epoch: the client adopts it
                # and presents it on its next primary (re)dial, so a
                # zombie ex-primary is fenced even by clients that only
                # ever reached this replica after the election
                epoch=self.meta.epoch,
            ),
        )
        served = self.metrics.counter(
            "shadow_reads",
            help="read RPCs served by this shadow in replica mode",
        )
        try:
            while True:
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if isinstance(msg, m.CltomaGoodbye):
                    reply = m.MatoclStatusReply(
                        req_id=msg.req_id, status=st.OK
                    )
                elif (
                    type(msg).__name__ not in self._REPLICA_SERVABLE
                    or not self._replica_ready()
                ):
                    # promoted mid-session, kill switch flipped, or an
                    # op outside the allowlist: the client reroutes to
                    # the primary (its own conn fails over if WE are
                    # the new primary)
                    reply = self._error_reply(msg, st.NOT_POSSIBLE)
                elif constants_mod.qos_enabled() and self.qos.armed and (
                    (busy := self._qos_shed(session_id, msg)) is not None
                ):
                    # locate storms shed per-tenant on replicas too —
                    # one scanner must not starve the fleet's locates
                    # through the shadow either. BUSY (not
                    # NOT_POSSIBLE) so the client backs off and retries
                    # instead of dropping the replica link.
                    reply = busy
                else:
                    t0 = time.perf_counter()
                    try:
                        reply = await self._handle_client(msg, session_id)
                        served.inc()
                    except fsmod.FsError as e:
                        reply = self._error_reply(msg, e.code)
                    except Exception:
                        self.log.exception(
                            "replica op %s failed", type(msg).__name__
                        )
                        reply = self._error_reply(msg, st.EIO)
                    dt = time.perf_counter() - t0
                    self.metrics.timing(type(msg).__name__).record(dt)
                    # replica-served reads charge the same session the
                    # primary would (the shadow's own registry; the
                    # client never double-counts — fallbacks re-enter
                    # the primary loop which records there instead)
                    self.session_ops.record(
                        session_id, _op_class_of(msg), dt,
                        trace_id=getattr(msg, "trace_id", 0),
                    )
                if reply is not None:
                    self._stamp_token(reply)
                    await framing.send_message(writer, reply)
        finally:
            # supersession guard (mirror of _client_loop's `is writer`
            # check): a half-open old replica connection must not
            # delete the session entry a REDIALED replica loop (or a
            # post-promotion command registration) installed for the
            # same id — ops running against a missing entry would skip
            # the export-subtree remap entirely
            if self.sessions.get(session_id) is entry:
                del self.sessions[session_id]
                self._session_epoch += 1

    def _error_reply(self, msg, code: int):
        if isinstance(msg, (m.CltomaReadChunk,)):
            return m.MatoclReadChunk(
                req_id=msg.req_id, status=code, chunk_id=0, version=0,
                file_length=0, locations=[],
            )
        if isinstance(msg, (m.CltomaWriteChunk,)):
            return m.MatoclWriteChunk(
                req_id=msg.req_id, status=code, chunk_id=0, version=0,
                file_length=0, locations=[],
            )
        if isinstance(msg, m.CltomaReaddir):
            return m.MatoclReaddir(req_id=msg.req_id, status=code, entries=[])
        if isinstance(msg, m.CltomaReadlink):
            return m.MatoclReadlink(req_id=msg.req_id, status=code, target="")
        if isinstance(msg, m.CltomaGetXattr):
            return m.MatoclXattrReply(req_id=msg.req_id, status=code, value=b"")
        if isinstance(msg, m.CltomaListXattr):
            return m.MatoclListXattr(req_id=msg.req_id, status=code, names=[])
        if isinstance(msg, m.CltomaGetQuota):
            return m.MatoclQuotaReply(req_id=msg.req_id, status=code, json="[]")
        if isinstance(msg, m.CltomaLockOp):
            return m.MatoclLockReply(req_id=msg.req_id, status=code)
        if isinstance(msg, m.CltomaTrashList):
            return m.MatoclTrashList(req_id=msg.req_id, status=code, json="[]")
        if isinstance(msg, m.CltomaFileRepair):
            return m.MatoclFileRepair(req_id=msg.req_id, status=code, json="{}")
        if isinstance(
            msg,
            (m.CltomaLookup, m.CltomaGetattr, m.CltomaMkdir, m.CltomaCreate,
             m.CltomaSetattr, m.CltomaSymlink, m.CltomaLink, m.CltomaSnapshot,
             m.CltomaAppendChunks),
        ):
            return m.MatoclAttrReply(
                req_id=msg.req_id, status=code, attr=_null_attr()
            )
        return m.MatoclStatusReply(req_id=msg.req_id, status=code)

    def _io_limit_share(self, session_id: int, group: str, bps: int) -> int:
        """Equal share of ``group``'s budget among its sessions that
        renewed in the last 5 s (globaliolimits allocation model)."""
        mono = time.monotonic()
        self._io_limited_sessions[(session_id, group)] = mono
        self._io_limited_sessions = {
            k: ts for k, ts in self._io_limited_sessions.items()
            if mono - ts < 5.0
        }
        n = sum(1 for (_sid, g) in self._io_limited_sessions if g == group)
        return bps // max(n, 1)

    def _check_quota(self, dir_inode: int, uid: int, gid: int,
                     d_inodes: int, d_bytes: int) -> None:
        """Raise QUOTA_EXCEEDED if hard limits forbid the addition."""
        if not self.meta.quotas.check(uid, gid, d_inodes, d_bytes):
            raise fsmod.FsError(st.QUOTA_EXCEEDED, f"uid {uid}/gid {gid}")
        # directory quotas along the ancestor chain
        fs = self.meta.fs
        cur = dir_inode
        hops = 0
        while cur and hops < 4096:
            entry = self.meta.quotas.entry(KIND_DIR, cur)
            node = fs.nodes.get(cur)
            if node is None:
                break
            if entry is not None and not self.meta.quotas.check_dir(
                (node.stat_inodes, node.stat_bytes), entry, d_inodes, d_bytes
            ):
                raise fsmod.FsError(st.QUOTA_EXCEEDED, f"dir {cur}")
            if cur == fsmod.ROOT_INODE or not node.parents:
                break
            cur = node.parents[0]
            hops += 1

    def _owns(self, node, uid: int) -> bool:
        """Ownership test for owner-gated ops (setgoal/seteattr/...):
        root, the owner, or anyone when the inode carries
        EATTR_NOOWNER (the flag makes every uid act as the owner)."""
        from lizardfs_tpu.constants import EATTR_NOOWNER

        return uid == 0 or uid == node.uid or bool(node.eattr & EATTR_NOOWNER)

    def _access_ok(self, node, uid: int, gids: list[int], want: int) -> bool:
        """One permission decision for every call site: RichACL if set,
        else mode bits + POSIX ACL. EATTR_NOOWNER short-circuits to the
        owner's view for every caller."""
        from lizardfs_tpu.constants import EATTR_NOOWNER

        if node.eattr & EATTR_NOOWNER and uid != 0:
            # evaluate as if the caller were the owner (mode/ACL owner
            # entries apply); root keeps its usual path below
            uid = node.uid
            gids = [node.gid]
        if node.rich_acl is not None:
            from lizardfs_tpu.master.richacl import RichAcl

            return RichAcl.from_dict(node.rich_acl).check_access(
                node.uid, node.gid, uid, gids, want, mode=node.mode
            )
        from lizardfs_tpu.master import acl as acl_mod

        a = acl_mod.Acl.from_dict(node.acl) if node.acl else None
        return acl_mod.check_access(
            node.mode, node.uid, node.gid, a, uid, gids, want
        )

    def _check_perm(self, node, uid: int, gids: list[int], want: int) -> None:
        if not self._access_ok(node, uid, gids, want):
            raise fsmod.FsError(st.EACCES, f"inode {node.inode}")

    def _grant_pending_locks(self, inode: int) -> None:
        queue = self._pending_locks.get(inode)
        if not queue:
            self._pending_locks.pop(inode, None)
            return
        still = []
        for p in queue:
            if self._lock_conflict(inode, p) is None:
                self._commit_lock(inode, p)
                w = self._session_writers.get(p["sid"])
                if w is not None:
                    try:
                        framing.write_message(
                            w,
                            m.MatoclLockGranted(inode=inode, token=p["token"]),
                        )
                    except (ConnectionError, RuntimeError):
                        pass
            else:
                still.append(p)
        if still:
            self._pending_locks[inode] = still
        else:
            self._pending_locks.pop(inode, None)

    def _lock_conflict(self, inode: int, p: dict):
        if p["ltype"] == LOCK_UNLOCK:
            return None
        if p["kind"] == "flock":
            return self.meta.locks.test_flock(
                inode, p["sid"], p["token"], p["ltype"]
            )
        return self.meta.locks.test(
            inode, p["sid"], p["token"], p["start"], p["end"], p["ltype"]
        )

    def _commit_lock(self, inode: int, p: dict) -> None:
        if p["kind"] == "flock":
            self.commit({
                "op": "lock_flock", "inode": inode, "sid": p["sid"],
                "token": p["token"], "ltype": p["ltype"],
            })
        else:
            self.commit({
                "op": "lock_posix", "inode": inode, "sid": p["sid"],
                "token": p["token"], "start": p["start"], "end": p["end"],
                "ltype": p["ltype"],
            })

    _MUTATING = (
        "CltomaMkdir", "CltomaCreate", "CltomaSymlink", "CltomaLink",
        "CltomaUnlink", "CltomaRmdir", "CltomaRename", "CltomaSetGoal",
        "CltomaSetattr", "CltomaTruncate", "CltomaWriteChunk",
        "CltomaWriteChunkEnd", "CltomaWriteChunkEndBatch",
        "CltomaSnapshot", "CltomaSetXattr",
        "CltomaSetQuota", "CltomaUndelete", "CltomaSetAcl",
        "CltomaSetRichAcl", "CltomaSetEattr", "CltomaFileRepair",
        "CltomaAppendChunks", "CltomaTapeDemote",
    )

    _INODE_FIELDS = ("parent", "inode", "parent_src", "parent_dst",
                     "dst_parent", "src_inode")

    def _in_subtree(self, inode: int, root: int) -> bool:
        """Is ``inode`` reachable under ``root``? Walks all parent
        chains (hardlinks may have several)."""
        if root == fsmod.ROOT_INODE or inode == root:
            return True
        seen: set[int] = set()
        frontier = [inode]
        for _ in range(4096):
            if not frontier:
                return False
            nxt: list[int] = []
            for i in frontier:
                if i == root:
                    return True
                node = self.meta.fs.nodes.get(i)
                if node is None:
                    continue
                for p in node.parents:
                    if p not in seen:
                        seen.add(p)
                        nxt.append(p)
            frontier = nxt
        return False

    def _apply_session_view(self, msg, session: dict) -> bool:
        """Subtree exports + root squash: remap the client's root inode
        to the exported directory, refuse inodes outside the exported
        subtree, squash root uids to maproot. False = access denied."""
        root = session.get("root", fsmod.ROOT_INODE)
        if root != fsmod.ROOT_INODE:
            for field in self._INODE_FIELDS:
                if getattr(msg, field, None) == fsmod.ROOT_INODE:
                    setattr(msg, field, root)
            for field in self._INODE_FIELDS:
                value = getattr(msg, field, None)
                if value is not None and not self._in_subtree(value, root):
                    return False
        maproot = session.get("maproot")
        if maproot is not None:
            # Squash caller IDENTITY fields only.  CltomaSetattr carries
            # caller identity in caller_uid/caller_gids while its uid/gid
            # are the chown TARGET — those must pass through untouched
            # (the squashed caller is then not root and the handler
            # denies the chown).
            scalars = (("caller_uid",) if isinstance(msg, m.CltomaSetattr)
                       else ("uid", "gid", "caller_uid"))
            for field in scalars:
                if getattr(msg, field, None) == 0:
                    setattr(msg, field, maproot)
            for field in ("gids", "caller_gids"):
                vals = getattr(msg, field, None)
                if vals:
                    setattr(msg, field,
                            [maproot if v == 0 else v for v in vals])
        return True

    async def _handle_client(self, msg, session_id: int = 0):
        fs = self.meta.fs
        now = int(time.time())
        session = self.sessions.get(session_id, {})
        if session:
            if session.get("readonly") and type(msg).__name__ in self._MUTATING:
                return self._error_reply(msg, st.EROFS)
            if not self._apply_session_view(msg, session):
                return self._error_reply(msg, st.EACCES)
        if isinstance(msg, m.CltomaGoodbye):
            if session:
                session["clean_close"] = True
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaSessionStats):
            # gateway workload summary push: folded into the `top`
            # rollup under this session (bounded: one doc per live
            # session, swept with the session registry)
            try:
                doc = json.loads(msg.stats_json) if msg.stats_json else {}
                if not isinstance(doc, dict):
                    raise ValueError("stats doc must be an object")
            except ValueError:
                return m.MatoclStatusReply(
                    req_id=msg.req_id, status=st.EINVAL
                )
            doc["ts"] = time.time()
            self.session_stats[session_id] = doc
            # gateway heat leg: pushes may carry a "hot" table of
            # [inode, ops, bytes] rows (protocol gateways serve data
            # without per-inode master RPCs, so this is the only way
            # their traffic reaches the heat map)
            if constants_mod.heat_enabled():
                for row in doc.get("hot") or ():
                    try:
                        ino, ops, nbytes = (
                            int(row[0]), float(row[1]), float(row[2])
                        )
                    except (TypeError, ValueError, IndexError):
                        continue
                    self.heat.charge("inode", ino, ops=ops, nbytes=nbytes)
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaLookup):
            self._check_perm(fs.dir_node(msg.parent), msg.uid, list(msg.gids), 1)
            if msg.name in (".", ".."):
                # NFS/FUSE path walking.  ".." clamps at the session's
                # export root so a subtree export can't be escaped.
                node = fs.dir_node(msg.parent)
                sroot = session.get("root", fsmod.ROOT_INODE)
                if msg.name == ".." and node.inode != sroot and node.parents:
                    node = fs.node(node.parents[0])
                return self._attr_reply(msg.req_id, node)
            node = fs.lookup(msg.parent, msg.name)
            return self._attr_reply(msg.req_id, node)
        if isinstance(msg, m.CltomaGetattr):
            # attr readers join the invalidation-watch set: gateways
            # cache attr/access decisions off this reply, and a later
            # chmod/seteattr via ANOTHER session must push them stale
            # (cross-gateway revocation no longer waits out META_TTL_S)
            self._note_watcher(msg.inode, session_id)
            return self._attr_reply(msg.req_id, fs.node(msg.inode))
        if isinstance(msg, m.CltomaTapeInfo):
            node = fs.node(msg.inode)
            want_stamp = self._content_stamp(msg.inode, node)
            stamp_fresh = [
                c for c in self.meta.tape_copies.get(msg.inode, [])
                if (c["length"], c["mtime"], c.get("gen", 0)) == want_stamp
            ]
            doc = {
                "wanted": self._goal_tape_copies(node.goal),
                "pending": msg.inode in self.tape_pending,
                "copies": self.meta.tape_copies.get(msg.inode, []),
                "fresh": len(stamp_fresh),
                # lifecycle tiering state: tape-only / restore running /
                # archive forced by the scanner without a $tape goal
                "demoted": msg.inode in self.meta.demoted,
                "recalling": msg.inode in self._recall_inflight,
                "forced": msg.inode in self.tape_force,
            }
            return m.MatoclTapeInfoReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
            )
        if isinstance(msg, m.CltomaTapeDemote):
            node = fs.file_node(msg.inode)
            self._check_perm(node, msg.uid, list(msg.gids), 2)
            return m.MatoclStatusReply(
                req_id=msg.req_id, status=self._try_demote(msg.inode, now)
            )
        if isinstance(msg, m.CltomaTapeRecall):
            fs.file_node(msg.inode)  # must exist and be a file
            if msg.inode not in self.meta.demoted:
                return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
            try:
                code = await retrymod.bounded_wait(
                    asyncio.shield(self._ensure_recall(msg.inode)), 120.0
                )
            except asyncio.TimeoutError:
                code = st.TIMEOUT  # the recall task itself keeps going
            return m.MatoclStatusReply(req_id=msg.req_id, status=code)
        if isinstance(msg, m.CltomaStatFs):
            # the space sum is O(servers) — memoize briefly so a statfs
            # storm against a 10k-chunkserver master stays O(1) per call
            # (space figures move at heartbeat pace anyway)
            mono = time.monotonic()
            cached = getattr(self, "_statfs_cache", None)
            if cached is None or mono - cached[0] > 2.0:
                servers = self.meta.registry.connected_servers()
                cached = (
                    mono,
                    sum(s.total_space for s in servers),
                    sum(s.free_space for s in servers),
                )
                self._statfs_cache = cached
            return m.MatoclStatFsReply(
                req_id=msg.req_id, status=st.OK, total_space=cached[1],
                avail_space=cached[2], inodes=len(fs.nodes),
            )
        if isinstance(msg, m.CltomaChunkDamaged):
            # client-side CRC rejection: the named holder's copy of the
            # part is bad. Volatile-registry handling identical to a
            # chunkserver scrubber report — drop the part and queue the
            # chunk through the RebuildEngine's endangered feed. The
            # file itself stays readable (the client already recovered
            # via decode); this report is what closes the loop from
            # detection to re-replication.
            srv = self.meta.registry.server_at(msg.host, msg.port)
            if srv is not None:
                self.meta.registry.drop_part(
                    msg.chunk_id, srv.cs_id, msg.part_id
                )
                self.meta.registry.mark_endangered(msg.chunk_id)
                self.log.warning(
                    "client reported damaged chunk %016X part %d on "
                    "cs %d (%s:%d)", msg.chunk_id, msg.part_id,
                    srv.cs_id, msg.host, msg.port,
                )
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaMkdir):
            self._check_perm(fs.dir_node(msg.parent), msg.uid, [msg.gid], 2 | 1)
            self._check_quota(msg.parent, msg.uid, msg.gid, 1, 0)
            inode = fs.alloc_inode()
            self.commit({
                "op": "mknode", "parent": msg.parent, "name": msg.name,
                "inode": inode, "ftype": fsmod.TYPE_DIR, "mode": msg.mode,
                "uid": msg.uid, "gid": msg.gid, "ts": now, "goal": 1,
                "trash_time": 86400,
            })
            return self._attr_reply(msg.req_id, fs.node(inode))
        if isinstance(msg, m.CltomaCreate):
            self._check_perm(fs.dir_node(msg.parent), msg.uid, [msg.gid], 2 | 1)
            self._check_quota(msg.parent, msg.uid, msg.gid, 1, 0)
            parent_goal = fs.dir_node(msg.parent).goal
            inode = fs.alloc_inode()
            self.commit({
                "op": "mknode", "parent": msg.parent, "name": msg.name,
                "inode": inode, "ftype": fsmod.TYPE_FILE, "mode": msg.mode,
                "uid": msg.uid, "gid": msg.gid, "ts": now, "goal": parent_goal,
                "trash_time": 86400,
            })
            return self._attr_reply(msg.req_id, fs.node(inode))
        if isinstance(msg, m.CltomaSymlink):
            self._check_perm(fs.dir_node(msg.parent), msg.uid, [msg.gid], 2 | 1)
            self._check_quota(msg.parent, msg.uid, msg.gid, 1, 0)
            inode = fs.alloc_inode()
            self.commit({
                "op": "mknode", "parent": msg.parent, "name": msg.name,
                "inode": inode, "ftype": fsmod.TYPE_SYMLINK, "mode": 0o777,
                "uid": msg.uid, "gid": msg.gid, "ts": now, "goal": 1,
                "trash_time": 0, "symlink_target": msg.target,
            })
            return self._attr_reply(msg.req_id, fs.node(inode))
        if isinstance(msg, m.CltomaReadlink):
            node = fs.node(msg.inode)
            if node.ftype != fsmod.TYPE_SYMLINK:
                return m.MatoclReadlink(req_id=msg.req_id, status=st.EINVAL, target="")
            return m.MatoclReadlink(
                req_id=msg.req_id, status=st.OK, target=node.symlink_target
            )
        if isinstance(msg, m.CltomaLink):
            target = fs.file_node(msg.inode)
            self._check_perm(fs.dir_node(msg.parent), msg.uid, list(msg.gids), 2 | 1)
            self._check_quota(msg.parent, target.uid, target.gid, 1, target.length)
            self.commit({
                "op": "link", "inode": msg.inode, "parent": msg.parent,
                "name": msg.name, "ts": now,
            })
            return self._attr_reply(msg.req_id, fs.node(msg.inode))
        if isinstance(msg, m.CltomaReaddir):
            node = fs.dir_node(msg.inode)
            self._check_perm(node, msg.uid, list(msg.gids), 4)
            entries = [
                m.DirEntry(name=name, inode=i, ftype=fs.node(i).ftype)
                for name, i in sorted(node.children.items())
            ]
            return m.MatoclReaddir(req_id=msg.req_id, status=st.OK, entries=entries)
        if isinstance(msg, m.CltomaUnlink):
            self._check_perm(fs.dir_node(msg.parent), msg.uid, list(msg.gids), 2 | 1)
            self.commit({
                "op": "unlink", "parent": msg.parent, "name": msg.name,
                "ts": now, "to_trash": True,
            })
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaRmdir):
            self._check_perm(fs.dir_node(msg.parent), msg.uid, list(msg.gids), 2 | 1)
            self.commit({"op": "rmdir", "parent": msg.parent, "name": msg.name, "ts": now})
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaRename):
            ident = (msg.uid, list(msg.gids))
            self._check_perm(fs.dir_node(msg.parent_src), *ident, 2 | 1)
            self._check_perm(fs.dir_node(msg.parent_dst), *ident, 2 | 1)
            self.commit({
                "op": "rename", "parent_src": msg.parent_src,
                "name_src": msg.name_src, "parent_dst": msg.parent_dst,
                "name_dst": msg.name_dst, "ts": now,
            })
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaSetGoal):
            if msg.goal not in self.goals:
                return m.MatoclStatusReply(req_id=msg.req_id, status=st.EINVAL)
            node = fs.node(msg.inode)
            if not self._owns(node, msg.uid):
                raise fsmod.FsError(st.EPERM, "setgoal requires ownership")
            self.commit({"op": "setgoal", "inode": msg.inode, "goal": msg.goal, "ts": now})
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaSetEattr):
            from lizardfs_tpu import constants as consts

            if msg.eattr & ~sum(consts.EATTR_NAMES.values()):
                return m.MatoclStatusReply(req_id=msg.req_id, status=st.EINVAL)
            node = fs.node(msg.inode)
            if not self._owns(node, msg.uid):
                raise fsmod.FsError(st.EPERM, "seteattr requires ownership")
            self.commit({
                "op": "seteattr", "inode": msg.inode, "eattr": msg.eattr,
                "ts": now,
            })
            # eattr flags gate client/gateway caching decisions: push
            # the change so another gateway's cached attr snapshot (and
            # the decisions derived from it) drops NOW, not at TTL
            # expiry (ADVICE r05 #4 residual)
            self._invalidate_client_caches(msg.inode, exclude_sid=session_id)
            return self._attr_reply(msg.req_id, fs.node(msg.inode))
        if isinstance(msg, m.CltomaSetattr):
            node = fs.node(msg.inode)
            caller = getattr(msg, "caller_uid", 0)
            if caller != 0:
                if msg.set_mask & (2 | 4):
                    # chown/chgrp are root-only
                    raise fsmod.FsError(st.EPERM, "chown requires root")
                if not self._owns(node, caller):
                    # mode/times/trash-time changes need ownership
                    raise fsmod.FsError(st.EPERM, f"inode {msg.inode}")
            self.commit({
                "op": "setattr", "inode": msg.inode, "set_mask": msg.set_mask,
                "mode": msg.mode, "uid": msg.uid, "gid": msg.gid,
                "atime": msg.atime, "mtime": msg.mtime, "ts": now,
                "trash_time": msg.trash_time,
            })
            # metadata mutation push (ADVICE r05 #4 residual): a chmod/
            # chown through THIS session must revoke other gateways'
            # cached attr/access decisions immediately — before this,
            # cross-gateway permission revocation lagged by META_TTL_S
            self._invalidate_client_caches(msg.inode, exclude_sid=session_id)
            return self._attr_reply(msg.req_id, fs.node(msg.inode))
        if isinstance(msg, m.CltomaTruncate):
            self._check_perm(fs.file_node(msg.inode), msg.uid, list(msg.gids), 2)
            if (msg.inode in self.meta.demoted
                    and not self._recall_writer_ok(msg.inode, session_id)):
                # tape-only content must be recalled before reshaping it
                return self._error_reply(msg, st.TAPE_RECALL)
            self.commit({"op": "set_length", "inode": msg.inode,
                         "length": msg.length, "ts": now})
            self._invalidate_client_caches(msg.inode, exclude_sid=session_id)
            return self._attr_reply(msg.req_id, fs.node(msg.inode))
        if isinstance(msg, m.CltomaOpen):
            node = fs.node(msg.inode)
            if node.ftype == fsmod.TYPE_FILE and session_id:
                # dedupe on (session, handle): the client RPC layer
                # retries over reconnects and acquire isn't idempotent
                handles = session.setdefault("open_handles", set())
                key = (msg.inode, msg.handle)
                if key not in handles:
                    handles.add(key)
                    self.commit({
                        "op": "acquire", "inode": msg.inode,
                        "sid": session_id,
                    })
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaRelease):
            if session_id and session_id in self.meta.fs.open_refs.get(
                msg.inode, {}
            ):
                handles = session.setdefault("open_handles", set())
                key = (msg.inode, msg.handle)
                # release a registered handle exactly once; an UNKNOWN
                # handle (master restarted since the open: the in-memory
                # handle set died with the old process) still releases —
                # the persisted ref must be droppable after recovery
                if key in handles or not any(
                    i == msg.inode for i, _ in handles
                ):
                    handles.discard(key)
                    self.commit({
                        "op": "release", "inode": msg.inode,
                        "sid": session_id,
                    })
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaReadChunk):
            return await self._read_chunk(msg, session.get("ip"), session_id)
        if isinstance(msg, m.CltomaWriteChunk):
            return await self._write_chunk(msg, session_id)
        if isinstance(msg, m.CltomaWriteChunkEnd):
            # invalidate FIRST and unconditionally: even a failed write
            # (non-OK status, or quota raise below) may have overwritten
            # chunkserver blocks already — a spurious push only costs
            # the readers a refetch
            self._invalidate_client_caches(
                msg.inode, msg.chunk_index, exclude_sid=session_id
            )
            return await self._write_chunk_end(msg)
        if isinstance(msg, m.CltomaWriteChunkEndBatch):
            # coalesced commit: seal every chunk the client's write
            # window finished since its last flush — one round trip
            # instead of one per chunk. Entries apply IN ORDER; the
            # first failure's status is reported, later VALID entries
            # still apply (their bytes are already on the chunkservers
            # and their locks must not outlive the batch). Entries
            # refused by the subtree check are NOT applied at all —
            # like the single-RPC path's EACCES, an unauthorized end
            # must not unlock a chunk some other client may be
            # writing; its lock expires by timeout.
            status = st.OK
            root = session.get("root", fsmod.ROOT_INODE)
            for e in msg.ends:
                if root != fsmod.ROOT_INODE and not self._in_subtree(
                    e.inode, root
                ):
                    # nested inodes bypass _apply_session_view's field
                    # remap — enforce the subtree export here
                    if status == st.OK:
                        status = st.EACCES
                    continue
                self._invalidate_client_caches(
                    e.inode, e.chunk_index, exclude_sid=session_id
                )
                try:
                    self._apply_write_chunk_end(
                        e.chunk_id, e.inode, e.file_length, e.status
                    )
                except fsmod.FsError as err:
                    if status == st.OK:
                        status = err.code
            return m.MatoclStatusReply(req_id=msg.req_id, status=status)
        if isinstance(msg, m.CltomaSnapshot):
            # no invalidation needed: a snapshot lands on a NEW inode
            # (apply_snapshot raises EEXIST on an existing name), so no
            # client can hold cached blocks for it
            return await self._snapshot(msg, now)
        if isinstance(msg, m.CltomaFileRepair):
            return self._file_repair(msg, now)
        if isinstance(msg, m.CltomaAppendChunks):
            return self._append_chunks(msg, now)
        if isinstance(msg, m.CltomaSetXattr):
            import base64

            self._check_perm(fs.node(msg.inode), msg.uid, list(msg.gids), 2)
            self.commit({
                "op": "set_xattr", "inode": msg.inode, "name": msg.name,
                "value": base64.b64encode(msg.value).decode(), "ts": now,
            })
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaGetXattr):
            node = fs.node(msg.inode)
            self._check_perm(node, msg.uid, list(msg.gids), 4)
            if msg.name not in node.xattrs:
                return m.MatoclXattrReply(
                    req_id=msg.req_id, status=st.ENOATTR, value=b""
                )
            return m.MatoclXattrReply(
                req_id=msg.req_id, status=st.OK, value=node.xattrs[msg.name]
            )
        if isinstance(msg, m.CltomaListXattr):
            node = fs.node(msg.inode)
            return m.MatoclListXattr(
                req_id=msg.req_id, status=st.OK, names=sorted(node.xattrs)
            )
        if isinstance(msg, m.CltomaSetQuota):
            if msg.uid != 0:
                raise fsmod.FsError(st.EPERM, "setquota requires root")
            self.commit({
                "op": "set_quota", "kind": msg.kind, "owner_id": msg.owner_id,
                "soft_inodes": msg.soft_inodes, "hard_inodes": msg.hard_inodes,
                "soft_bytes": msg.soft_bytes, "hard_bytes": msg.hard_bytes,
                "remove": msg.remove,
            })
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaGetQuota):
            rows = []
            gidset = set(msg.gids) if msg.uid != 0 else frozenset()
            for (kind, oid), e in sorted(self.meta.quotas.entries.items()):
                node = fs.nodes.get(oid) if kind == KIND_DIR else None
                if msg.uid != 0:
                    # non-root sees only its own rows: its user quota,
                    # its groups' quotas, and dir quotas it owns
                    if not (
                        (kind == KIND_USER and oid == msg.uid)
                        or (kind == KIND_GROUP and oid in gidset)
                        or (node is not None and node.uid == msg.uid)
                    ):
                        continue
                row = {"kind": kind, "id": oid, **e.to_dict()}
                if node is not None:
                    row["used_inodes"] = node.stat_inodes
                    row["used_bytes"] = node.stat_bytes
                rows.append(row)
            return m.MatoclQuotaReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(rows)
            )
        if isinstance(msg, m.CltomaLockOp):
            return self._lock_op(msg, session_id)
        if isinstance(msg, m.CltomaSetAcl):
            try:
                payload = json.loads(msg.json)
            except ValueError:
                return m.MatoclStatusReply(req_id=msg.req_id, status=st.EINVAL)
            from lizardfs_tpu.master.acl import Acl

            for key in ("access", "default"):
                if payload.get(key) is not None:
                    Acl.from_dict(payload[key])  # validate shape
            node = fs.node(msg.inode)
            caller = getattr(msg, "uid", 0)
            if caller != 0 and caller != node.uid:
                raise fsmod.FsError(st.EPERM, "setfacl requires ownership")
            self.commit({
                "op": "set_acl", "inode": msg.inode,
                "access": payload.get("access"),
                "default": payload.get("default"), "ts": now,
            })
            # ACL changes revoke permissions like a chmod does: push
            self._invalidate_client_caches(msg.inode, exclude_sid=session_id)
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaSetRichAcl):
            from lizardfs_tpu.master.richacl import RichAcl

            try:
                payload = json.loads(msg.json) if msg.json else None
                racl = None
                if payload is not None:
                    if not isinstance(payload, dict) or not isinstance(
                        payload.get("aces"), list
                    ):
                        raise ValueError("acl payload must be {aces: [...]}")
                    racl = RichAcl.from_dict(payload)
            except (ValueError, KeyError, TypeError, AttributeError):
                return m.MatoclStatusReply(req_id=msg.req_id, status=st.EINVAL)
            node = fs.node(msg.inode)
            caller = getattr(msg, "uid", 0)
            if caller != 0 and caller != node.uid:
                raise fsmod.FsError(st.EPERM, "setrichacl requires ownership")
            self.commit({
                "op": "set_rich_acl", "inode": msg.inode,
                # normalized form only — never persist unvalidated keys
                "acl": racl.to_dict() if racl is not None else None,
                "ts": now,
            })
            self._invalidate_client_caches(msg.inode, exclude_sid=session_id)
            if racl is not None:
                # publish the ACL's per-class grant unions as the mode
                # (richacl_compute_max_masks analog) so the mode masks
                # do not immediately cap a freshly set ACL
                o, g, oth = racl.compute_max_masks(node.uid)
                new_mode = (node.mode & ~0o777) | (o << 6) | (g << 3) | oth
                if new_mode != node.mode:
                    self.commit({
                        "op": "setattr", "inode": msg.inode, "set_mask": 1,
                        "mode": new_mode, "uid": node.uid, "gid": node.gid,
                        "atime": node.atime, "mtime": node.mtime, "ts": now,
                    })
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        if isinstance(msg, m.CltomaGetRichAcl):
            node = fs.node(msg.inode)
            return m.MatoclAclReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({"rich": node.rich_acl}),
            )
        if isinstance(msg, m.CltomaGetAcl):
            node = fs.node(msg.inode)
            return m.MatoclAclReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({
                    "access": node.acl, "default": node.default_acl,
                    "mode": node.mode, "uid": node.uid, "gid": node.gid,
                }),
            )
        if isinstance(msg, m.CltomaAccess):
            from lizardfs_tpu.master import acl as acl_mod

            node = fs.node(msg.inode)
            # access decisions are cached gateway-side (NFS _access):
            # watch the session so a permission change pushes the
            # cached verdict stale instead of letting it ride the TTL
            self._note_watcher(msg.inode, session_id)
            ok = self._access_ok(node, msg.uid, list(msg.gids), msg.mask)
            return m.MatoclStatusReply(
                req_id=msg.req_id, status=st.OK if ok else st.EACCES
            )
        if isinstance(msg, m.CltomaIoLimitRequest):
            active = 1 if (self.io_limits or self.io_limit_bps > 0) else 0
            if getattr(msg, "probe", 0):
                # pure status query: answer limits_active without
                # registering the session in the allocation table
                return m.MatoclIoLimitReply(
                    req_id=msg.req_id, status=st.OK, bytes_per_sec=0,
                    renew_ms=10_000, subsystem=self.io_limit_subsystem,
                    limits_active=active,
                )
            if self.io_limits:
                # per-cgroup budgets: resolve the claimed group to its
                # closest configured ancestor, then share that group's
                # budget among the sessions renewing under it
                from lizardfs_tpu.utils.io_limits import (
                    UNCLASSIFIED, resolve_limit,
                )

                key, bps = resolve_limit(
                    msg.group or UNCLASSIFIED, self.io_limits
                )
                if bps <= 0:
                    return m.MatoclIoLimitReply(
                        req_id=msg.req_id, status=st.OK, bytes_per_sec=0,
                        renew_ms=10_000, subsystem=self.io_limit_subsystem,
                        limits_active=active,
                    )
                share = self._io_limit_share(session_id, key, bps)
                return m.MatoclIoLimitReply(
                    req_id=msg.req_id, status=st.OK, bytes_per_sec=share,
                    renew_ms=1000, subsystem=self.io_limit_subsystem,
                    limits_active=active,
                )
            if self.io_limit_bps <= 0:
                return m.MatoclIoLimitReply(
                    req_id=msg.req_id, status=st.OK, bytes_per_sec=0,
                    renew_ms=10_000, subsystem="", limits_active=0,
                )
            share = self._io_limit_share(session_id, "", self.io_limit_bps)
            return m.MatoclIoLimitReply(
                req_id=msg.req_id, status=st.OK, bytes_per_sec=share,
                renew_ms=1000, subsystem="", limits_active=1,
            )
        if isinstance(msg, m.CltomaTrashList):
            rows = [
                {"inode": inode, "name": name, "expires": exp, "parent": parent}
                for inode, (name, exp, parent) in sorted(fs.trash.items())
                if msg.uid == 0
                or (fs.nodes.get(inode) is not None
                    and fs.nodes[inode].uid == msg.uid)
            ]
            return m.MatoclTrashList(
                req_id=msg.req_id, status=st.OK, json=json.dumps(rows)
            )
        if isinstance(msg, m.CltomaUndelete):
            if msg.inode not in fs.trash:
                return m.MatoclStatusReply(req_id=msg.req_id, status=st.ENOENT)
            node = fs.nodes.get(msg.inode)
            # fail closed: an unresolvable trash entry is nobody's to restore
            if msg.uid != 0 and (node is None or msg.uid != node.uid):
                raise fsmod.FsError(st.EPERM, "undelete requires ownership")
            self.commit({"op": "undelete", "inode": msg.inode, "ts": now})
            return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)
        return m.MatoclStatusReply(req_id=getattr(msg, "req_id", 0), status=st.EINVAL)

    def _lock_op(self, msg: m.CltomaLockOp, session_id: int):
        inode, token = msg.inode, msg.token
        self.meta.fs.file_node(inode)  # must exist and be a file
        if msg.op == 2:  # test (F_GETLK); checks both spaces
            conflict = self.meta.locks.test(
                inode, session_id, token, msg.start, msg.end, msg.ltype
            ) or self.meta.locks.test_flock(
                inode, session_id, token, msg.ltype
            )
            return m.MatoclLockReply(
                req_id=msg.req_id,
                status=st.OK if conflict is None else st.LOCKED,
            )
        p = {
            "kind": "flock" if msg.op == 1 else "posix",
            "sid": session_id, "token": token,
            "start": msg.start, "end": msg.end, "ltype": msg.ltype,
        }
        if self._lock_conflict(inode, p) is None:
            self._commit_lock(inode, p)
            if msg.ltype == LOCK_UNLOCK:
                # an unlock also cancels this owner's queued requests in
                # the range (a waiter that gave up aborts cleanly)
                queue = self._pending_locks.get(inode, [])
                end = msg.end or MAX_OFFSET
                queue[:] = [
                    q for q in queue
                    if not (q["sid"] == session_id and q["token"] == token
                            and q["kind"] == p["kind"]
                            and (q["kind"] == "flock"
                                 or (q["start"] < end
                                     and msg.start < (q["end"] or MAX_OFFSET))))
                ]
            # any successful change can free capacity (full unlock, but
            # also downgrades and range narrowing) — retry waiters
            self._grant_pending_locks(inode)
            ok = True
        else:
            if msg.wait:
                self._pending_locks.setdefault(inode, []).append(p)
            ok = False
        return m.MatoclLockReply(
            req_id=msg.req_id, status=st.OK if ok else st.LOCKED
        )

    def _file_repair(self, msg: m.CltomaFileRepair, now: int):
        """`lizardfs filerepair` (file_repair.cc analog): walk the
        file's chunks; readable-but-degraded chunks route through the
        RebuildEngine (rebuilt, never zeroed), unreadable chunks are
        version-fixed from retained stale-version parts when coverage
        allows, and only truly unrecoverable chunks are zero-filled."""
        fs = self.meta.fs
        node = fs.file_node(msg.inode)
        if not self._owns(node, msg.uid):
            raise fsmod.FsError(st.EPERM, "filerepair requires ownership")
        registry = self.meta.registry
        counts = {"repaired_versions": 0, "zeroed": 0,
                  "queued_rebuild": 0, "ok_chunks": 0}
        mutated = False
        for idx, cid in enumerate(list(node.chunks)):
            if cid == 0:
                continue
            chunk = registry.chunks.get(cid)
            if chunk is None:
                # metadata references a chunk the registry no longer
                # knows — the slot can only be zero-filled
                self.commit({"op": "repair_zero_chunk",
                             "inode": msg.inode, "chunk_index": idx,
                             "ts": now})
                counts["zeroed"] += 1
                mutated = True
                continue
            state = registry.evaluate(chunk)
            if state.is_readable:
                if state.needs_work:
                    # repairable: rebuilt through the engine, not zeroed
                    registry.mark_endangered(cid)
                    counts["queued_rebuild"] += 1
                else:
                    counts["ok_chunks"] += 1
                continue
            if self._repair_chunk_version(chunk):
                counts["repaired_versions"] += 1
                registry.mark_endangered(cid)
                mutated = True
                continue
            self.commit({"op": "repair_zero_chunk", "inode": msg.inode,
                         "chunk_index": idx, "ts": now})
            counts["zeroed"] += 1
            mutated = True
        if mutated:
            self._invalidate_client_caches(msg.inode)
        return m.MatoclFileRepair(
            req_id=msg.req_id, status=st.OK, json=json.dumps(counts)
        )

    def _repair_chunk_version(self, chunk) -> bool:
        """Version-fix an unreadable chunk: adopt the newest retained
        stale version whose surviving parts restore readability
        (file_repair.cc correct-version mode). The parts are already on
        disk at that version, so adopting is pure metadata."""
        registry = self.meta.registry
        stale = registry.stale_versions.get(chunk.chunk_id)
        if not stale:
            return False
        t = geometry.SliceType(chunk.slice_type)
        need = 1 if t.is_standard else geometry.required_parts_to_recover(t)
        by_ver: dict[int, list[tuple[int, int]]] = {}
        for (cs_id, part_id), ver in stale.items():
            srv = registry.servers.get(cs_id)
            if srv is None or not srv.connected:
                continue
            cpt = geometry.ChunkPartType.from_id(part_id)
            if int(cpt.type) != chunk.slice_type:
                continue
            by_ver.setdefault(ver, []).append((cs_id, cpt.part))
        for ver in sorted(by_ver, reverse=True):
            if len({p for _, p in by_ver[ver]}) < need:
                continue
            # parts still registered at the CURRENT version become the
            # wrong-version ones after the adoption: unregister them
            # (a mixed-version location set would serve WRONG_VERSION
            # on reads while evaluate() counts the chunk healthy) and
            # retain them as stale material in their turn
            old_holders = set(chunk.parts)
            if old_holders:
                t_cur = geometry.SliceType(chunk.slice_type)
                registry.unregister_parts(chunk, old_holders)
                for cs_id, part in old_holders:
                    registry.record_stale(
                        chunk.chunk_id, cs_id,
                        geometry.ChunkPartType(t_cur, part).id,
                        chunk.version,
                    )
            self.commit({"op": "bump_chunk_version",
                         "chunk_id": chunk.chunk_id, "version": ver})
            for cs_id, part in by_ver[ver]:
                registry.record_part(chunk, cs_id, part)
            for key in [k for k, v in stale.items() if v == ver]:
                del stale[key]
            if not stale:
                registry.stale_versions.pop(chunk.chunk_id, None)
            self.log.info(
                "filerepair: chunk %d version-fixed to v%d (%d parts)",
                chunk.chunk_id, ver, len(by_ver[ver]),
            )
            return True
        return False

    def _append_chunks(self, msg: m.CltomaAppendChunks, now: int):
        """`lizardfs appendchunks` (append_file.cc analog): O(1)
        concatenation — dst is padded to a chunk boundary and src's
        chunks are SHARED onto its tail through the snapshot refcount
        machinery; a later write to either side COWs the chunk."""
        fs = self.meta.fs
        src = fs.file_node(msg.inode_src)
        dst = fs.file_node(msg.inode_dst)
        if msg.inode_src == msg.inode_dst:
            return self._error_reply(msg, st.EINVAL)
        ident = (msg.uid, list(msg.gids))
        self._check_perm(src, *ident, 4)
        self._check_perm(dst, *ident, 2)
        if (msg.inode_src in self.meta.demoted
                or msg.inode_dst in self.meta.demoted):
            # a demoted side holds no chunks to share: concat would
            # fabricate holes where tape-only bytes belong
            return self._error_reply(msg, st.TAPE_RECALL)
        padded = (
            (dst.length + MFSCHUNKSIZE - 1) // MFSCHUNKSIZE * MFSCHUNKSIZE
        )
        parent = dst.parents[0] if dst.parents else fsmod.ROOT_INODE
        self._check_quota(
            parent, dst.uid, dst.gid, 0, padded + src.length - dst.length
        )
        # a write in flight on EITHER file must not race the concat:
        # a locked chunk is mid-mutation, and a dst chunk attached past
        # the length-implied boundary is a concurrent write that
        # WriteChunkEnd has not sealed yet — the padding would land on
        # top of it (set_length's "never drop chunks" invariant)
        if len(dst.chunks) > (
            (dst.length + MFSCHUNKSIZE - 1) // MFSCHUNKSIZE
        ):
            return self._error_reply(msg, st.CHUNK_BUSY)
        for cid in (*src.chunks, *dst.chunks):
            chunk = self.meta.registry.chunks.get(cid) if cid else None
            if chunk is not None and chunk.locked_until > time.monotonic():
                return self._error_reply(msg, st.CHUNK_BUSY)
        self.commit({"op": "append_chunks", "inode_dst": msg.inode_dst,
                     "inode_src": msg.inode_src, "ts": now})
        self._invalidate_client_caches(msg.inode_dst, exclude_sid=None)
        return self._attr_reply(msg.req_id, fs.node(msg.inode_dst))

    async def _snapshot(self, msg: m.CltomaSnapshot, now: int):
        fs = self.meta.fs
        src = fs.node(msg.src_inode)
        ident = (getattr(msg, "uid", 0), list(getattr(msg, "gids", [0])))
        self._check_perm(src, *ident, 4)
        if self.meta.demoted:
            # a demoted file in the subtree holds no chunks to share —
            # its clone would silently read zeros; recall first
            stack = [src.inode]
            while stack:
                cur = stack.pop()
                if cur in self.meta.demoted:
                    return self._error_reply(msg, st.TAPE_RECALL)
                n = fs.nodes.get(cur)
                if n is not None and n.ftype == fsmod.TYPE_DIR:
                    stack.extend(n.children.values())
        self._check_perm(fs.dir_node(msg.dst_parent), *ident, 2 | 1)
        wi, wb = fs._node_weight(src)
        self._check_quota(msg.dst_parent, src.uid, src.gid, wi, wb)
        # pre-assign all clone inodes so replay is deterministic
        inode_map: dict[str, int] = {}

        def assign(node):
            inode_map[str(node.inode)] = fs.alloc_inode()
            if node.ftype == fsmod.TYPE_DIR:
                for child in sorted(node.children.values()):
                    assign(fs.node(child))

        assign(src)
        self.commit({
            "op": "snapshot", "src_inode": msg.src_inode,
            "dst_parent": msg.dst_parent, "dst_name": msg.dst_name,
            "inode_map": inode_map, "ts": now,
        })
        return self._attr_reply(
            msg.req_id, fs.node(inode_map[str(msg.src_inode)])
        )

    def _attr_reply(self, req_id: int, node) -> m.MatoclAttrReply:
        return m.MatoclAttrReply(req_id=req_id, status=st.OK, attr=_attr_of(node))

    def _locations_of(self, chunk, client_ip: str | None = None) -> list[m.PartLocation]:
        """Part locations, same-rack servers first (topology read
        locality, topology.h:25 analog)."""
        t = geometry.SliceType(chunk.slice_type)
        rows = []
        for cs_id, part in sorted(chunk.parts):
            srv = self.meta.registry.servers.get(cs_id)
            if srv is None or not srv.connected:
                continue
            dist = (
                self.topology.distance(client_ip, srv.host)
                if client_ip else 0
            )
            # equal part+distance replicas rank by observed load (heat
            # share + queue depth + health): readers drain toward the
            # cold copy a goal boost just created instead of piling
            # onto the server that made the chunk hot. server_load is
            # empty with LZ_HEAT off, keeping the pre-heat ordering.
            load = self.meta.registry.server_load.get(cs_id, 0.0)
            rows.append((part, dist, load, srv))
        rows.sort(key=lambda r: (r[0], r[1], r[2]))
        return [
            m.PartLocation(
                addr=m.Addr(host=srv.host, port=srv.data_addr_port),
                part_id=geometry.ChunkPartType(t, part).id,
            )
            for part, _, _, srv in rows
        ]

    # how long a locate keeps a session subscribed to invalidations;
    # must exceed the client cache TTL (3 s) so every cache fast-path
    # hit is covered by a still-live watch
    CACHE_WATCH_TTL = 60.0

    async def _read_watcher_sweep(self) -> None:
        """Expire idle watch subscriptions — without this, one dict
        entry per inode ever read would accumulate for the master's
        lifetime."""
        now = time.monotonic()
        for inode in list(self._read_watchers):
            watchers = self._read_watchers[inode]
            for sid in [
                s for s, ts in watchers.items()
                if now - ts > self.CACHE_WATCH_TTL
                or s not in self._session_writers
            ]:
                del watchers[sid]
            if not watchers:
                del self._read_watchers[inode]

    def _note_watcher(self, inode: int, session_id: int) -> None:
        """Subscribe a session to ``inode``'s invalidation pushes (it
        just read something cacheable about the inode: chunk
        locations, attrs, or an access verdict)."""
        if session_id:
            self._read_watchers.setdefault(inode, {})[session_id] = (
                time.monotonic()
            )

    def _invalidate_client_caches(
        self, inode: int, chunk_index: int = 0xFFFFFFFF,
        exclude_sid: int | None = None,
    ) -> None:
        """Push MatoclCacheInvalidate to every session that recently
        located chunks of ``inode``, except the mutator (its own cache
        was already updated client-side). Reference analog:
        src/master/matoclserv.cc data-cache invalidation."""
        watchers = self._read_watchers.get(inode)
        if not watchers:
            return
        now = time.monotonic()
        dead = []
        for sid, ts in watchers.items():
            if now - ts > self.CACHE_WATCH_TTL:
                dead.append(sid)
                continue
            if sid == exclude_sid:
                continue
            w = self._session_writers.get(sid)
            if w is None:
                dead.append(sid)
                continue
            try:
                framing.write_message(
                    w,
                    m.MatoclCacheInvalidate(
                        inode=inode, chunk_index=chunk_index,
                        # raises the watcher's monotonic-reads floor so
                        # its next read can't be served pre-mutation by
                        # a lagging replica
                        meta_version=self.changelog.version,
                    ),
                )
            except (ConnectionError, RuntimeError):
                dead.append(sid)
        for sid in dead:
            watchers.pop(sid, None)
        if not watchers:
            self._read_watchers.pop(inode, None)

    async def _read_chunk(
        self, msg: m.CltomaReadChunk, client_ip: str | None = None,
        session_id: int = 0,
    ):
        node = self.meta.fs.file_node(msg.inode)
        self._check_perm(node, msg.uid, list(msg.gids), 4)
        if msg.inode in self.meta.demoted:
            # tape-only data: kick the recall (idempotent single-flight)
            # and refuse with the transient status — a reader that
            # waits (CltomaTapeRecall) or simply retries later succeeds
            # once the archive streamed back
            self._ensure_recall(msg.inode)
            return m.MatoclReadChunk(
                req_id=msg.req_id, status=st.TAPE_RECALL, chunk_id=0,
                version=0, file_length=node.length, locations=[],
            )
        self._note_watcher(msg.inode, session_id)
        chunk_id = (
            node.chunks[msg.chunk_index] if msg.chunk_index < len(node.chunks) else 0
        )
        if chunk_id == 0:
            # hole: no chunk — client reads zeros
            return m.MatoclReadChunk(
                req_id=msg.req_id, status=st.OK, chunk_id=0, version=0,
                file_length=node.length, locations=[],
            )
        chunk = self.meta.registry.chunk(chunk_id)
        # heat map, chunk kind, ops only: the real byte weight arrives
        # via chunkserver heartbeat folds — this keeps a hot chunk
        # tracked even between folds
        if constants_mod.heat_enabled():
            self.heat.charge("chunk", chunk_id)
        return m.MatoclReadChunk(
            req_id=msg.req_id, status=st.OK, chunk_id=chunk_id,
            version=chunk.version, file_length=node.length,
            locations=self._locations_of(chunk, client_ip),
        )

    async def _write_chunk(self, msg: m.CltomaWriteChunk,
                           session_id: int = 0):
        node = self.meta.fs.file_node(msg.inode)
        self._check_perm(node, msg.uid, list(msg.gids), 2)
        if (msg.inode in self.meta.demoted
                and not self._recall_writer_ok(msg.inode, session_id)):
            # tape-only file: recall before mutating (only the
            # recalling tape server's session may write mid-restore)
            return m.MatoclWriteChunk(
                req_id=msg.req_id, status=st.TAPE_RECALL, chunk_id=0,
                version=0, file_length=0, locations=[],
            )
        chunk_id = (
            node.chunks[msg.chunk_index] if msg.chunk_index < len(node.chunks) else 0
        )
        if chunk_id == 0:
            return await self._create_new_chunk(msg, node)
        chunk = self.meta.registry.chunk(chunk_id)
        if constants_mod.heat_enabled():
            # chunk-kind heat, ops only (bytes ride the CS folds)
            self.heat.charge("chunk", chunk_id)
        if chunk.locked_until > time.monotonic():
            return m.MatoclWriteChunk(
                req_id=msg.req_id, status=st.CHUNK_BUSY, chunk_id=0, version=0,
                file_length=0, locations=[],
            )
        if chunk.refcount > 1:
            # snapshot-shared chunk: copy-on-write before mutating
            return await self._cow_chunk(msg, node, chunk)
        # version bump so stale copies are detectable (chunk lock + bump,
        # matoclserv.cc fuse_write_chunk semantics)
        new_version = chunk.version + 1
        holders = sorted(chunk.parts)
        t = geometry.SliceType(chunk.slice_type)
        acks = []
        for cs_id, part in holders:
            link = self.cs_links.get(cs_id)
            if link is None:
                acks.append((cs_id, part, None))
                continue
            acks.append((
                cs_id, part,
                link.command(
                    m.MatocsSetVersion,
                    chunk_id=chunk_id,
                    old_version=chunk.version,
                    new_version=new_version,
                    part_id=geometry.ChunkPartType(t, part).id,
                ),
            ))
        ok_holders: list[tuple[int, int]] = []
        live = [(cs_id, part, coro) for cs_id, part, coro in acks
                if coro is not None]
        replies = await asyncio.gather(
            *(coro for _, _, coro in live), return_exceptions=True
        )
        for (cs_id, part, _), reply in zip(live, replies):
            if isinstance(reply, (ConnectionError, asyncio.TimeoutError)):
                continue  # missed the bump: dropped as stale below
            if isinstance(reply, BaseException):
                raise reply  # protocol/programming error: surface it
            if reply.status == st.OK:
                ok_holders.append((cs_id, part))
        if not ok_holders:
            return m.MatoclWriteChunk(
                req_id=msg.req_id, status=st.NO_CHUNK_SERVERS, chunk_id=0,
                version=0, file_length=0, locations=[],
            )
        # copies that missed the bump are stale: unregister them so the
        # reply's locations are all at new_version, and queue re-repair
        stale = chunk.parts - set(ok_holders)
        if stale:
            self.meta.registry.unregister_parts(chunk, stale)
            self.meta.registry.mark_endangered(chunk_id)
        self.commit({
            "op": "bump_chunk_version", "chunk_id": chunk_id, "version": new_version,
        })
        chunk.locked_until = time.monotonic() + CHUNK_LOCK_SECONDS
        return m.MatoclWriteChunk(
            req_id=msg.req_id, status=st.OK, chunk_id=chunk_id,
            version=new_version, file_length=node.length,
            locations=self._locations_of(chunk),
        )

    async def _cow_chunk(self, msg: m.CltomaWriteChunk, node, chunk):
        """Duplicate a snapshot-shared chunk on its part holders, point
        the file at the private copy, then grant the write on it."""
        new_id = self.meta.registry.next_chunk_id
        self.meta.registry.next_chunk_id = new_id + 1
        t = geometry.SliceType(chunk.slice_type)
        version = 1
        acks = []
        for cs_id, part in sorted(chunk.parts):
            link = self.cs_links.get(cs_id)
            if link is None:
                continue
            acks.append((
                cs_id, part,
                link.command(
                    m.MatocsDuplicateChunk,
                    chunk_id=new_id, version=version,
                    part_id=geometry.ChunkPartType(t, part).id,
                    src_chunk_id=chunk.chunk_id, src_version=chunk.version,
                ),
            ))
        created = []
        for cs_id, part, coro in acks:
            try:
                reply = await coro
                if reply.status == st.OK:
                    created.append((cs_id, part))
            except (ConnectionError, asyncio.TimeoutError):
                pass
        # the duplicate set must be READABLE (any k distinct parts for
        # striped slices, >=1 copy for std); missing redundancy is
        # rebuilt by the health loop on the new chunk — a single down
        # replica must not block writes to a snapshot-shared chunk
        distinct = {part for _, part in created}
        needed = (
            geometry.required_parts_to_recover(t) if not t.is_standard else 1
        )
        if len(distinct) < needed:
            for cs_id, part in created:
                link = self.cs_links.get(cs_id)
                if link is not None:
                    try:
                        await link.command(
                            m.MatocsDeleteChunk, chunk_id=new_id,
                            version=version,
                            part_id=geometry.ChunkPartType(t, part).id,
                        )
                    except (ConnectionError, asyncio.TimeoutError):
                        pass
            return m.MatoclWriteChunk(
                req_id=msg.req_id, status=st.NO_CHUNK_SERVERS, chunk_id=0,
                version=0, file_length=0, locations=[],
            )
        self.commit({
            "op": "cow_chunk", "inode": msg.inode, "chunk_index": msg.chunk_index,
            "old_chunk_id": chunk.chunk_id, "new_chunk_id": new_id,
            "slice_type": chunk.slice_type, "version": version,
            "copies": chunk.copies, "goal_id": chunk.goal_id,
        })
        new_chunk = self.meta.registry.chunk(new_id)
        for cs_id, part in created:
            self.meta.registry.record_part(new_chunk, cs_id, part)
        new_chunk.locked_until = time.monotonic() + CHUNK_LOCK_SECONDS
        if self.meta.registry.evaluate(new_chunk).needs_work:
            self.meta.registry.mark_endangered(new_id)
        self.log.info(
            "COW: chunk %d -> %d for inode %d", chunk.chunk_id, new_id, msg.inode
        )
        return m.MatoclWriteChunk(
            req_id=msg.req_id, status=st.OK, chunk_id=new_id, version=version,
            file_length=node.length, locations=self._locations_of(new_chunk),
        )

    def _slice_type_for_goal(self, goal_id: int) -> geometry.SliceType:
        goal = self.goals.get(goal_id)
        s = goal.disk_slice() if goal is not None else None
        if s is None:
            return geometry.SliceType(geometry.STANDARD)
        return s.type

    def _labels_for_goal(
        self, goal_id: int, t: geometry.SliceType, part_list: list[int]
    ) -> list[str]:
        """Per-slot placement labels from the goal definition."""
        goal = self.goals.get(goal_id)
        s = goal.disk_slice() if goal is not None else None
        if s is None:
            return ["_"] * len(part_list)
        if t.is_standard:
            out: list[str] = []
            for label, count in sorted(s.labels_of_part(0).items()):
                out.extend([label] * count)
            out = out[: len(part_list)]
            return out + ["_"] * (len(part_list) - len(out))
        return [
            next(iter(s.labels_of_part(p)), "_") if p < s.size else "_"
            for p in part_list
        ]

    async def _create_new_chunk(self, msg: m.CltomaWriteChunk, node):
        t = self._slice_type_for_goal(node.goal)
        goal = self.goals.get(node.goal)
        copies = goal.expected_copies() if (goal and t.is_standard) else 1
        # std goals: N copies of part 0; xor/ec: one copy of each part
        part_list = [0] * copies if t.is_standard else list(range(t.expected_parts))
        nparts = len(part_list)
        try:
            servers = self.meta.registry.choose_servers(
                nparts, labels=self._labels_for_goal(node.goal, t, part_list)
            )
        except ValueError:
            return m.MatoclWriteChunk(
                req_id=msg.req_id, status=st.NO_CHUNK_SERVERS, chunk_id=0,
                version=0, file_length=0, locations=[],
            )
        # reserve the id immediately — the awaits below suspend this
        # coroutine and a concurrent create must not reuse it
        chunk_id = self.meta.registry.next_chunk_id
        self.meta.registry.next_chunk_id = chunk_id + 1
        version = 1
        # command part creation on each server first; registry mutation is
        # committed only after at least the data parts exist
        acks = []
        for part, srv in zip(part_list, servers):
            link = self.cs_links.get(srv.cs_id)
            if link is None:
                continue
            acks.append((
                part, srv,
                link.command(
                    m.MatocsCreateChunk,
                    chunk_id=chunk_id, version=version,
                    part_id=geometry.ChunkPartType(t, part).id,
                ),
            ))
        created: list[tuple[int, ChunkServerInfo]] = []
        replies = await asyncio.gather(
            *(coro for _, _, coro in acks), return_exceptions=True
        )
        for (part, srv, _), reply in zip(acks, replies):
            if isinstance(reply, (ConnectionError, asyncio.TimeoutError)):
                continue  # that server just doesn't get the part
            if isinstance(reply, BaseException):
                raise reply  # protocol/programming error: surface it
            if reply.status == st.OK:
                created.append((part, srv))
        if len(created) < nparts:
            # roll back whatever was created
            for part, srv in created:
                link = self.cs_links.get(srv.cs_id)
                if link is not None:
                    try:
                        await link.command(
                            m.MatocsDeleteChunk, chunk_id=chunk_id,
                            version=version,
                            part_id=geometry.ChunkPartType(t, part).id,
                        )
                    except (ConnectionError, asyncio.TimeoutError):
                        pass
            return m.MatoclWriteChunk(
                req_id=msg.req_id, status=st.NO_CHUNK_SERVERS, chunk_id=0,
                version=0, file_length=0, locations=[],
            )
        self.commit({
            "op": "create_chunk", "chunk_id": chunk_id,
            "slice_type": int(t), "version": version, "copies": copies,
            "goal_id": node.goal,
        })
        self.commit({
            "op": "set_chunk", "inode": msg.inode,
            "chunk_index": msg.chunk_index, "chunk_id": chunk_id,
        })
        chunk = self.meta.registry.chunk(chunk_id)
        for part, srv in created:
            self.meta.registry.record_part(chunk, srv.cs_id, part)
        chunk.locked_until = time.monotonic() + CHUNK_LOCK_SECONDS
        return m.MatoclWriteChunk(
            req_id=msg.req_id, status=st.OK, chunk_id=chunk_id, version=version,
            file_length=node.length, locations=self._locations_of(chunk),
        )

    async def _write_chunk_end(self, msg: m.CltomaWriteChunkEnd):
        self._apply_write_chunk_end(
            msg.chunk_id, msg.inode, msg.file_length, msg.status
        )
        return m.MatoclStatusReply(req_id=msg.req_id, status=st.OK)

    def _apply_write_chunk_end(
        self, chunk_id: int, inode: int, file_length: int, status: int
    ) -> None:
        """Seal one chunk's write: unlock, re-evaluate redundancy, and
        (on a clean end) journal the length/mtime. Shared by the
        per-chunk RPC and the coalesced CltomaWriteChunkEndBatch."""
        chunk = self.meta.registry.chunks.get(chunk_id)
        if chunk is not None:
            chunk.locked_until = 0.0
            state = self.meta.registry.evaluate(chunk)
            if state.needs_work:
                self.meta.registry.mark_endangered(chunk_id)
        if status == st.OK:
            node = self.meta.fs.file_node(inode)
            if file_length > node.length:
                delta = file_length - node.length
                parent = node.parents[0] if node.parents else fsmod.ROOT_INODE
                self._check_quota(parent, node.uid, node.gid, 0, delta)
            # journal every completed write (the reference logs a
            # LENGTH/WRITE line per write too): updates mtime and the
            # content generation, so tape staleness and shadow replay
            # see in-place overwrites, not just growth.
            # write-path grow: never drop chunks — a concurrent write
            # may have attached a higher chunk index already
            self.commit({
                "op": "set_length", "inode": inode,
                "length": max(file_length, node.length),
                "ts": int(time.time()), "drop_chunks": False,
            })

    # --- chunkserver service (matocsserv analog) --------------------------------------

    # registration ingest slice: a 10k-server storm piles up megapart
    # reports; apply them in slices with yield points so client service
    # keeps running between slices (stall-watchdog pinned in the storm
    # test)
    REGISTER_INGEST_SLICE = 4096

    async def _ingest_parts(
        self, cs_id: int, infos, collect_stale: bool
    ) -> list:
        """Apply a registration's part report in slices, yielding the
        event loop between slices (chunked apply — one 1M-part report
        must not stall every other connection for its whole walk)."""
        stale = []
        registry = self.meta.registry
        for i, info in enumerate(infos):
            if not registry.add_part(
                info.chunk_id, cs_id, info.part_id, info.version
            ):
                if collect_stale:
                    stale.append(info)
            if (i + 1) % self.REGISTER_INGEST_SLICE == 0:
                await asyncio.sleep(0)
        return stale

    async def _cs_loop(self, reader, writer, first: m.CstomaRegister) -> None:
        if not self.is_active:
            if (
                self.personality == "shadow"
                and shadow_reads_enabled()
                and getattr(first, "mirror", 0)
            ):
                # passive mirror registration: the shadow learns part
                # locations (volatile state the changelog cannot carry)
                # so replica locates have locations to serve; it never
                # commands the chunkserver. Non-mirror registrations
                # still get NOT_POSSIBLE — the chunkserver's command
                # link must keep cycling until it finds the active.
                await self._mirror_cs_loop(reader, writer, first)
                return
            await framing.send_message(
                writer,
                m.MatocsRegisterReply(
                    req_id=first.req_id, status=st.NOT_POSSIBLE, cs_id=0,
                    epoch=self.meta.epoch,
                ),
            )
            return
        if getattr(first, "mirror", 0):
            # a mirror link never carries commands; the ACTIVE must not
            # adopt one as a command link (its pushes would be dropped
            # by the peer's pump) — refuse so the chunkserver backs off.
            # The refusal CARRIES our epoch: a chunkserver mirror-dialing
            # a freshly promoted master learns of the election from this
            # very reply and flips the address mirror->command (fencing
            # its old command link to the deposed ex-primary).
            await framing.send_message(
                writer,
                m.MatocsRegisterReply(
                    req_id=first.req_id, status=st.NOT_POSSIBLE, cs_id=0,
                    epoch=self.meta.epoch,
                ),
            )
            return
        if self.observe_peer_epoch(getattr(first, "epoch", 0)):
            # this chunkserver has seen a newer master — we just fenced
            # ourselves; refuse so its link cycles to the real active
            await framing.send_message(
                writer,
                m.MatocsRegisterReply(
                    req_id=first.req_id, status=st.NOT_POSSIBLE, cs_id=0,
                    epoch=self.meta.epoch,
                ),
            )
            return
        link = _CsLink(self, reader, writer)
        srv = self.meta.registry.register_server(
            first.addr.host, first.addr.port, first.label,
            first.total_space, first.used_space,
            data_port=getattr(first, "data_port", 0),
        )
        srv.mirror = False  # command link (a promoted shadow's entry
        # for this addr may still carry the mirror flag)
        link.cs_id = srv.cs_id
        self.cs_links[srv.cs_id] = link
        stale: list[m.ChunkPartInfo] = await self._ingest_parts(
            srv.cs_id, first.chunks, collect_stale=True
        )
        await framing.send_message(
            writer,
            m.MatocsRegisterReply(
                req_id=first.req_id, status=st.OK, cs_id=srv.cs_id,
                epoch=self.meta.epoch,
            ),
        )
        self.log.info(
            "chunkserver %d registered (%s:%d, %d parts, %d stale)",
            srv.cs_id, srv.host, srv.port, len(first.chunks), len(stale),
        )
        for info in stale:
            # a wrong-version part of a chunk that is currently
            # UNREADABLE is the only repair material `filerepair` has —
            # keep it on disk and remember it instead of deleting
            # (normal stale copies, e.g. bump stragglers of a healthy
            # chunk, are reclaimed as before)
            chunk = self.meta.registry.chunks.get(info.chunk_id)
            if (
                chunk is not None
                and not self.meta.registry.evaluate(chunk).is_readable
            ):
                self.meta.registry.record_stale(
                    info.chunk_id, srv.cs_id, info.part_id, info.version
                )
                continue
            self.spawn(self._delete_stale(link, info))
        try:
            while True:
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if not self.is_active:
                    # demoted mid-link (fenced, or lost an election):
                    # a shadow must never hold a command link — close
                    # so the chunkserver's heartbeat loop re-cycles the
                    # address list and finds the new active
                    break
                if isinstance(msg, m.CstomaChunkOpStatus):
                    link.dispatch_ack(msg)
                elif isinstance(msg, m.CstomaHeartbeat):
                    if self.observe_peer_epoch(getattr(msg, "epoch", 0)):
                        # the chunkserver heard of a newer election than
                        # we did (its heartbeat echoes the max epoch it
                        # has observed) — we just stepped down; drop the
                        # command link instead of acking as active
                        break
                    srv.total_space = msg.total_space
                    srv.used_space = msg.used_space
                    if getattr(msg, "health_json", ""):
                        # health rollup input: the CS's SLO burn/stall/
                        # disk snapshot rides the heartbeat (old peers
                        # send "" and stay health-unknown)
                        try:
                            self.cs_health[srv.cs_id] = json.loads(
                                msg.health_json
                            )
                        except ValueError:
                            pass
                    hj = getattr(msg, "heat_json", "")
                    if hj and constants_mod.heat_enabled():
                        # per-chunk heat fold: the byte-weight input of
                        # the cluster heat map (old peers send "")
                        try:
                            self.heat.fold_cs(srv.cs_id, json.loads(hj))
                        except ValueError:
                            pass
                    await framing.send_message(
                        writer, m.MatocsRegisterReply(
                            req_id=msg.req_id, status=st.OK, cs_id=srv.cs_id,
                            # QoS data-plane config refresh: weights /
                            # budgets changed live propagate within one
                            # heartbeat ("" when off/unconfigured)
                            qos_json=self._qos_cs_json(),
                            # fencing epoch refresh: every heartbeat ack
                            # re-stamps the cluster epoch so the fleet
                            # converges on it within one interval
                            epoch=self.meta.epoch,
                        )
                    )
                elif isinstance(msg, (m.CstomaChunkDamaged, m.CstomaChunkLost)):
                    for info in msg.chunks:
                        self.meta.registry.drop_part(
                            info.chunk_id, srv.cs_id, info.part_id
                        )
                        self.meta.registry.mark_endangered(info.chunk_id)
                elif isinstance(msg, m.CstomaChunkNew):
                    for info in msg.chunks:
                        self.meta.registry.add_part(
                            info.chunk_id, srv.cs_id, info.part_id, info.version
                        )
        finally:
            link.fail_all()
            # supersession guard: a quick reconnect registers the same
            # cs_id (addr index) and its sliced ingest YIELDS — this
            # old connection's teardown must not tear down the live
            # replacement's registration mid-ingest
            if self.cs_links.get(srv.cs_id) is link:
                self.cs_links.pop(srv.cs_id, None)
                # drop the health snapshot with the link: a dead
                # server's frozen burn/breach figures must not haunt
                # the rollup (a reconnect re-registers and heartbeats
                # fresh state)
                self.cs_health.pop(srv.cs_id, None)
                affected = self.meta.registry.server_disconnected(srv.cs_id)
                for cid in affected:
                    self.meta.registry.mark_endangered(cid)
                self.log.info(
                    "chunkserver %d disconnected (%d chunks affected)",
                    srv.cs_id, len(affected),
                )

    async def _mirror_cs_loop(
        self, reader, writer, first: m.CstomaRegister
    ) -> None:
        """Shadow-side chunkserver mirror: accept the registration's
        part report (and follow-up heartbeats / gain-loss reports) into
        THIS master's registry so replica locates can serve locations —
        but never send a command (stale parts are the ACTIVE master's to
        reclaim; a shadow deleting parts would be catastrophic).
        Chunkservers re-send their full part list periodically on the
        same connection; each re-registration replaces the server's
        recorded part set wholesale (drift between reports self-heals).
        Closed on promotion so the chunkserver re-registers over a
        command-capable link.

        ``self.meta.registry`` is re-read at every use: a shadow image
        re-download REPLACES the registry object (load_sections), and a
        captured reference would orphan every live mirror link onto the
        old table while _ingest_parts wrote the new one."""
        self._mirror_cs_writers.add(writer)

        async def ingest_registration(msg: m.CstomaRegister):
            registry = self.meta.registry
            srv = registry.register_server(
                msg.addr.host, msg.addr.port, msg.label,
                msg.total_space, msg.used_space,
                data_port=getattr(msg, "data_port", 0),
            )
            srv.mirror = True  # passive location feed, not a command link
            # supersession marker (same race as _cs_loop's `is link`
            # guard): a re-dialed mirror link registers the same cs_id
            # while the half-open old loop lingers in read_message —
            # the old loop's teardown must not drop the new link's parts
            self._mirror_cs_owner[srv.cs_id] = writer
            registry.reset_server_parts(srv.cs_id)
            await self._ingest_parts(srv.cs_id, msg.chunks,
                                     collect_stale=False)
            await framing.send_message(
                writer,
                m.MatocsRegisterReply(
                    req_id=msg.req_id, status=st.OK, cs_id=srv.cs_id,
                    # shadow's replayed epoch: keeps mirror-registered
                    # chunkservers fencing-current even before this
                    # node is ever promoted
                    epoch=self.meta.epoch,
                ),
            )
            return srv

        srv = None
        try:
            srv = await ingest_registration(first)
            self.log.info(
                "chunkserver mirror-registered (%s:%d, %d parts)",
                srv.host, srv.port, len(first.chunks),
            )
            while self.personality == "shadow":
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if not self.personality == "shadow":
                    break
                if isinstance(msg, m.CstomaRegister):
                    srv = await ingest_registration(msg)
                elif isinstance(msg, m.CstomaHeartbeat):
                    srv.total_space = msg.total_space
                    srv.used_space = msg.used_space
                    await framing.send_message(
                        writer, m.MatocsRegisterReply(
                            req_id=msg.req_id, status=st.OK, cs_id=srv.cs_id,
                            epoch=self.meta.epoch,
                        )
                    )
                elif isinstance(msg, (m.CstomaChunkDamaged, m.CstomaChunkLost)):
                    for info in msg.chunks:
                        self.meta.registry.drop_part(
                            info.chunk_id, srv.cs_id, info.part_id
                        )
                elif isinstance(msg, m.CstomaChunkNew):
                    for info in msg.chunks:
                        self.meta.registry.add_part(
                            info.chunk_id, srv.cs_id, info.part_id,
                            info.version,
                        )
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # peer died mid-registration; cleanup below
        finally:
            self._mirror_cs_writers.discard(writer)
            if (
                srv is not None
                and self.personality == "shadow"
                and self._mirror_cs_owner.get(srv.cs_id) is writer
            ):
                # still a shadow AND still the owning link: the mirror
                # peer is gone, drop its parts. A superseded loop (the
                # chunkserver re-dialed; owner moved on) must not wipe
                # the live link's fresh report, and after PROMOTION the
                # chunkserver re-registers command-capable on the same
                # addr-indexed entry — disconnecting would race that.
                self._mirror_cs_owner.pop(srv.cs_id, None)
                self.meta.registry.server_disconnected(srv.cs_id)

    async def _delete_stale(self, link: _CsLink, info: m.ChunkPartInfo) -> None:
        try:
            await link.command(
                m.MatocsDeleteChunk, chunk_id=info.chunk_id,
                version=info.version, part_id=info.part_id,
            )
        except (ConnectionError, asyncio.TimeoutError):
            pass

    # --- tape server service (matotsserv.cc analog) -----------------------------------

    async def _ts_loop(self, reader, writer, first: m.TstomaRegister) -> None:
        if not self.is_active:
            await framing.send_message(
                writer, m.MatotsRegisterReply(
                    req_id=first.req_id, status=st.NOT_POSSIBLE, ts_id=0
                ),
            )
            return
        link = _CsLink(self, reader, writer)
        ts_id = self._next_ts_id
        self._next_ts_id += 1
        label = first.label or "_"
        self.ts_links[ts_id] = {
            "link": link, "label": label,
            # the tape server's own client session (0 = old peer):
            # recalls scope the demoted-file write guard to exactly it
            "sid": getattr(first, "session_id", 0),
        }
        await framing.send_message(
            writer, m.MatotsRegisterReply(
                req_id=first.req_id, status=st.OK, ts_id=ts_id
            ),
        )
        self.log.info("tape server %d registered (label %s)", ts_id, label)
        self._tape_rescan()
        try:
            while True:
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if isinstance(msg, (m.TstomaPutDone, m.TstomaRecallDone)):
                    link.dispatch_ack(msg)
        finally:
            self.ts_links.pop(ts_id, None)
            link.fail_all()
            self.log.info("tape server %d disconnected", ts_id)

    def _goal_tape_copies(self, goal_id: int) -> int:
        g = self.goals.get(goal_id)
        return g.tape_copies() if g is not None else 0

    def _content_stamp(self, inode: int, node) -> tuple[int, int, int]:
        return (node.length, node.mtime,
                self.meta.content_gen.get(inode, 0))

    def _tape_missing_labels(self, inode: int, node) -> list[str]:
        """Goal tape labels not yet covered by a fresh copy. A named
        label needs a server with that label; a wildcard is satisfied by
        any fresh copy not already claimed by a named label. A
        lifecycle-forced inode (``tape_force``) wants one wildcard copy
        even when its goal carries no $tape slice."""
        goal = self.goals.get(node.goal)
        labels = goal.tape_labels() if goal is not None else []
        if not labels and inode in self.tape_force:
            labels = [geometry.WILDCARD_LABEL]
        if not labels:
            return []
        stamp = self._content_stamp(inode, node)
        fresh = {
            c["label"] for c in self.meta.tape_copies.get(inode, [])
            if (c["length"], c["mtime"], c.get("gen", 0)) == stamp
        }
        named = [l for l in labels if l != geometry.WILDCARD_LABEL]
        missing = [l for l in named if l not in fresh]
        wild = len(labels) - len(named)
        spare_fresh = len(fresh - set(named))
        missing += [geometry.WILDCARD_LABEL] * max(wild - spare_fresh, 0)
        return missing

    def _tape_rescan_sync(self, inodes: list[int]) -> None:
        for inode in inodes:
            node = self.meta.fs.nodes.get(inode)
            if (node is not None and node.ftype == fsmod.TYPE_FILE
                    and self._tape_missing_labels(inode, node)):
                self.tape_pending.setdefault(
                    inode, self._content_stamp(inode, node)
                )

    def _tape_rescan(self) -> None:
        """Requeue files whose tape coverage is missing or stale — run
        when a tape server registers (startup recovery; runtime marking
        is incremental via _tape_mark). Walks the namespace in slices
        off the hot path so a reconnect never stalls the loop."""

        async def walk():
            inodes = list(self.meta.fs.nodes)
            for i in range(0, len(inodes), 10_000):
                self._tape_rescan_sync(inodes[i:i + 10_000])
                await asyncio.sleep(0)

        self.spawn(walk())

    def _tape_mark(self, op: dict) -> None:
        """Incremental tape-dirty marking, called after every commit."""
        t = op["op"]
        if t in ("set_length", "set_chunk", "setgoal", "mknode", "undelete"):
            inodes = [op["inode"]]
        elif t == "snapshot":
            inodes = list(op.get("inode_map", {}).values())
        elif t == "purge_trash":
            inode = op["inode"]
            self.tape_pending.pop(inode, None)
            self.tape_force.discard(inode)
            if (inode not in self.meta.fs.nodes
                    and inode in self.meta.tape_copies):
                self.commit({"op": "tape_drop", "inode": inode})
                for e in self.ts_links.values():
                    # reclaim all archived versions of the dead file
                    try:
                        framing.write_message(
                            e["link"].writer, m.MatotsDeleteFile(
                                req_id=0, inode=inode,
                                keep_mtime=0, keep_length=0,
                            ),
                        )
                    except (ConnectionError, RuntimeError):
                        pass
            return
        else:
            return
        for inode in inodes:
            node = self.meta.fs.nodes.get(inode)
            if node is None or node.ftype != fsmod.TYPE_FILE:
                continue
            if self._goal_tape_copies(node.goal) > 0:
                self.tape_pending[inode] = self._content_stamp(inode, node)
            else:
                # a content mutation resets a lifecycle-forced archive
                # too: the file is hot again, the scanner re-decides
                self.tape_force.discard(inode)
                self.tape_pending.pop(inode, None)

    async def _tape_drain(self) -> None:
        if not (self.is_active and self.ts_links and self.tape_pending):
            return
        batch = [i for i in list(self.tape_pending)
                 if i not in self._tape_inflight][:64]
        for inode in batch:
            node = self.meta.fs.nodes.get(inode)
            if node is None:
                self.tape_pending.pop(inode, None)
                continue
            stamp = self._content_stamp(inode, node)
            self.tape_pending[inode] = stamp
            missing = self._tape_missing_labels(inode, node)
            if not missing:
                self.tape_pending.pop(inode, None)
                continue
            fresh = {
                c["label"] for c in self.meta.tape_copies.get(inode, [])
                if (c["length"], c["mtime"], c.get("gen", 0)) == stamp
            }
            entry = None
            for e in self.ts_links.values():
                if e["label"] in missing or (
                    geometry.WILDCARD_LABEL in missing
                    and e["label"] not in fresh
                ):
                    entry = e
                    break
            if entry is None:
                # no connected server can satisfy THIS inode's labels;
                # others behind it may still be placeable
                continue
            self._tape_inflight.add(inode)
            self.spawn(self._tape_put(entry, inode, node, stamp))

    async def _tape_put(self, entry: dict, inode: int, node, stamp) -> None:
        try:
            done = await entry["link"].command(
                m.MatotsPutFile, inode=inode,
                path=self.meta.fs.path_of(inode),
                length=node.length, mtime=node.mtime, timeout=60.0,
            )
            if (done.status == st.OK
                    and (done.length, done.mtime) == stamp[:2]
                    and self.tape_pending.get(inode) == stamp):
                cur = self.meta.fs.nodes.get(inode)
                if cur is not None and \
                        self._content_stamp(inode, cur) == stamp:
                    self.commit({
                        "op": "tape_copy", "inode": inode,
                        "label": entry["label"], "length": stamp[0],
                        "mtime": stamp[1], "gen": stamp[2],
                        "ts": int(time.time()),
                    })
                    # reclaim stale archive versions on that server
                    # (fire-and-forget; re-sent on the next fresh copy)
                    try:
                        framing.write_message(
                            entry["link"].writer, m.MatotsDeleteFile(
                                req_id=0, inode=inode,
                                keep_mtime=stamp[1], keep_length=stamp[0],
                            ),
                        )
                    except (ConnectionError, RuntimeError):
                        pass
        except (ConnectionError, asyncio.TimeoutError, st.StatusError):
            pass  # stays pending; next drain retries
        finally:
            self._tape_inflight.discard(inode)

    # --- lifecycle tiering: demote to tape, recall on access ---------------------------

    def _tape_fresh_labels(self, inode: int, stamp) -> set[str]:
        """Labels holding an archival copy at exactly this content
        stamp."""
        return {
            c["label"] for c in self.meta.tape_copies.get(inode, [])
            if (c["length"], c["mtime"], c.get("gen", 0)) == tuple(stamp)
        }

    def _try_demote(self, inode: int, now: int) -> int:
        """Demote one file to the tape tier. OK = demoted (or nothing
        to do), CHUNK_BUSY = archive queued / file busy, retry later."""
        node = self.meta.fs.nodes.get(inode)
        if node is None or node.ftype != fsmod.TYPE_FILE:
            return st.ENOENT
        if inode in self.meta.demoted:
            return st.OK  # already tape-only
        if node.length == 0 or not node.chunks:
            return st.OK  # nothing to free; GET serves zeros already
        if self.meta.fs.open_refs.get(inode) or inode in self._recall_inflight:
            return st.CHUNK_BUSY  # never demote under an open handle
        for cid in node.chunks:
            chunk = self.meta.registry.chunks.get(cid) if cid else None
            if chunk is not None and chunk.locked_until > time.monotonic():
                return st.CHUNK_BUSY  # write in flight
        stamp = self._content_stamp(inode, node)
        if self._tape_fresh_labels(inode, stamp):
            self.commit({"op": "tape_demote", "inode": inode, "ts": now})
            self.tape_force.discard(inode)
            self.tape_pending.pop(inode, None)
            self._invalidate_client_caches(inode)
            self.metrics.counter(
                "tape_demoted",
                help="files demoted to the tape tier (chunk data freed)",
            ).inc()
            return st.OK
        # no fresh archival copy yet: force-queue one (wildcard label,
        # goal-independent) and report busy so the caller retries
        self.tape_force.add(inode)
        self.tape_pending.setdefault(inode, stamp)
        return st.CHUNK_BUSY

    def _recall_writer_ok(self, inode: int, session_id: int) -> bool:
        """May this session write a demoted inode right now? Only the
        recalling tape server's session, and only once the recall task
        dispatched the restore (sid recorded). A legacy tape server
        that registered without a session id (sid 0) gets the old
        permissive standdown — the recall-done length check is then
        the only concurrent-write defense."""
        if inode not in self._recall_inflight:
            return False
        sid = self._recall_sids.get(inode)
        if sid is None:
            return False  # restore not dispatched yet: nobody writes
        return sid == 0 or sid == session_id

    def _ensure_recall(self, inode: int) -> asyncio.Future:
        """The single-flight recall future for an inode: every GET that
        trips over a demoted file awaits the same restore."""
        fut = self._recall_inflight.get(inode)
        if fut is None or fut.done():
            fut = asyncio.get_running_loop().create_future()
            self._recall_inflight[inode] = fut
            self.spawn(self._tape_recall_task(inode, fut))
        return fut

    async def _tape_recall_task(self, inode: int, fut: asyncio.Future) -> None:
        status = st.EIO
        try:
            doc = self.meta.demoted.get(inode)
            if doc is None:
                status = st.OK
                return
            want = (doc["length"], doc["mtime"], doc.get("gen", 0))
            labels = self._tape_fresh_labels(inode, want)
            entry = next(
                (e for e in self.ts_links.values() if e["label"] in labels),
                None,
            )
            if entry is None:
                # no connected tape server holds the archived version
                status = st.NOT_POSSIBLE
                return
            # scope the write-guard standdown to the restoring session
            # (0 = legacy tape server: permissive, length check below
            # is then the only concurrent-write defense)
            self._recall_sids[inode] = entry.get("sid", 0)
            done = await entry["link"].command(
                m.MatotsRecallFile, inode=inode,
                path=self.meta.fs.path_of(inode),
                length=doc["length"], mtime=doc["mtime"], timeout=120.0,
            )
            if done.status != st.OK:
                status = done.status
                return
            node = self.meta.fs.nodes.get(inode)
            if node is None or inode not in self.meta.demoted:
                status = st.OK if node is not None else st.ENOENT
                return
            # a write that raced the restore makes the content live
            # again but NOT the archived version: clear the demoted
            # state without the mtime/stamp restore, and let _tape_mark
            # (which already saw the write) drive any re-archive. With
            # a session-scoped guard (sid > 0) concurrent writes were
            # refused outright, so the length check is pure defense;
            # for a legacy tape server (sid == 0) it is the only
            # concurrent-write tell we have (a same-length race slips
            # through — upgrade the tape server to close it).
            clean = (
                (done.length, done.mtime) == want[:2]
                and node.length == doc["length"]
            )
            self.commit({
                "op": "tape_recall_done", "inode": inode,
                "ts": int(time.time()), "restore": clean,
            })
            self.tape_pending.pop(inode, None)
            self._invalidate_client_caches(inode)
            self.metrics.counter(
                "tape_recalled",
                help="files recalled from the tape tier on access",
            ).inc()
            status = st.OK
        except (ConnectionError, asyncio.TimeoutError):
            status = st.TIMEOUT
        finally:
            self._recall_inflight.pop(inode, None)
            self._recall_sids.pop(inode, None)
            if not fut.done():
                fut.set_result(status)

    def _lifecycle_rule_of(self, node) -> float | None:
        """demote_after_s from a lifecycle directory's rule xattr, or
        None when the rule is absent/offline/unparseable."""
        raw = node.xattrs.get(constants_mod.S3_LIFECYCLE_XATTR)
        if not raw:
            return None
        try:
            rule = json.loads(raw.decode("utf-8"))
            if not rule.get("enabled", True):
                return None
            return max(float(rule["demote_after_s"]), 0.0)
        except (ValueError, KeyError, UnicodeDecodeError):
            return None

    async def _lifecycle_tick(self) -> None:
        """Age-based demote scan over lifecycle-marked directories
        (S3 buckets with rules): files colder than the rule's
        demote_after_s push through the existing tape archive flow and
        demote once a fresh copy lands. Budgeted per tick with a
        RESUMABLE cursor (the saved walk stack): a bucket larger than
        one tick's budget makes progress every tick instead of
        rescanning the same prefix forever."""
        if not (self.is_active and self.meta.fs.lifecycle_dirs):
            return
        if not constants_mod.s3_lifecycle_enabled():
            return
        fs = self.meta.fs
        now = int(time.time())
        scanned = demoted = 0
        # drop cursors of roots that lost their rule/marker
        for root in [r for r in self._lifecycle_stacks
                     if r not in fs.lifecycle_dirs]:
            del self._lifecycle_stacks[root]
        for root in list(fs.lifecycle_dirs):
            dnode = fs.nodes.get(root)
            if dnode is None or dnode.ftype != fsmod.TYPE_DIR:
                fs.lifecycle_dirs.discard(root)
                self._lifecycle_stacks.pop(root, None)
                continue
            after_s = self._lifecycle_rule_of(dnode)
            if after_s is None:
                self._lifecycle_stacks.pop(root, None)
                continue
            # resume where the last tick stopped; a fresh (or finished)
            # walk restarts at the root. Stale inodes saved in a cursor
            # are skipped via nodes.get below.
            stack = self._lifecycle_stacks.pop(root, None) or [root]
            while stack:
                if scanned >= self.lifecycle_scan_budget:
                    self._lifecycle_stacks[root] = stack  # resume here
                    return
                scanned += 1
                if scanned % 2048 == 0:
                    await asyncio.sleep(0)  # stay off the hot loop
                # lint: waive(cross-await-race): _run_timer awaits each tick to completion — lifecycle ticks never overlap, so the cursor stack and fs alias can't be clobbered by a concurrent scan
                node = fs.nodes.get(stack.pop())
                if node is None:
                    continue
                if node.ftype == fsmod.TYPE_DIR:
                    stack.extend(node.children.values())
                    continue
                if node.ftype != fsmod.TYPE_FILE:
                    continue
                if node.inode in self.meta.demoted:
                    continue
                if now - node.mtime <= after_s:
                    continue
                if self._try_demote(node.inode, now) == st.OK:
                    demoted += 1
                    if demoted >= self.lifecycle_demote_budget:
                        self._lifecycle_stacks[root] = stack
                        return

    # --- health loop (ChunkWorker analog) ----------------------------------------------

    async def _health_tick(self) -> None:
        # HA posture gauges are set on EVERY personality — during a
        # failover the node an operator is watching is precisely the
        # one that is NOT (yet) active
        self.metrics.gauge(
            "ha_epoch",
            help="cluster fencing epoch this node has applied (bumped "
                 "by every promotion; 0 = pre-HA / LZ_HA off)",
        ).set(self.meta.epoch)
        self.metrics.gauge(
            "ha_is_active",
            help="1 when this node serves as the active master",
        ).set(int(self.is_active))
        if not self.is_active:
            return
        self.metrics.gauge("chunks").set(len(self.meta.registry.chunks))
        self.metrics.gauge("endangered_queue").set(
            len(self.meta.registry.endangered)
        )
        self.metrics.gauge("chunkservers_connected").set(len(self.cs_links))
        self.metrics.gauge("inodes").set(len(self.meta.fs.nodes))
        # metrics-history inputs for the `top` trends: aggregate
        # per-session op rate + live session population ride the
        # retention rings like any other gauge
        self.metrics.gauge(
            "session_ops_rate",
            help="aggregate client-RPC rate across tracked sessions "
                 "(ops/s over the accounting window)",
        ).set(self.session_ops.total_rate())
        self.metrics.gauge(
            "sessions_active",
            help="client sessions with a live connection",
        ).set(sum(
            1 for s in self.sessions.values() if s.get("connected")
        ))
        self.metrics.gauge("open_files").set(len(self.meta.fs.open_refs))
        self.metrics.gauge("sustained_files").set(
            len(self.meta.fs.sustained)
        )
        # cluster health rollup as derived Prometheus gauges: status
        # (0 ok / 1 degraded / 2 critical), fleet-wide SLO breach total,
        # and how many registered chunkservers report unhealthy/absent
        report = self.cluster_health(evaluate_chunks=False)
        from lizardfs_tpu.runtime import slo as slomod

        self.metrics.gauge(
            "cluster_health_status",
            help="aggregated cluster health: 0 ok, 1 degraded, 2 critical",
        ).set(slomod.STATUS_ORDER.index(report["status"]))
        self.metrics.gauge(
            "cluster_slo_breaches",
            help="SLO breaches across master + all reporting chunkservers",
        ).set(report["summary"]["breaches_total"])
        self.metrics.gauge(
            "cluster_cs_unhealthy",
            help="registered chunkservers down or reporting degraded/"
                 "critical health",
        ).set(report["summary"]["cs_unhealthy"])
        # shadow replication lag (changelog positions): the incident
        # metric for the read-replica plane — staleness retries climb
        # when this does
        self.metrics.gauge(
            "shadow_lag",
            help="worst connected-shadow replication lag in changelog "
                 "positions (0 = all shadows caught up or none connected)",
        ).set(report["summary"]["shadow_lag_max"])
        self.metrics.gauge(
            "shadows_connected",
            help="shadow/metalogger changelog subscribers connected",
        ).set(report["summary"]["shadows"])
        # released chunks: delete their on-disk parts
        drained = self.meta.registry.pending_deletes[:16]
        del self.meta.registry.pending_deletes[:16]
        for dead in drained:
            t = geometry.SliceType(dead.slice_type)
            for cs_id, part in dead.parts:
                link = self.cs_links.get(cs_id)
                if link is None:
                    continue
                self.spawn(self._delete_orphan(link, dead, t, part))
        if len(self._repl_fail_until) > 256:
            # deleted/abandoned chunks leave expired deadlines behind;
            # prune so the dict tracks only active backoffs
            now = time.monotonic()
            self._repl_fail_until = {
                cid: t for cid, t in self._repl_fail_until.items() if t > now
            }
        # until the first danger-aggregate publish, also advance the
        # bootstrap counter so /health's lost/endangered become exact
        # within minutes of a restart, not after a full cursor cycle
        self.meta.registry.danger_bootstrap()
        # heat loop: decay, goal boosts/demotes, placement loads, QoS
        # pressure expiry — before health_work so a fresh boost's
        # missing copies are scheduled in this same tick
        self._heat_tick()
        work = self.meta.registry.health_work(limit=16)
        for item in work:
            if item[0] == "replicate":
                _, chunk, part = item
                if chunk.locked_until > time.monotonic():
                    continue
                if self._repl_fail_until.get(chunk.chunk_id, 0) > time.monotonic():
                    # keep it in the priority FIFO (cheap: one pop +
                    # requeue per tick) so the retry happens when the
                    # backoff expires, not a full scan cycle later
                    self.meta.registry.mark_endangered(chunk.chunk_id)
                    continue
                t = geometry.SliceType(chunk.slice_type)
                state = self.meta.registry.evaluate(chunk)
                self.rebuild.submit(rebuild_mod.Rebuild(
                    chunk_id=chunk.chunk_id, part=part,
                    priority=rebuild_mod.classify(chunk, state),
                    kind="replicate",
                    bytes_est=geometry.number_of_blocks_in_part(
                        geometry.ChunkPartType(t, part)
                    ) * MFSBLOCKSIZE,
                ))
            elif item[0] == "delete":
                _, chunk, cs_id, part = item
                self.spawn(self._delete_redundant(chunk, cs_id, part))
            elif item[0] == "move":
                _, chunk, src_cs, part, dst_cs = item
                t = geometry.SliceType(chunk.slice_type)
                self.rebuild.submit(rebuild_mod.Rebuild(
                    chunk_id=chunk.chunk_id, part=part,
                    priority=rebuild_mod.PRIORITY_REBALANCE,
                    kind="move", src_cs=src_cs, dst_cs=dst_cs,
                    bytes_est=geometry.number_of_blocks_in_part(
                        geometry.ChunkPartType(t, part)
                    ) * MFSBLOCKSIZE,
                ))
        # launch what the scheduler admits (priority order under the
        # concurrency cap); every launch reports back via finished()
        for rb in self.rebuild.next_batch():
            chunk = self.meta.registry.chunks.get(rb.chunk_id)
            if chunk is None:
                self.rebuild.skipped(rb)
                continue
            if chunk.locked_until > time.monotonic():
                # a client write was granted while the rebuild sat
                # queued: step aside and retry when the lock clears
                self.rebuild.skipped(rb)
                self.meta.registry.mark_endangered(rb.chunk_id)
                continue
            rb.trace_id = tracing.new_id() if tracing.enabled() else 0
            if rb.kind == "move":
                self.spawn(
                    self._move_part(chunk, rb.src_cs, rb.part, rb.dst_cs, rb)
                )
            else:
                self.spawn(self._replicate_part(chunk, rb.part, rb))
        self.metrics.gauge("rebuilds_active").set(
            float(len(self.rebuild.active))
        )
        await self._reclaim_stale_parts()

    async def _reclaim_stale_parts(self) -> None:
        """Retained stale-version parts are repair material only while
        their chunk is unreadable; once it recovers (e.g. the rest of a
        rolling restart re-registered the real parts) they are disk
        waste — reclaim a bounded batch per tick so a restart's
        transient retentions can't accumulate forever."""
        registry = self.meta.registry
        if not registry.stale_versions:
            return
        reclaimed = 0
        for cid in list(registry.stale_versions):
            if reclaimed >= 16:
                break
            chunk = registry.chunks.get(cid)
            if chunk is not None and \
                    not registry.evaluate(chunk).is_readable:
                continue  # still the only hope of a version-fix
            reclaimed += 1
            entries = registry.stale_versions.pop(cid, {})
            for (cs_id, part_id), version in entries.items():
                link = self.cs_links.get(cs_id)
                if link is None:
                    continue
                self.spawn(self._delete_stale(link, m.ChunkPartInfo(
                    chunk_id=cid, version=version, part_id=part_id,
                )))

    async def _delete_orphan(self, link, dead, t, part: int) -> None:
        try:
            await link.command(
                m.MatocsDeleteChunk, chunk_id=dead.chunk_id,
                version=dead.version, part_id=geometry.ChunkPartType(t, part).id,
            )
        except (ConnectionError, asyncio.TimeoutError):
            pass

    async def _replicate_part(
        self, chunk, part: int, rb: rebuild_mod.Rebuild | None = None
    ) -> None:
        if rb is None:  # direct callers (tests) bypass the scheduler
            rb = rebuild_mod.Rebuild(
                chunk_id=chunk.chunk_id, part=part,
                priority=rebuild_mod.PRIORITY_ENDANGERED,
            )
            rb.started_at = time.monotonic()
            self.rebuild.active[rb.key] = rb
        ok = False
        attempted = False
        t0 = time.perf_counter()
        tw0 = time.time()
        try:
            t = geometry.SliceType(chunk.slice_type)
            holders = {cs for cs, _ in chunk.parts}
            label = self._labels_for_goal(chunk.goal_id, t, [part])[0]
            try:
                target = self.meta.registry.choose_servers(
                    1, exclude=holders, labels=[label]
                )[0]
            except ValueError:
                # every connected server already holds some part (e.g.
                # ec(3,2) on 5 servers after one died). Doubling up on a
                # server that lacks THIS part beats leaving the chunk
                # endangered forever — the reference fills goals with
                # repeats too when servers run short.
                same_part = {cs for cs, p in chunk.parts if p == part}
                try:
                    target = self.meta.registry.choose_servers(
                        1, exclude=same_part, labels=[label]
                    )[0]
                except ValueError:
                    return
            link = self.cs_links.get(target.cs_id)
            if link is None:
                return
            sources = self._locations_of(chunk)
            # cluster rebuild throttle: pace this part's bytes against
            # the admin-tunable budget BEFORE commanding the rebuild
            await self.rebuild.throttle(rb.bytes_est)
            # re-check the write lock: the chunk may have been queued
            # across ticks (concurrency cap) and throttled across
            # awaits — a client write granted meanwhile must not race
            # a rebuild assembled from parts it is mutating
            if chunk.locked_until > time.monotonic():
                return
            attempted = True
            try:
                reply = await link.command(
                    m.MatocsReplicate,
                    chunk_id=chunk.chunk_id, version=chunk.version,
                    part_id=geometry.ChunkPartType(t, part).id,
                    sources=sources, trace_id=rb.trace_id, timeout=60.0,
                )
            except (ConnectionError, asyncio.TimeoutError):
                return
            if reply.status == st.OK:
                ok = True
                self._repl_fail_until.pop(chunk.chunk_id, None)
            else:
                self.log.warning(
                    "replication of chunk %d v%d part %d to cs %d failed:"
                    " %s (sources: %s)",
                    chunk.chunk_id, chunk.version, part, target.cs_id,
                    st.name(reply.status),
                    # PartLocation carries addr+part, not cs_id — the
                    # old cs_id access raised here, killing the task
                    # with the failure reason unlogged
                    [(f"{l.addr.host}:{l.addr.port}",
                      geometry.ChunkPartType.from_id(l.part_id).part)
                     for l in sources],
                )
                self._repl_fail_until[chunk.chunk_id] = (
                    time.monotonic() + 5.0
                )
        finally:
            if attempted:
                # scheduler-side accounting: the span names the rebuild
                # in trace-dump, the replicate SLO class catches slow
                # rebuilds (flight-recording their timeline), the
                # engine folds the outcome into progress/ETA
                dt = time.perf_counter() - t0
                self.trace_ring.record(
                    rb.trace_id, "rebuild", tw0, time.time(),
                    role="master", bytes=rb.bytes_est,
                    chunk_id=chunk.chunk_id,
                )
                self.slo.observe(
                    "replicate", dt, trace_id=rb.trace_id, name="rebuild"
                )
                self.rebuild.finished(rb, ok, rb.bytes_est if ok else 0)
            else:
                # never attempted (no target / link gone / re-locked):
                # free the slot without polluting failure telemetry
                self.rebuild.skipped(rb)
            # re-evaluate on the next tick until healthy — but only hot-
            # requeue chunks that can actually be repaired: an
            # unreadable chunk (fewer than k live parts) has no sources,
            # so the endangered FIFO would spin on it forever; the
            # routine scan keeps retrying it at its own slower pace
            state = self.meta.registry.evaluate(chunk)
            if state.needs_work and state.is_readable:
                self.meta.registry.mark_endangered(chunk.chunk_id)

    async def _move_part(
        self, chunk, src_cs: int, part: int, dst_cs: int,
        rb: rebuild_mod.Rebuild | None = None,
    ) -> None:
        """Rebalancing migration: replicate the part onto the target,
        then drop the source copy. The replicate window is long (up to
        60 s) and does NOT lock the chunk; if a client write bumped the
        version meanwhile, the fresh copy is stale — drop it and abort
        instead of registering it."""
        if rb is None:  # direct callers (tests) bypass the scheduler
            rb = rebuild_mod.Rebuild(
                chunk_id=chunk.chunk_id, part=part,
                priority=rebuild_mod.PRIORITY_REBALANCE, kind="move",
                src_cs=src_cs, dst_cs=dst_cs,
            )
            rb.started_at = time.monotonic()
            self.rebuild.active[rb.key] = rb
        moved = False
        attempted = False
        v0 = chunk.version
        try:
            t = geometry.SliceType(chunk.slice_type)
            link = self.cs_links.get(dst_cs)
            if link is None:
                return
            part_id = geometry.ChunkPartType(t, part).id
            await self.rebuild.throttle(rb.bytes_est)
            attempted = True
            try:
                reply = await link.command(
                    m.MatocsReplicate,
                    chunk_id=chunk.chunk_id, version=v0,
                    part_id=part_id, sources=self._locations_of(chunk),
                    trace_id=rb.trace_id, timeout=60.0,
                )
            except (ConnectionError, asyncio.TimeoutError):
                return
            if reply.status != st.OK:
                return
            current = self.meta.registry.chunks.get(chunk.chunk_id)
            if (
                current is not chunk
                or chunk.version != v0
                or chunk.locked_until > time.monotonic()
            ):
                # chunk changed under the migration: discard the copy
                try:
                    await link.command(
                        m.MatocsDeleteChunk, chunk_id=chunk.chunk_id,
                        version=v0, part_id=part_id,
                    )
                except (ConnectionError, asyncio.TimeoutError):
                    pass
                return
            self.meta.registry.record_part(chunk, dst_cs, part)
            await self._delete_redundant(chunk, src_cs, part)
            self.metrics.counter("rebalance_moves").inc()
            moved = True
        finally:
            if attempted:
                self.rebuild.finished(
                    rb, moved, rb.bytes_est if moved else 0
                )
            else:
                self.rebuild.skipped(rb)

    async def _delete_redundant(self, chunk, cs_id: int, part: int) -> None:
        link = self.cs_links.get(cs_id)
        if link is None:
            return
        t = geometry.SliceType(chunk.slice_type)
        part_id = geometry.ChunkPartType(t, part).id
        try:
            reply = await link.command(
                m.MatocsDeleteChunk, chunk_id=chunk.chunk_id,
                version=chunk.version, part_id=part_id,
            )
            if reply.status == st.OK:
                self.meta.registry.drop_part(chunk.chunk_id, cs_id, part_id)
        except (ConnectionError, asyncio.TimeoutError):
            pass

    # --- shadow / metalogger stream (matomlserv analog) ---------------------------------

    async def _shadow_loop(self, reader, writer, first: m.MltomaRegister) -> None:
        if self.observe_peer_epoch(getattr(first, "epoch", 0)):
            # the registering shadow/metalogger has replayed a NEWER
            # epoch_bump than our own state — a later election happened
            # without us. We just stepped down; refuse the stream (a
            # zombie feeding changelog lines would fork its follower).
            await framing.send_message(
                writer,
                m.MatomlRegisterReply(
                    req_id=first.req_id, status=st.NOT_POSSIBLE,
                    version=self.changelog.version, epoch=self.meta.epoch,
                ),
            )
            return
        self.shadow_writers.append(writer)
        await framing.send_message(
            writer,
            m.MatomlRegisterReply(
                req_id=first.req_id, status=st.OK,
                version=self.changelog.version,
                # followers compare this against their replayed epoch:
                # lower than theirs = we are the zombie, they refuse us
                epoch=self.meta.epoch,
            ),
        )
        try:
            # serve image download requests; changelog lines are pushed by
            # commit(); shadows ack their applied position (MltomaAck) so
            # health/admin can report per-shadow replication lag
            while True:
                try:
                    msg = await framing.read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                if isinstance(msg, m.MltomaAck):
                    self.shadow_status[id(writer)] = {
                        "version": msg.version,
                        "serving": bool(getattr(msg, "serving", 0)),
                        "ts": time.monotonic(),
                    }
                    continue
                if isinstance(msg, m.MltomaDownloadImage):
                    doc = {
                        "format": "inline",
                        **self.meta.to_sections(),
                    }
                    await framing.send_message(
                        writer,
                        m.MatomlImage(
                            req_id=msg.req_id, status=st.OK,
                            version=self.changelog.version,
                            image=json.dumps(doc, sort_keys=True).encode(),
                        ),
                    )
        finally:
            if writer in self.shadow_writers:
                self.shadow_writers.remove(writer)
            self.shadow_status.pop(id(writer), None)

    # --- shadow personality: follow the active master -------------------------------------

    async def _shadow_follow(self) -> None:
        """masterconn analog (src/master/masterconn.cc:401-483): stream
        the changelog from the active master, applying through the same
        MetadataStore.apply path; download the image when behind."""
        while self.personality == "shadow":
            try:
                await self._shadow_follow_once()
            except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
                self.log.info("shadow link lost (%s); retrying", e)
            except asyncio.CancelledError:
                return
            await asyncio.sleep(1.0)

    async def _shadow_verify_checksum(self) -> None:
        if self.personality != "shadow":
            return
        try:
            reader, writer = await retrymod.bounded_wait(
                asyncio.open_connection(*self.active_addr), 5.0
            )
            await framing.send_message(
                writer,
                m.AdminCommand(
                    req_id=1, command="metadata-checksum", json="{}"
                ),
            )
            reply = await asyncio.wait_for(framing.read_message(reader), 5.0)
            writer.close()
        except (OSError, ConnectionError, asyncio.TimeoutError):
            return  # active unreachable; the follow loop handles that
        try:
            doc = json.loads(reply.json)
        except (AttributeError, ValueError):
            return
        if doc.get("version") != self.changelog.version:
            return  # mid-catch-up; compare only at equal versions
        # O(1) fast path: compare incremental digests. A full
        # recomputation (which alone can see state corrupted outside
        # apply()) runs in a FORKED child — O(namespace) must not stall
        # the shadow's replication loop — on mismatch and periodically
        # (background-updater analog).
        active_sum = doc.get("checksum")
        self._verify_probe_n = getattr(self, "_verify_probe_n", 0) + 1
        if (active_sum == self.meta.checksum()
                and self._verify_probe_n % 20 != 0):
            return  # fast-path match; deep check runs every 20th probe
        try:
            pid = os.fork() if _fork_safe() else -1
        except OSError:
            pid = -1
        if pid == 0:
            code = 1
            try:
                code = (
                    0 if f"{self.meta.full_digest():032x}" == active_sum
                    else 2
                )
            finally:
                os._exit(code)
        if pid > 0:
            rc = await self._wait_child(pid, timeout=600.0)
        else:  # fork unavailable: recompute on the loop (degraded)
            rc = 0 if f"{self.meta.full_digest():032x}" == active_sum else 2
        if rc == 0:
            if active_sum != self.meta.checksum():
                # state matches the active; only the local incremental
                # digest drifted — re-anchor (rare, O(namespace))
                self.log.warning(
                    "shadow incremental digest drift; re-anchoring"
                )
                self.meta.reset_digest()
            return
        self.log.error(
            "shadow metadata DIVERGED from active at v%d — "
            "re-downloading the image", self.changelog.version,
        )
        self._force_image_download = True
        w = getattr(self, "_follow_writer", None)
        if w is not None:
            w.close()  # the follow loop reconnects and re-downloads

    async def _shadow_follow_once(self) -> None:
        # bounded dial (unbounded-await audit): a blackholed active must
        # cost one 5 s attempt per follow-loop lap, never the OS SYN
        # timeout — an electing shadow has to notice promotion promptly
        reader, writer = await retrymod.bounded_wait(
            asyncio.open_connection(*self.active_addr), 5.0
        )
        self._follow_writer = writer
        try:
            await framing.send_message(
                writer,
                m.MltomaRegister(
                    req_id=1, version_known=self.changelog.version,
                    # our replayed cluster epoch: a deposed ex-primary we
                    # accidentally dial sees it is behind and steps down
                    epoch=self.meta.epoch,
                ),
            )
            hello = await framing.read_message(reader)
            if not isinstance(hello, m.MatomlRegisterReply) or hello.status != st.OK:
                raise ConnectionError("active master rejected shadow registration")
            if (
                constants_mod.ha_enabled()
                and getattr(hello, "epoch", 0)
                and hello.epoch < self.meta.epoch
            ):
                # zombie active: it never applied the epoch_bump we
                # replayed — following it would fork our history off the
                # elected leader's. Drop the link; the follow loop (or
                # the failover controller's next leader event) re-points.
                raise ConnectionError(
                    f"refusing stale active (epoch {hello.epoch} < "
                    f"ours {self.meta.epoch})"
                )
            if (
                hello.version > self.changelog.version
                or getattr(self, "_force_image_download", False)
            ):
                self._force_image_download = False
                await self._shadow_download_image(reader, writer)
            # replica reads may serve from here on: the stream is live
            # and we are at (or catching up to) the active's position
            self._follow_connected = True
            self._shadow_ack(writer, force=True)
            while self.personality == "shadow":
                msg = await framing.read_message(reader)
                if isinstance(msg, m.MatomlChangelogLine):
                    await self._shadow_apply(msg, reader, writer)
                    self._shadow_ack(writer)
        finally:
            self._follow_connected = False
            await retrymod.close_writer(writer, swallow_cancel=True)

    async def _shadow_ack_tick(self) -> None:
        w = getattr(self, "_follow_writer", None)
        if self._follow_connected and w is not None:
            self._shadow_ack(w, force=True)

    def _shadow_ack(self, writer, force: bool = False) -> None:
        """Throttled applied-position report to the active (lag
        telemetry input for `health` / the shadow_lag gauge)."""
        now = time.monotonic()
        if not force and now - self._last_shadow_ack < 1.0:
            return
        self._last_shadow_ack = now
        try:
            framing.write_message(
                writer,
                m.MltomaAck(
                    version=self.changelog.version,
                    serving=int(shadow_reads_enabled()),
                ),
            )
        except (ConnectionError, RuntimeError):
            pass  # the follow loop notices the dead link itself

    async def _shadow_download_image(self, reader, writer) -> None:
        await framing.send_message(writer, m.MltomaDownloadImage(req_id=2))
        while True:
            msg = await framing.read_message(reader)
            if isinstance(msg, m.MatomlImage):
                break
            # changelog lines racing the download are superseded by it
        if msg.status != st.OK:
            raise ConnectionError("image download failed")
        doc = json.loads(msg.image.decode())
        self.meta.load_sections(doc)
        self.changelog.close()
        self.changelog.version = msg.version
        self.changelog.open()
        save_image(self.data_dir, msg.version, self.meta.to_sections())
        # load_sections REPLACED self.meta.registry: live mirror links
        # hold cs_ids from the old table — close them so chunkservers
        # re-register (fresh part reports) against the new registry
        for w in list(self._mirror_cs_writers):
            try:
                w.close()
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        self.log.info("shadow: downloaded metadata image at v%d", msg.version)

    async def _shadow_apply(self, line: m.MatomlChangelogLine, reader, writer) -> None:
        if line.version <= self.changelog.version:
            return  # duplicate during catch-up
        if line.version != self.changelog.version + 1:
            self.log.warning(
                "shadow: changelog gap (have v%d, got v%d) — re-downloading",
                self.changelog.version, line.version,
            )
            await self._shadow_download_image(reader, writer)
            return
        op = json.loads(line.line)
        self.meta.apply(op)
        self.changelog.append(op)  # assigns the same version, persists

    def observe_peer_epoch(self, peer_epoch: int) -> bool:
        """Zombie-fencing input: every register/heartbeat surface feeds
        the peer's highest observed cluster epoch here. An ACTIVE master
        seeing a HIGHER epoch than its own has been superseded by an
        election it never heard (partitioned ex-primary): it steps down
        to shadow on the spot — all mutating timers and loops guard on
        ``is_active``, so demotion mid-run is safe — instead of merging
        late writes into a forked history. Returns True when the caller
        must refuse/close its link (we just fenced ourselves).

        Epoch 0 is a pre-HA peer (or LZ_HA off end to end): fencing
        disengaged, byte-for-byte the manual-promotion behavior."""
        if not peer_epoch or not constants_mod.ha_enabled():
            return False
        if self.is_active and peer_epoch > self.meta.epoch:
            self.log.error(
                "FENCED: peer reports cluster epoch %d > our %d — a newer "
                "master was elected; stepping down to shadow",
                peer_epoch, self.meta.epoch,
            )
            self.metrics.counter("ha_fenced").inc()
            self.personality = "shadow"
            return True
        return False

    def promote(self) -> None:
        """Shadow -> active master (promoteAutoToMaster analog,
        personality.h:69). Chunkservers and clients find us by cycling
        their configured master address lists."""
        if self.personality == "master":
            return
        self.personality = "master"
        self._follow_connected = False
        if self._shadow_task is not None:
            self._shadow_task.cancel()
            self._shadow_task = None
        # passive chunkserver mirror links never carry commands: close
        # them so every chunkserver re-registers over a command-capable
        # link (their heartbeat loops reconnect within one interval)
        for w in list(self._mirror_cs_writers):
            try:
                w.close()
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        if constants_mod.ha_enabled():
            # fenced promotion: the new active's FIRST committed write
            # claims the next cluster epoch. It rides the changelog
            # (replayed by every shadow/metalogger) and is stamped on
            # every register/heartbeat ack from here on, so a zombie
            # ex-primary's links are refused by its own peers. With
            # LZ_HA off no bump is committed and every epoch field
            # stays 0 — manual promotion behaves exactly as before.
            self.commit({"op": "epoch_bump", "epoch": self.meta.epoch + 1})
        self.log.info(
            "promoted to active master at v%d (epoch %d)",
            self.changelog.version, self.meta.epoch,
        )

    def follow(self, addr: tuple[str, int]) -> None:
        """(Re-)point this node at the CURRENT active master and stream
        its changelog. The failover controller calls this whenever the
        election names a leader: a shadow must track the live leader —
        not its boot-time ACTIVE_MASTER, which may itself have been
        demoted — and a demoted master must start following, or every
        replica silently stays behind and a later promotion loses
        acknowledged writes (r05 HA e2e flake root cause)."""
        if self.personality == "master" or self.active_addr != addr:
            self.personality = "shadow"
            self.active_addr = addr
            if self._shadow_task is not None:
                self._shadow_task.cancel()
            self._shadow_task = self.spawn(self._shadow_follow())
            self.log.info(
                "following active master at %s:%d (v%d)",
                addr[0], addr[1], self.changelog.version,
            )

    # --- admin ----------------------------------------------------------------------------

    # mutating admin surface requires challenge-response auth when an
    # ADMIN_PASSWORD is configured (registered_admin_connection.cc)
    ADMIN_PRIVILEGED = frozenset({
        "tweaks-set", "save-metadata", "promote-shadow", "reload", "stop",
        "rremove-task", "setgoal-task", "settrashtime-task",
        "synth-populate",
    })

    async def _admin_message(self, writer, msg, state: dict | None = None) -> None:
        state = state if state is not None else {}
        if isinstance(msg, m.AdminCommand):
            reply = self.admin_gate(msg, state)
            if reply is not None:
                await framing.send_message(writer, reply)
                return
        if isinstance(msg, m.AdminInfo):
            info = {
                "personality": self.personality,
                "version": self.changelog.version,
                "inodes": len(self.meta.fs.nodes),
                "chunks": len(self.meta.registry.chunks),
                "chunkservers": [
                    {
                        "cs_id": s.cs_id, "host": s.host, "port": s.port,
                        "label": s.label, "connected": s.connected,
                        "total_space": s.total_space, "used_space": s.used_space,
                        # mirror=True: a shadow's passive location feed,
                        # NOT a command link — active-discovery tooling
                        # must skip these
                        "mirror": s.mirror,
                    }
                    for s in self.meta.registry.servers.values()
                ],
                "sessions": len(self.sessions),
                "open_files": len(self.meta.fs.open_refs),
                "sustained_files": len(self.meta.fs.sustained),
                "trash_files": len(self.meta.fs.trash),
            }
            await framing.send_message(
                writer,
                m.AdminInfoReply(req_id=msg.req_id, status=st.OK, json=json.dumps(info)),
            )
            return
        if isinstance(msg, m.AdminCommand):
            reply = await self._admin_command(msg)
            await framing.send_message(writer, reply)

    def _ha_status(self) -> dict:
        """The `ha` section of health / the admin `ha` command: this
        node's failover posture. Always present (operators check it
        FIRST during an incident); election fields appear only when a
        FailoverController is wired (__main__ with ELECTION_ID)."""
        doc: dict = {
            "enabled": constants_mod.ha_enabled(),
            "personality": self.personality,
            "epoch": self.meta.epoch,
            "fenced": int(self.metrics.counter("ha_fenced").total),
        }
        ctrl = self.ha_controller
        if ctrl is not None:
            doc.update(ctrl.status())
        return doc

    def cluster_health(self, evaluate_chunks: bool = True) -> dict:
        """The cluster-wide health rollup: this master's own snapshot,
        every chunkserver's heartbeat-folded snapshot, and chunk-level
        danger, aggregated to one status.

        Chunk danger comes from the registry's maintained aggregate
        (published by the routine health-walk cycle — the evaluations
        the walk already pays for), NEVER a full-table sweep: /health
        is a probe endpoint monitors may poll every few seconds, and
        the old O(all-chunks) evaluation was the master's biggest
        per-probe stall at 1M chunks (test_scalability pins the bound).
        ``evaluate_chunks=False`` (the per-tick gauge path) uses the
        endangered queue length instead of the aggregate.

        Freshness contract: ``endangered`` is backstopped by the live
        FIFO (a chunkserver death shows within a tick); ``lost`` is
        cycle-fresh — exact as of the last completed walk cycle (or
        the post-restart bootstrap sweep, registry.danger_bootstrap),
        lagging a fresh loss by up to one cycle. Alert on
        status/endangered for immediacy; ``lost`` is the precise
        classification, not the tripwire."""
        from lizardfs_tpu.runtime import slo as slomod

        master_snap = self.health_snapshot()
        if evaluate_chunks:
            endangered, lost, _ = self.meta.registry.danger_counts
            # a fresh burst (chunkserver died seconds ago) shows in the
            # endangered FIFO before the walk cycle republishes
            endangered = max(endangered, len(self.meta.registry.endangered))
        else:
            endangered = len(self.meta.registry.endangered)
            lost = 0
        servers = {}
        cs_unhealthy = 0
        breaches = master_snap.get("breaches_total", 0)
        worst_burn = 0.0
        for s in self.meta.registry.servers.values():
            snap = dict(self.cs_health.get(s.cs_id, {}))
            snap["connected"] = s.connected
            if not s.connected:
                # "down" is the whole signal for a dead server: its
                # last snapshot's burn/breach figures are frozen at
                # heartbeat age and must not keep inflating the fleet
                # aggregates (burn decays, frozen values don't)
                snap = {"connected": False, "status": "down"}
                cs_unhealthy += 1
            elif not snap.get("status"):
                snap["status"] = "unknown"  # old peer: no health in hb
            elif snap["status"] != "ok":
                cs_unhealthy += 1
            if s.connected:
                breaches += snap.get("breaches_total", 0)
                for cls in snap.get("slo", {}).values():
                    worst_burn = max(worst_burn, cls.get("burn_fast", 0.0))
            servers[s.cs_id] = snap
        status = master_snap["status"]
        for snap in servers.values():
            if snap["status"] == "down":
                status = slomod.worst_status(status, "degraded")
            elif snap["status"] != "unknown":
                status = slomod.worst_status(status, snap["status"])
        if endangered:
            status = slomod.worst_status(status, "degraded")
        if lost:
            status = slomod.worst_status(status, "critical")
        for cls in master_snap.get("slo", {}).values():
            worst_burn = max(worst_burn, cls.get("burn_fast", 0.0))
        # per-shadow replication lag (changelog positions): shadows ack
        # their applied version over the changelog stream; `health`
        # names each one so a lagging replica is visible before clients
        # notice the staleness retries
        now_m = time.monotonic()
        shadows = [
            {
                "version": snap["version"],
                "lag": max(self.changelog.version - snap["version"], 0),
                "serving": snap["serving"],
                "age_s": round(now_m - snap["ts"], 1),
            }
            for snap in self.shadow_status.values()
        ]
        # protocol gateways, by role, from the session registry: the
        # rollup names every front door (fuse clients register as
        # pyclient/fuse, gateways as nfs-gateway / s3-gateway), so "is
        # the s3 tier up" is answerable from `lizardfs-admin health`
        gateways: dict[str, int] = {"nfs": 0, "s3": 0}
        for sess in self.sessions.values():
            if not sess.get("connected"):
                continue
            info = str(sess.get("info", ""))
            if info.startswith("nfs-gateway"):
                gateways["nfs"] += 1
            elif info.startswith("s3-gateway"):
                gateways["s3"] += 1
        # QoS: NAME currently-throttled tenants so "who is being shed"
        # is answerable from `lizardfs-admin health` during an incident
        qos_doc: dict = {}
        if constants_mod.qos_enabled() and (
            self.qos.armed or self.qos.sheds
        ):
            snap = self.qos.snapshot()
            qos_doc = {
                "armed": snap["armed"],
                "throttled": self.qos.throttled_tenants(),
                "sheds": snap["sheds"],
            }
            # per-tenant SLO objectives (QOS_CFG p99_ms): evaluate each
            # configured tenant's worst observed master-leg p99 across
            # its connected sessions against its objective
            if self.qos.objectives:
                qos_doc["objectives"] = self._qos_objective_report()
        # heat: the hottest chunks and any standing goal boosts, so an
        # operator reading a degraded rollup sees the hot spot (and the
        # adaptive-replication response) without a second probe
        heat_doc: dict = {}
        if constants_mod.heat_enabled():
            boosted = {
                cid: self.meta.registry.chunks[cid].boost
                for cid in self.meta.registry.boosted
                if cid in self.meta.registry.chunks
            }
            heat_doc = {
                "chunks": self.heat.top("chunk", 4),
                "boosted": {str(c): b for c, b in boosted.items()},
                "qos_pressure": sorted(self._heat_qos_pressure),
            }
        return {
            "status": status,
            "master": master_snap,
            "chunkservers": servers,
            "shadows": shadows,
            "gateways": gateways,
            "qos": qos_doc,
            "heat": heat_doc,
            "ha": self._ha_status(),
            "tape": {
                "servers": len(self.ts_links),
                "pending": len(self.tape_pending),
                "demoted": len(self.meta.demoted),
                "recalling": len(self._recall_inflight),
            },
            "summary": {
                "endangered": endangered,
                "lost": lost,
                "cs_unhealthy": cs_unhealthy,
                "breaches_total": breaches,
                "worst_burn_fast": round(worst_burn, 3),
                "shadows": len(self.shadow_writers),
                "shadow_lag_max": max(
                    (s["lag"] for s in shadows), default=0
                ),
            },
        }

    def _qos_objective_report(self) -> dict:
        """Per-tenant SLO check: worst session_ops p99 (ms) across a
        tenant's connected sessions vs. its configured ``p99_ms``
        objective. Cold path (health/admin only)."""
        out: dict[str, dict] = {}
        by_tenant: dict[str, list[int]] = {}
        for sid, sess in self.sessions.items():
            if sess.get("connected"):
                by_tenant.setdefault(
                    sess.get("tenant", qosmod.DEFAULT_TENANT), []
                ).append(sid)
        variants = self.metrics.labeled_timings.get("session_ops", {})
        for tenant, objective in self.qos.objectives.items():
            worst = 0.0
            for key, timing in variants.items():
                labels = dict(key)
                for sid in by_tenant.get(tenant, ()):
                    if labels.get("session") == f"s{sid}":
                        worst = max(
                            worst, timing.quantile_us(0.99) / 1e3
                        )
            out[tenant] = {
                "p99_ms": round(worst, 3),
                "objective_ms": objective,
                "breached": bool(worst > objective),
            }
        return out

    def top_report(self, k: int = 16, resolution: str = "sec") -> dict:
        """The cluster-wide workload rollup `lizardfs-admin top` and
        the webui ``/api/top`` render: per-session op rates / bytes /
        p99 / exemplars from this master's own accounting, decorated
        with session identity, merged with every chunkserver's
        heartbeat-folded top-K (data-plane bytes) and every gateway's
        pushed protocol-op summary, plus short metrics-history rings so
        the view shows trends, not just instants."""
        now = time.time()
        sessions_doc: dict[str, dict] = {}
        for row in self.session_ops.top(k):
            sessions_doc[row["session"]] = {"master": row}
        # decorate with the session registry's identity; sessions only
        # known through a gateway push still get a row
        for sid, sess in self.sessions.items():
            label = f"s{sid}"
            if label not in sessions_doc and sid not in self.session_stats:
                continue
            entry = sessions_doc.setdefault(label, {})
            entry["info"] = str(sess.get("info", ""))
            entry["ip"] = sess.get("ip", "")
            entry["connected"] = bool(sess.get("connected"))
            entry["tenant"] = sess.get("tenant", qosmod.DEFAULT_TENANT)
            stats = self.session_stats.get(sid)
            if stats is not None:
                entry["gateway"] = dict(stats)
                entry["gateway"]["age_s"] = round(
                    now - stats.get("ts", now), 1
                )
                # client-pushed phase breakdowns ride the same stats
                # doc (Client.push_session_stats); lift them to the
                # entry so `top` renders each session's read/write
                # roofline without digging into the gateway sub-doc
                for key in ("read_phases", "write_phases"):
                    if stats.get(key):
                        entry[key] = stats[key]
        # chunkserver legs: per-session data-plane summaries folded
        # into heartbeats (health_json "sessions"); merged per session
        chunkservers: dict[str, list] = {}
        for cs_id, snap in self.cs_health.items():
            rows = snap.get("sessions") or []
            if not rows:
                continue
            chunkservers[str(cs_id)] = rows
            for row in rows:
                entry = sessions_doc.setdefault(row["session"], {})
                entry.setdefault("chunkservers", {})[str(cs_id)] = row
        history = {
            name: self.metrics.history(name, resolution)
            for name in (
                "session_ops_rate", "sessions_active",
                "cluster_health_status", "cluster_slo_breaches",
                "endangered_queue",
                "slo_locate_burn_fast",
            )
        }
        # per-tenant rollup: aggregate the master-leg rates of each
        # tenant's sessions + whether admission is currently shedding
        # it (the `top` tenant column's source)
        tenants_doc: dict[str, dict] = {}
        throttled = set(
            self.qos.throttled_tenants()
            if constants_mod.qos_enabled() else ()
        )
        for label, entry in sessions_doc.items():
            tenant = entry.get("tenant")
            if tenant is None:
                continue
            row = tenants_doc.setdefault(
                tenant, {"sessions": 0, "rate_ops": 0.0, "throttled": False}
            )
            row["sessions"] += 1
            row["rate_ops"] = round(
                row["rate_ops"]
                + entry.get("master", {}).get("rate_ops", 0.0), 2
            )
        for tenant in throttled:
            tenants_doc.setdefault(
                tenant, {"sessions": 0, "rate_ops": 0.0}
            )["throttled"] = True
        return {
            "ts": now,
            "enabled": accounting.enabled(),
            "resolution": resolution,
            "sessions": sessions_doc,
            "chunkservers": chunkservers,
            "tenants": tenants_doc,
            "totals": {
                "rate_ops": self.session_ops.total_rate(),
                "sessions_tracked": self.session_ops.active_sessions(),
                "sessions_connected": sum(
                    1 for s in self.sessions.values() if s.get("connected")
                ),
            },
            "slo": self.slo.snapshot(),
            "history": history,
        }

    async def _admin_command(self, msg: m.AdminCommand) -> m.AdminReply:
        if msg.command == "top":
            try:
                payload = json.loads(msg.json) if msg.json else {}
                k = int(payload.get("k", 16))
                resolution = str(payload.get("resolution", "sec"))
            except (ValueError, TypeError):
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL, json="{}"
                )
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(self.top_report(k, resolution)),
            )
        if msg.command == "health":
            # cluster-wide rollup (overrides the base daemon's
            # single-process snapshot): one command answers "is the
            # cluster healthy" — also served at the webui /health
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(self.cluster_health()),
            )
        if msg.command == "ha":
            # failover posture: personality, cluster epoch, election
            # state (term/leader/quorum when a controller is wired),
            # promotion/fencing counters — `lizardfs-admin ha`
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(self._ha_status()),
            )
        if msg.command == "qos":
            # show/set fair-share weights and limits LIVE (the tweaks
            # plane is the other write path for the per-class rates;
            # SIGHUP re-reads QOS_CFG wholesale). Payload keys:
            #   {"weight": {tenant: w}}, {"rate": {class: ops_s}},
            #   {"data_inflight_mb": v}, {"data_bps": v},
            #   {"rebuild_weight": v}  — empty payload = show
            try:
                payload = json.loads(msg.json) if msg.json else {}
                for tenant, w in (payload.get("weight") or {}).items():
                    self.qos.set_weight(str(tenant), float(w))
                    self.qos_doc.setdefault("tenants", {}).setdefault(
                        str(tenant), {}
                    )["weight"] = float(w)
                for cls, rate in (payload.get("rate") or {}).items():
                    self.qos.set_rate(str(cls), float(rate))
                    self._qos_rate_tweaks[str(cls)].value = float(rate)
                    self.qos_doc.setdefault("rates", {})[str(cls)] = (
                        float(rate)
                    )
                for key in ("data_inflight_mb", "data_bps",
                            "rebuild_weight"):
                    if key in payload:
                        self.qos_doc[key] = float(payload[key])
                        self.qos.generation += 1
                if payload:
                    self._qos_cs_cache = ()
            except (ValueError, TypeError) as e:
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL,
                    json=json.dumps({"error": str(e)[:200]}),
                )
            doc = self.qos.snapshot()
            doc["enabled"] = constants_mod.qos_enabled()
            doc["data"] = {
                "inflight_mb": float(
                    self.qos_doc.get("data_inflight_mb", 0) or 0
                ),
                "data_bps": float(self.qos_doc.get("data_bps", 0) or 0),
                "rebuild_weight": float(
                    self.qos_doc.get("rebuild_weight", 1.0)
                ),
            }
            doc["default_tenant"] = self.qos_tenants.default
            doc["match_rules"] = list(self.qos_tenants.rules)
            if self.qos.objectives:
                doc["objectives"] = self._qos_objective_report()
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
            )
        basic = self.handle_admin_basics(msg)
        if basic is not None:
            return basic
        if msg.command == "save-metadata":
            await self._dump_image()
            return m.AdminReply(req_id=msg.req_id, status=st.OK, json="{}")
        if msg.command == "reload":
            self.reload()
            result = getattr(self, "_last_reload", {})
            return m.AdminReply(
                req_id=msg.req_id,
                # scripts check the status like they do for tweaks-set:
                # a partial reload is a failure, details in the JSON
                status=st.OK if not result.get("failed") else st.EINVAL,
                json=json.dumps(result),
            )
        if msg.command == "heat":
            # the cluster heat map: hottest chunks/inodes/servers with
            # decayed scores, thresholds, standing goal boosts, and any
            # heat-armed QoS pressure (lizardfs-admin heat / webui)
            registry = self.meta.registry
            doc = self.heat.snapshot({
                cid: registry.chunks[cid].boost
                for cid in registry.boosted if cid in registry.chunks
            })
            doc["enabled"] = constants_mod.heat_enabled()
            doc["server_load"] = {
                str(cs): round(v, 3)
                for cs, v in sorted(registry.server_load.items())
            }
            doc["qos_pressure"] = sorted(self._heat_qos_pressure)
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
            )
        if msg.command == "rebuild-status":
            # RebuildEngine progress: queue depths by priority class,
            # active rebuilds, throttle config, rate + backlog ETA —
            # plus the endangered FIFO feeding it
            doc = self.rebuild.status()
            doc["endangered_queue"] = len(self.meta.registry.endangered)
            doc["stale_version_chunks"] = len(
                self.meta.registry.stale_versions
            )
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
            )
        if msg.command == "chunks-health":
            # budgeted incremental walk: an accurate on-demand count
            # still visits every chunk, but in slices with yield points
            # so a 1M-chunk table never stalls client service for the
            # whole evaluation (the old loop was a single synchronous
            # full-registry sweep)
            healthy = endangered = lost = 0
            registry = self.meta.registry
            ids = list(registry.chunks.keys())
            for start in range(0, len(ids), 4096):
                for cid in ids[start:start + 4096]:
                    chunk = registry.chunks.get(cid)
                    if chunk is None:
                        continue  # deleted while we yielded
                    state = registry.evaluate(chunk)
                    if not state.is_readable:
                        lost += 1
                    elif state.is_endangered or state.missing_parts:
                        endangered += 1
                    else:
                        healthy += 1
                await asyncio.sleep(0)
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({
                    "healthy": healthy, "endangered": endangered, "lost": lost,
                }),
            )
        if msg.command == "promote-shadow":
            if self.personality == "master":
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL,
                    json='{"error": "already active"}',
                )
            self.promote()
            return m.AdminReply(req_id=msg.req_id, status=st.OK, json="{}")
        if msg.command in ("rremove-task", "setgoal-task", "settrashtime-task"):
            from lizardfs_tpu.master import tasks as tasks_mod

            try:
                payload = json.loads(msg.json)
                now = int(time.time())
                if msg.command == "rremove-task":
                    gen = tasks_mod.recursive_remove_ops(
                        self.meta.fs, int(payload["parent"]),
                        str(payload["name"]), now,
                    )
                elif msg.command == "setgoal-task":
                    gen = tasks_mod.subtree_setgoal_ops(
                        self.meta.fs, int(payload["inode"]),
                        int(payload["goal"]), now,
                    )
                else:
                    gen = tasks_mod.subtree_settrashtime_ops(
                        self.meta.fs, int(payload["inode"]),
                        int(payload["seconds"]), now,
                    )
                task = self.task_manager.submit(msg.command, gen)
            except (KeyError, ValueError, fsmod.FsError) as e:
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL,
                    json=json.dumps({"error": str(e)[:200]}),
                )
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(task.to_dict())
            )
        if msg.command == "list-tasks":
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps([
                    t.to_dict() for t in self.task_manager.tasks.values()
                ]),
            )
        if msg.command == "synth-populate":
            # storm-bench loader: bulk-create a synthetic namespace +
            # chunk registry (files/chunks/servers) through the normal
            # commit path so shadows converge on it from the changelog.
            # Batched commits with yield points: the master keeps
            # serving while a million inodes stream in.
            if not self.is_active:
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL,
                    json='{"error": "not the active master"}',
                )
            try:
                payload = json.loads(msg.json or "{}")
                files = int(payload.get("files", 0))
                servers = int(payload.get("servers", 0))
                copies = int(payload.get("copies", 1))
                dir_name = str(payload.get("dir", "synthstorm"))
            except (ValueError, TypeError) as e:
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL,
                    json=json.dumps({"error": str(e)[:200]}),
                )
            fs = self.meta.fs
            now = int(time.time())
            root = fs.node(fsmod.ROOT_INODE)
            dir_inode = root.children.get(dir_name)
            if dir_inode is None:
                dir_inode = fs.alloc_inode()
                self.commit({
                    "op": "mknode", "parent": fsmod.ROOT_INODE,
                    "name": dir_name, "inode": dir_inode, "ftype":
                    fsmod.TYPE_DIR, "mode": 0o755, "uid": 0, "gid": 0,
                    "ts": now, "goal": 1, "trash_time": 0,
                })
            created = 0
            batch = 10_000
            while created < files:
                n = min(batch, files - created)
                base_inode = fs.next_inode
                fs.next_inode += n  # pre-reserve like alloc_inode
                base_chunk = self.meta.registry.next_chunk_id
                self.meta.registry.next_chunk_id += n
                self.commit({
                    "op": "synth_populate", "parent": dir_inode,
                    "base_inode": base_inode, "base_chunk": base_chunk,
                    "count": n, "servers": servers, "copies": copies,
                    "ts": now,
                })
                created += n
                await asyncio.sleep(0)
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({
                    "files": files, "servers": servers,
                    "dir_inode": dir_inode,
                    "inodes": len(fs.nodes),
                    "chunks": len(self.meta.registry.chunks),
                    "version": self.changelog.version,
                }),
            )
        if msg.command == "metadata-checksum":
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({
                    "version": self.changelog.version,
                    "checksum": self.meta.checksum(self.changelog.version),
                }),
            )
        return m.AdminReply(req_id=msg.req_id, status=st.EINVAL, json="{}")


def _attr_of(node) -> m.Attr:
    return m.Attr(
        inode=node.inode, ftype=node.ftype, mode=node.mode, uid=node.uid,
        gid=node.gid, atime=node.atime, mtime=node.mtime, ctime=node.ctime,
        nlink=node.nlink, length=node.length, goal=node.goal,
        trash_time=node.trash_time, eattr=node.eattr,
    )


def _null_attr() -> m.Attr:
    return m.Attr(
        inode=0, ftype=0, mode=0, uid=0, gid=0, atime=0, mtime=0, ctime=0,
        nlink=0, length=0, goal=0, trash_time=0,
    )
