"""Client export rules + rack topology.

Exports (mfsexports.cfg analog, reference: src/master/exports.cc):
lines of ``ADDRESS DIRECTORY OPTIONS``:

    *              /        rw,alldirs
    10.0.0.0/8     /data    ro
    10.1.2.3       /        rw,maproot=0,password=secret

Matching is most-specific-prefix-first; a client with no matching rule
is refused at registration. Options: ``ro``/``rw``, ``maproot=UID``
(root squash target), ``password=...``.

Topology (mfstopology.cfg analog, reference: src/master/topology.h):
lines of ``ADDRESS RACKID`` mapping networks to racks; the master sorts
chunk locations so same-rack chunkservers come first for each client.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass


def _parse_net(s: str) -> ipaddress.IPv4Network:
    if s == "*":
        return ipaddress.ip_network("0.0.0.0/0")
    if "/" not in s:
        s += "/32"
    return ipaddress.ip_network(s, strict=False)


@dataclass
class ExportRule:
    net: ipaddress.IPv4Network
    path: str
    readonly: bool = False
    maproot: int | None = None
    password: str = ""

    @classmethod
    def parse(cls, line: str) -> "ExportRule | None":
        line = line.split("#", 1)[0].strip()
        if not line:
            return None
        parts = line.split()
        if len(parts) < 2:
            raise ValueError(f"malformed export line: {line!r}")
        net = _parse_net(parts[0])
        path = parts[1]
        rule = cls(net=net, path=path)
        for opt in (parts[2].split(",") if len(parts) > 2 else []):
            opt = opt.strip()
            if opt == "ro":
                rule.readonly = True
            elif opt in ("rw", "alldirs", ""):
                pass
            elif opt.startswith("maproot="):
                rule.maproot = int(opt.split("=", 1)[1])
            elif opt.startswith("password="):
                rule.password = opt.split("=", 1)[1]
            else:
                raise ValueError(f"unknown export option {opt!r}")
        return rule


class Exports:
    def __init__(self, rules: list[ExportRule] | None = None):
        # default: everyone, rw, whole tree (open cluster)
        self.rules = rules if rules is not None else [
            ExportRule(net=_parse_net("*"), path="/")
        ]

    @classmethod
    def load(cls, text: str) -> "Exports":
        rules = []
        for lineno, line in enumerate(text.splitlines(), 1):
            try:
                rule = ExportRule.parse(line)
            except ValueError as e:
                raise ValueError(f"exports line {lineno}: {e}") from None
            if rule:
                rules.append(rule)
        return cls(rules)

    def match(self, ip: str, password: str = "") -> ExportRule | None:
        """Most-specific matching rule whose password matches."""
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            addr = ipaddress.ip_address("127.0.0.1")
        best: ExportRule | None = None
        for rule in self.rules:
            if addr in rule.net:
                if rule.password and rule.password != password:
                    continue
                if best is None or rule.net.prefixlen > best.net.prefixlen:
                    best = rule
        return best


class Topology:
    """IP network -> rack id; distance 0 = same rack, 1 = different."""

    def __init__(self):
        self.nets: list[tuple[ipaddress.IPv4Network, int]] = []

    @classmethod
    def load(cls, text: str) -> "Topology":
        topo = cls()
        for lineno, line in enumerate(text.splitlines(), 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 2:
                raise ValueError(f"topology line {lineno}: {line!r}")
            topo.nets.append((_parse_net(parts[0]), int(parts[1])))
        return topo

    def rack_of(self, ip: str) -> int:
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return -1
        best_len = -1
        rack = -1
        for net, rid in self.nets:
            if addr in net and net.prefixlen > best_len:
                best_len = net.prefixlen
                rack = rid
        return rack

    def distance(self, ip_a: str, ip_b: str) -> int:
        if ip_a == ip_b:
            return 0
        ra, rb = self.rack_of(ip_a), self.rack_of(ip_b)
        if ra >= 0 and ra == rb:
            return 1
        return 2
