"""Minimum-cost slot→server assignment for labeled placement.

The reference solves label-constrained chunk placement with an
auction-style linear assignment optimizer
(src/common/linear_assignment_optimizer.h) because greedy label
matching can strand constrained slots: with slots {A, _} and servers
{s1:A}, a greedy wildcard pass that grabs s1 first leaves the A slot
unplaceable even though a perfect assignment exists. This module is the
same idea with the classic Hungarian algorithm (O(n^3), n = slots ≤ 40
per goal — microseconds at that size).

Costs are integers: a label mismatch dominates everything, then fuller
servers cost more (spreads data), then a small caller-supplied jitter
keeps repeated placements from hammering one server.
"""

from __future__ import annotations

MISMATCH = 10**9  # label violation: worth any amount of imbalance


def solve(cost: list[list[int]]) -> list[int]:
    """Hungarian algorithm: ``cost[i][j]`` = cost of slot i on column j.

    Returns per-slot column indices minimizing total cost. Requires
    len(cost) <= len(cost[0]); columns may stay unused.
    """
    n, m = len(cost), len(cost[0])
    assert n <= m, "need at least as many columns as slots"
    INF = float("inf")
    # potentials + matching, the classic O(n^2 m) shortest-augmenting-path
    # formulation (1-indexed internals)
    u = [0.0] * (n + 1)
    v = [0.0] * (m + 1)
    match = [0] * (m + 1)  # column -> row matched (0 = free)
    way = [0] * (m + 1)
    for i in range(1, n + 1):
        match[0] = i
        j0 = 0
        minv = [INF] * (m + 1)
        used = [False] * (m + 1)
        while True:
            used[j0] = True
            i0, delta, j1 = match[j0], INF, 0
            for j in range(1, m + 1):
                if not used[j]:
                    cur = cost[i0 - 1][j - 1] - u[i0] - v[j]
                    if cur < minv[j]:
                        minv[j] = cur
                        way[j] = j0
                    if minv[j] < delta:
                        delta = minv[j]
                        j1 = j
            for j in range(m + 1):
                if used[j]:
                    u[match[j]] += delta
                    v[j] -= delta
                else:
                    minv[j] -= delta
            j0 = j1
            if match[j0] == 0:
                break
        while j0:
            j1 = way[j0]
            match[j0] = match[j1]
            j0 = j1
    out = [0] * n
    for j in range(1, m + 1):
        if match[j]:
            out[match[j] - 1] = j - 1
    return out


def assign_slots(
    slot_labels: list[str],
    servers: list,
    jitter,
    wildcard: str = "_",
    load=None,
) -> list[int]:
    """Optimal distinct-server choice for one slice's slots.

    ``servers`` expose ``.label`` and ``.free_space``; ``jitter(i, j)``
    -> small int noise. ``load(j)`` (optional) -> observed load score
    for column j in [0, 1+] (heartbeat health + queue depth + heat
    share); a loaded server costs as much extra as full fullness would,
    so placement leans away from hot servers without ever violating a
    label. Requires len(servers) >= len(slot_labels); the caller
    handles the fewer-servers-than-slots case (repeats allowed)
    separately. Returns server indices per slot; mismatched labels are
    only used when no matching assignment exists (placed beats
    unplaced).
    """
    max_free = max((s.free_space for s in servers), default=0) or 1
    cost = []
    for i, want in enumerate(slot_labels):
        row = []
        for j, s in enumerate(servers):
            c = 0 if (want == wildcard or s.label == want) else MISMATCH
            # fuller servers cost more: scale fullness into [0, 1000]
            c += 1000 - (s.free_space * 1000) // max_free
            if load is not None:
                # observed load scales into the same [0, 1000] band as
                # fullness (load 0 — the heat-off state — adds nothing)
                c += min(int(load(j) * 1000), 1000)
            c += int(jitter(i, j))
            row.append(c)
        cost.append(row)
    return solve(cost)
