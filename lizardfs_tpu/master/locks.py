"""File locking: POSIX byte-range locks + BSD flock (held state).

Mirror of the reference's lock engine (reference: src/master/locks.h:
29-224 LockRanges/FileLocks): per-file interval lists of shared/
exclusive locks, owner = (session_id, owner_token); overlapping ranges
from one owner merge/split POSIX-style. Only HELD locks live here —
they replicate via the changelog and persist in the metadata image.
Blocked (waiting) requests are live-master-only state queued by the
master server, which re-tests and commits grants as locks release.
"""

from __future__ import annotations

from dataclasses import dataclass, field

LOCK_UNLOCK = 0
LOCK_SHARED = 1
LOCK_EXCLUSIVE = 2

MAX_OFFSET = (1 << 63) - 1


@dataclass(frozen=True)
class Owner:
    session_id: int
    token: int  # process/fd discriminator within the session


@dataclass
class Range:
    start: int
    end: int  # exclusive
    ltype: int
    owner: Owner

    def overlaps(self, start: int, end: int) -> bool:
        return self.start < end and start < self.end


class FileLocks:
    """Locks of one file: interval list + FIFO pending queue."""

    def __init__(self):
        self.ranges: list[Range] = []

    # --- queries -----------------------------------------------------------

    def test(self, owner: Owner, start: int, end: int, ltype: int) -> Range | None:
        """First conflicting range held by another owner (POSIX F_GETLK)."""
        for r in self.ranges:
            if r.owner == owner or not r.overlaps(start, end):
                continue
            if ltype == LOCK_EXCLUSIVE or r.ltype == LOCK_EXCLUSIVE:
                return r
        return None

    # --- mutations ---------------------------------------------------------

    def _remove_owner_range(self, owner: Owner, start: int, end: int) -> None:
        """Carve [start, end) out of this owner's ranges (POSIX split)."""
        out: list[Range] = []
        for r in self.ranges:
            if r.owner != owner or not r.overlaps(start, end):
                out.append(r)
                continue
            if r.start < start:
                out.append(Range(r.start, start, r.ltype, r.owner))
            if r.end > end:
                out.append(Range(end, r.end, r.ltype, r.owner))
        self.ranges = out

    def _merge_owner(self, owner: Owner) -> None:
        """Coalesce adjacent same-type ranges of one owner."""
        mine = sorted(
            (r for r in self.ranges if r.owner == owner), key=lambda r: r.start
        )
        others = [r for r in self.ranges if r.owner != owner]
        merged: list[Range] = []
        for r in mine:
            if merged and merged[-1].ltype == r.ltype and merged[-1].end >= r.start:
                merged[-1].end = max(merged[-1].end, r.end)
            else:
                merged.append(r)
        self.ranges = others + merged

    def apply(self, owner: Owner, start: int, end: int, ltype: int) -> bool:
        """Set/clear a held lock. True = applied; False = refused
        (conflict — the caller maps to LOCKED or queues the waiter)."""
        if ltype == LOCK_UNLOCK:
            self._remove_owner_range(owner, start, end)
            return True
        if self.test(owner, start, end, ltype) is not None:
            return False
        self._remove_owner_range(owner, start, end)
        self.ranges.append(Range(start, end, ltype, owner))
        self._merge_owner(owner)
        return True

    def release_session(self, session_id: int) -> None:
        self.ranges = [r for r in self.ranges if r.owner.session_id != session_id]

    @property
    def empty(self) -> bool:
        return not self.ranges


class LockManager:
    """All files' locks. flock and POSIX locks live in independent
    spaces, as on Linux: a whole-file flock never conflicts with a
    byte-range fcntl lock."""

    def __init__(self):
        self.posix_files: dict[int, FileLocks] = {}
        self.flock_files: dict[int, FileLocks] = {}

    @staticmethod
    def _file(table: dict[int, FileLocks], inode: int) -> FileLocks:
        fl = table.get(inode)
        if fl is None:
            fl = table[inode] = FileLocks()
        return fl

    def posix(self, inode: int, session_id: int, token: int, start: int,
              end: int, ltype: int) -> bool:
        return self._file(self.posix_files, inode).apply(
            Owner(session_id, token), start, end or MAX_OFFSET, ltype
        )

    def flock(self, inode: int, session_id: int, token: int,
              ltype: int) -> bool:
        return self._file(self.flock_files, inode).apply(
            Owner(session_id, token), 0, MAX_OFFSET, ltype
        )

    def test(self, inode: int, session_id: int, token: int, start: int,
             end: int, ltype: int) -> Range | None:
        fl = self.posix_files.get(inode)
        if fl is None:
            return None
        return fl.test(Owner(session_id, token), start, end or MAX_OFFSET, ltype)

    def test_flock(self, inode: int, session_id: int, token: int,
                   ltype: int) -> Range | None:
        fl = self.flock_files.get(inode)
        if fl is None:
            return None
        return fl.test(Owner(session_id, token), 0, MAX_OFFSET, ltype)

    def release_session(self, session_id: int) -> list[int]:
        """Release all held locks of a session; returns the inodes that
        freed capacity (the caller retries its queued waiters there)."""
        woken = []
        for table in (self.posix_files, self.flock_files):
            for inode, fl in list(table.items()):
                before = len(fl.ranges)
                fl.release_session(session_id)
                if len(fl.ranges) != before:
                    woken.append(inode)
                if fl.empty:
                    del table[inode]
        return woken

    def session_inodes(self, session_id: int) -> list[int]:
        """Inodes where the session holds locks."""
        inodes = set()
        for table in (self.posix_files, self.flock_files):
            for inode, fl in table.items():
                if any(r.owner.session_id == session_id for r in fl.ranges):
                    inodes.add(inode)
        return sorted(inodes)
