"""HeatTracker: the master's decayed per-chunk / per-inode / per-server
heat map — the subsystem that closes the workload-observatory loop.

PR 12 built the accounting legs (client RPC charges on the master, CS
top-K heartbeat folds, gateway session-stats pushes) but nothing acted
on them: a viral file kept hammering the same k+m chunkservers while
the fleet idled. This module turns those streams into a bounded,
decayed heat sketch the master can *act* on:

* **bounded memory** — one Space-Saving-style heavy-hitter table per
  kind (chunk / inode / server), ``capacity`` cells each. A new key
  arriving at a full table evicts the coldest cell and inherits its
  decayed score (the classic Space-Saving error bound), so the hottest
  keys are always tracked without the table ever growing.
* **epoch decay** — :meth:`tick` halves every score each
  ``half_life_s`` of elapsed time, so "hot" always means *recently*
  hot and hysteresis-driven demotion follows the storm down for free.
* **rendered** — ``lizardfs-admin heat`` / webui ``/api/heat`` read
  :meth:`snapshot`; the currently-tracked cells export as the
  ``lizardfs_heat_*`` labeled metric families (cumulative ops/bytes
  counters, bounded by the sketch capacity, retired via
  ``drop_labeled`` on eviction) and master-leg charges with a trace id
  feed the ``heat_hot_ops`` labeled histogram whose +Inf bucket
  carries the hottest cell's trace-id exemplar.
* **acted on** — :meth:`boost_decisions` compares decayed chunk heat
  against the ``heat_boost_bytes`` / ``heat_demote_bytes`` thresholds
  (runtime-tunable tweaks) and tells the master which chunks to
  goal-boost / goal-demote through the changelog;
  :meth:`server_loads` folds per-server heat share into the placement
  load scores (master/chunks.py ``server_load``).

The whole plane is behind the ``LZ_HEAT`` kill switch
(constants.heat_enabled) — consulted by the call SITES (master tick,
chunkserver fold), not here: the tracker itself is a pure data
structure so tests can drive it directly.
"""

from __future__ import annotations

KINDS = ("chunk", "inode", "server")

# decayed-score floor below which a cell is dropped entirely (its
# labeled series retire with it): keeps a quiet cluster's heat page
# empty instead of full of stale near-zero cells
EVICT_EPSILON = 1.0


class _Cell:
    """One tracked key: decayed scores (the heat) + monotonic raw
    totals (the exported counters — Prometheus counters must never go
    down; a re-tracked key after eviction restarts them, which scrapers
    treat as an ordinary counter reset)."""

    __slots__ = ("ops", "nbytes", "ops_total", "bytes_total", "trace_id")

    def __init__(self):
        self.ops = 0.0        # decayed op heat
        self.nbytes = 0.0     # decayed byte heat (THE heat score)
        self.ops_total = 0.0
        self.bytes_total = 0.0
        self.trace_id = 0     # most recent charged trace (hottest-cell drill)


class HeatTracker:
    # sketch capacity per kind: heat exists to find the FEW hot keys,
    # and the labeled metric families it exports must stay far under
    # the registry's LABEL_VARIANT_CAP
    CAPACITY = 64
    HALF_LIFE_S = 30.0

    def __init__(self, metrics=None, tweaks=None,
                 capacity: int = CAPACITY,
                 half_life_s: float = HALF_LIFE_S):
        self.metrics = metrics
        self.capacity = capacity
        self.half_life_s = half_life_s
        self._tables: dict[str, dict[int, _Cell]] = {k: {} for k in KINDS}
        self._last_decay = 0.0
        self.evictions = 0
        # adaptive-replication knobs ride the tweaks registry (admin
        # tweaks-set / SIGHUP tunable, the rebuild_bps pattern):
        # boost when decayed chunk heat crosses heat_boost_bytes,
        # demote only after it falls below heat_demote_bytes (the
        # hysteresis band), never more than heat_max_boosted chunks
        # boosted at once, each by heat_boost_copies extra copies.
        if tweaks is not None:
            self._boost_bytes = tweaks.register(
                "heat_boost_bytes", 32 * 1024 * 1024)
            self._demote_bytes = tweaks.register(
                "heat_demote_bytes", 4 * 1024 * 1024)
            self._boost_copies = tweaks.register("heat_boost_copies", 2)
            self._max_boosted = tweaks.register("heat_max_boosted", 8)
            # decay half-life is live-tunable too: shortening it makes
            # demotion follow a storm down faster (and lets the chaos
            # drill assert the full boost→demote cycle in seconds)
            self._half_life = tweaks.register(
                "heat_half_life_s", half_life_s)
        else:  # unit tests / detached use
            class _V:  # noqa: N801 - tiny value cell
                def __init__(self, v):
                    self.value = v

            self._boost_bytes = _V(32 * 1024 * 1024)
            self._demote_bytes = _V(4 * 1024 * 1024)
            self._boost_copies = _V(2)
            self._max_boosted = _V(8)
            self._half_life = _V(half_life_s)

    # --- charging -----------------------------------------------------------

    def charge(self, kind: str, key: int, ops: float = 1.0,
               nbytes: float = 0.0, seconds: float = 0.0,
               trace_id: int = 0) -> None:
        """Account heat to one key. CS heartbeat folds charge (ops,
        bytes) batches; master RPC legs also carry the op's latency +
        trace id, which feed the exemplar histogram."""
        table = self._tables[kind]
        cell = table.get(key)
        if cell is None:
            cell = _Cell()
            if len(table) >= self.capacity:
                coldest = min(table, key=lambda k: table[k].nbytes)
                evicted = table.pop(coldest)
                self.evictions += 1
                # Space-Saving: the newcomer inherits the evicted
                # score — it may have been this hot already while
                # untracked (over-estimates, never under-estimates)
                cell.ops = evicted.ops
                cell.nbytes = evicted.nbytes
                if self.metrics is not None:
                    self.metrics.drop_labeled("heat_ops", "key", coldest)
                    self.metrics.drop_labeled("heat_bytes", "key", coldest)
                    self.metrics.drop_labeled("heat_hot_ops", "key", coldest)
            table[key] = cell
        cell.ops += ops
        cell.nbytes += nbytes
        cell.ops_total += ops
        cell.bytes_total += nbytes
        if trace_id:
            cell.trace_id = trace_id
        if self.metrics is not None:
            labels = {"kind": kind, "key": key}
            self.metrics.labeled_counter(
                "heat_ops", labels,
                help="ops observed on currently-tracked hot keys "
                     "(heat sketch cells; series retire on eviction)",
            ).inc(ops)
            self.metrics.labeled_counter(
                "heat_bytes", labels,
                help="bytes observed on currently-tracked hot keys "
                     "(heat sketch cells; series retire on eviction)",
            ).inc(nbytes)
            if seconds > 0.0 or trace_id:
                # hottest-cell drill-down: op latency histogram whose
                # +Inf bucket carries the trace-id exemplar
                self.metrics.labeled_timing(
                    "heat_hot_ops", labels,
                    help="per-hot-key op latency with trace-id "
                         "exemplars (heat map drill-down)",
                ).record(seconds, trace_id=trace_id)

    def fold_cs(self, cs_id: int, doc: dict) -> None:
        """Ingest one chunkserver heartbeat heat fold:
        ``{"chunks": [[chunk_id, ops, bytes], ...]}`` (heat_json). The
        server's own heat is the sum of its chunk folds."""
        total_ops = 0.0
        total_bytes = 0.0
        for row in doc.get("chunks", ()):
            try:
                cid, ops, nbytes = int(row[0]), float(row[1]), float(row[2])
            except (TypeError, ValueError, IndexError):
                continue
            self.charge("chunk", cid, ops=ops, nbytes=nbytes)
            total_ops += ops
            total_bytes += nbytes
        if total_ops or total_bytes:
            self.charge("server", cs_id, ops=total_ops, nbytes=total_bytes)

    # --- decay / queries ----------------------------------------------------

    def tick(self, now: float) -> None:
        """Apply epoch decay for the wall time elapsed since the last
        tick and drop cells that decayed to nothing (their labeled
        series retire so the scrape page empties after a storm)."""
        if self._last_decay == 0.0:
            self._last_decay = now
            return
        dt = now - self._last_decay
        if dt <= 0:
            return
        self._last_decay = now
        factor = 0.5 ** (dt / max(float(self._half_life.value), 0.1))
        for kind, table in self._tables.items():
            dead = []
            for key, cell in table.items():
                cell.ops *= factor
                cell.nbytes *= factor
                if cell.nbytes < EVICT_EPSILON and cell.ops < EVICT_EPSILON:
                    dead.append(key)
            for key in dead:
                del table[key]
                if self.metrics is not None:
                    self.metrics.drop_labeled("heat_ops", "key", key)
                    self.metrics.drop_labeled("heat_bytes", "key", key)
                    self.metrics.drop_labeled("heat_hot_ops", "key", key)
        if self.metrics is not None:
            self.metrics.gauge(
                "heat_tracked_cells",
                help="keys currently tracked by the heat sketch "
                     "(all kinds; bounded by capacity per kind)",
            ).set(float(sum(len(t) for t in self._tables.values())))

    def heat_of(self, kind: str, key: int) -> float:
        cell = self._tables[kind].get(key)
        return cell.nbytes if cell is not None else 0.0

    def top(self, kind: str, k: int = 16) -> list[dict]:
        table = self._tables[kind]
        rows = sorted(
            table.items(), key=lambda kv: kv[1].nbytes, reverse=True
        )[:k]
        return [
            {
                "key": key,
                "heat_bytes": round(cell.nbytes, 1),
                "heat_ops": round(cell.ops, 2),
                "total_bytes": int(cell.bytes_total),
                "total_ops": int(cell.ops_total),
                "trace_id": f"0x{cell.trace_id:x}" if cell.trace_id else "",
            }
            for key, cell in rows
        ]

    def snapshot(self, boosted: dict[int, int] | None = None,
                 k: int = 16) -> dict:
        """The `heat` admin / webui document."""
        return {
            "half_life_s": float(self._half_life.value),
            "capacity": self.capacity,
            "evictions": self.evictions,
            "thresholds": {
                "heat_boost_bytes": int(self._boost_bytes.value),
                "heat_demote_bytes": int(self._demote_bytes.value),
                "heat_boost_copies": int(self._boost_copies.value),
                "heat_max_boosted": int(self._max_boosted.value),
            },
            "chunks": self.top("chunk", k),
            "inodes": self.top("inode", k),
            "servers": self.top("server", k),
            "boosted": dict(boosted or {}),
        }

    # --- the feedback legs --------------------------------------------------

    def boost_decisions(
        self, boosted: dict[int, int]
    ) -> tuple[list[tuple[int, int]], list[int]]:
        """(to_boost, to_demote) against the current sketch.

        ``boosted`` is the live map of chunk_id -> boost currently
        applied (mirrors ChunkInfo.boost). Boost when decayed heat
        crosses ``heat_boost_bytes`` (bounded by ``heat_max_boosted``
        concurrent boosts); demote only when heat falls below
        ``heat_demote_bytes`` — the hysteresis band between the two
        keeps a flickering chunk from thrashing the changelog."""
        boost_at = float(self._boost_bytes.value)
        demote_at = float(self._demote_bytes.value)
        copies = max(int(self._boost_copies.value), 1)
        cap = max(int(self._max_boosted.value), 0)
        table = self._tables["chunk"]
        to_demote = [
            cid for cid in sorted(boosted)
            if (table[cid].nbytes if cid in table else 0.0) < demote_at
        ]
        to_boost: list[tuple[int, int]] = []
        room = cap - (len(boosted) - len(to_demote))
        if room > 0 and boost_at > 0:
            hot = sorted(
                (
                    (cell.nbytes, cid) for cid, cell in table.items()
                    if cid not in boosted and cell.nbytes >= boost_at
                ),
                reverse=True,
            )
            to_boost = [(cid, copies) for _, cid in hot[:room]]
        return to_boost, to_demote

    def server_loads(self, health: dict[int, dict],
                     waiting: dict[int, float] | None = None) -> dict[int, float]:
        """Placement load scores (master/chunks.py ``server_load``):
        per-server heat share + degraded-health penalty + queue-depth
        pressure, each clamped so one signal cannot drown the others.

        ``health`` is the master's cs_id -> heartbeat health doc map;
        ``waiting`` optionally carries cs_id -> queued data-plane bytes
        (DRR queue depth from the health fold)."""
        table = self._tables["server"]
        total = sum(c.nbytes for c in table.values()) or 1.0
        loads: dict[int, float] = {}
        for cs_id, cell in table.items():
            loads[cs_id] = min(cell.nbytes / total, 1.0)
        for cs_id, doc in health.items():
            status = str((doc or {}).get("status", "ok"))
            if status not in ("", "ok"):
                loads[cs_id] = loads.get(cs_id, 0.0) + 0.5
        for cs_id, nbytes in (waiting or {}).items():
            # 64 MiB queued = full extra point of load
            loads[cs_id] = loads.get(cs_id, 0.0) + min(
                float(nbytes) / (64 * 1024 * 1024), 1.0
            )
        return loads
