"""Daemon runtime: event-loop harness, config system, RPC connections."""
