"""Deterministic, seeded fault injection at the system's choke points.

Every failure path in the tree used to be validated by a one-off test
(a SIGKILL here, a frozen shadow there); the only injectable fault was
the ``debug_read_delay_ms`` chunkserver tweak. This module generalizes
that into a first-class framework (the analog of the reference's
``SLOW_CHUNK_OPERATIONS``-style debug hooks and its system-test fault
drills, tests/tools/lizardfs.sh): a seeded rule set, parsed from the
``LZ_FAULTS`` environment spec or armed live over the admin channel,
consulted at a handful of natural choke points:

  ``frame_send`` / ``frame_recv``  proto/framing message boundaries
                                   (op = message class name)
  ``disk_pread`` / ``disk_pwrite`` chunkserver/chunk_store block IO
                                   (op = "<chunk_id:016X>:<part_id>")
  ``dial``                         outbound connects: client data plane,
                                   RPC links, pooled chunkserver conns
                                   (op = "rpc"|"cs"|..., peer = host:port)
  ``serve_read``                   chunkserver asyncio read path (the
                                   ``debug_read_delay_ms`` alias site)
  ``http_recv`` / ``http_send``    S3 gateway HTTP framing boundaries
                                   (op = method on recv, S3 op on send)

Spec grammar (whitespace-tolerant)::

    LZ_FAULTS = [ "seed=" N ";" ] rule ( ";" rule )*
    rule      = match SP action
    match     = role ":" site [ ":" op [ ":" peer ] ]   # fnmatch patterns
    action    = kind [ "=" value ] ( "," key "=" val )*

Actions:

  ``delay=MS``      stall MS milliseconds at the point
  ``drop``          abort the connection / fail the op (ConnectionResetError)
  ``error[=NAME]``  raise a status error (proto.status name or int; disk
                    sites surface it as a ChunkStoreError, frame sites as
                    a connection reset). Default EIO.
  ``flip``          flip one payload bit (frame bodies; disk_pread data
                    post-CRC-verify so the *receiver* catches it;
                    disk_pwrite data pre-CRC-store = latent corruption)
  ``short``         truncate: a partial frame then disconnect, a short
                    read, or a written block whose CRC slot is stale

Keys: ``p=0.5`` fire probability (default 1), ``limit=N`` max fires
(default unlimited), ``after=N`` skip the first N matches.

Example::

    LZ_FAULTS="seed=42; chunkserver:disk_pread flip,limit=1; \
               client:frame_send:CltocsWrite* delay=40,p=0.25"

Determinism: every probabilistic draw (fire/skip, flip bit position)
comes from a per-rule ``random.Random`` seeded from the global seed and
the rule's index — the same spec plus the same sequence of match calls
yields the same decisions, so a failing chaos schedule replays exactly
from its printed seed.

Kill-switch discipline (the LZ_WRITE_WINDOW / LZ_SHM_RING contract):
with ``LZ_FAULTS`` unset and no rules armed, :data:`ACTIVE` is False and
every instrumented site reduces to one module-attribute check — zero
added syscalls, zero behavior change, byte-identical output. While any
rule is armed, native fast paths (which cannot be instrumented from
Python) stand down so every byte flows through hookable code; this is a
documented behavior change *of the armed state only*.

Role resolution: the process-level role (set by each daemon's
``__main__`` entry point, or ``LZ_ROLE``) is the default; daemons
additionally scope every inbound connection's handling task via
:func:`role_scope`, so in-process multi-daemon tests still attribute
server-side fires correctly. Disk sites pass ``role="chunkserver"``
explicitly — a chunk store only ever belongs to one.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import fnmatch
import os
import random
import threading
import time

# site names wired in the tree (kept here so tools/tests can enumerate)
SITES = (
    "frame_send", "frame_recv", "disk_pread", "disk_pwrite", "dial",
    "serve_read", "http_recv", "http_send",
)

ACTIONS = ("delay", "drop", "error", "flip", "short")

#: fast-path flag: instrumented sites check this ONE module attribute
#: before doing anything else. False <=> zero overhead, zero change.
ACTIVE: bool = False

_LOCK = threading.Lock()
_PROCESS_ROLE = os.environ.get("LZ_ROLE", "client")
_role_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "lz_fault_role", default=None
)

# bounded fire log: (wall time, role, site, op, peer, action, rule text).
# Surfaced by the `faults` admin command and folded into health
# snapshots so incident output NAMES the injected fault.
_EVENTS: collections.deque = collections.deque(maxlen=256)

# role -> Metrics registry for faults_injected{site,action} counters
_METRICS: dict[str, object] = {}


class FaultSpecError(ValueError):
    pass


class Decision:
    """What one matched rule asks the site to do. Sites interpret the
    action in site-appropriate terms (see module docstring)."""

    __slots__ = ("action", "ms", "code", "rule")

    def __init__(self, action: str, ms: float, code: int, rule: "FaultRule"):
        self.action = action
        self.ms = ms
        self.code = code
        self.rule = rule


class FaultRule:
    __slots__ = (
        "role", "site", "op", "peer", "action", "ms", "code", "prob",
        "limit", "after", "alias", "matched", "fired", "_rng",
    )

    def __init__(self, role, site, op, peer, action, ms=0.0, code=0,
                 prob=1.0, limit=0, after=0, alias=None):
        self.role = role or "*"
        self.site = site or "*"
        self.op = op or "*"
        self.peer = peer or "*"
        self.action = action
        self.ms = ms
        self.code = code
        self.prob = prob
        self.limit = limit  # 0 = unlimited
        self.after = after
        self.alias = alias  # set for tweak-armed rules (one per alias)
        self.matched = 0
        self.fired = 0
        self._rng = random.Random(0)

    def seed(self, global_seed: int, index: int) -> None:
        # distinct, reproducible stream per rule position
        self._rng = random.Random((global_seed * 0x9E3779B9 + index) & 0xFFFFFFFF)

    def matches(self, role: str, site: str, op: str, peer: str) -> bool:
        return (
            fnmatch.fnmatchcase(site, self.site)
            and fnmatch.fnmatchcase(role, self.role)
            and fnmatch.fnmatchcase(op, self.op)
            and fnmatch.fnmatchcase(peer, self.peer)
        )

    def draw(self) -> bool:
        """Deterministic fire/skip decision for one match."""
        self.matched += 1
        if self.matched <= self.after:
            return False
        if self.limit and self.fired >= self.limit:
            return False
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            return False
        self.fired += 1
        return True

    def rand_index(self, n: int) -> int:
        """Deterministic index draw (flip bit positions)."""
        return self._rng.randrange(n) if n > 0 else 0

    def text(self) -> str:
        out = f"{self.role}:{self.site}:{self.op}:{self.peer} {self.action}"
        if self.action == "delay":
            out += f"={self.ms:g}"
        elif self.action == "error" and self.code:
            out += f"={self.code}"
        mods = []
        if self.prob < 1.0:
            mods.append(f"p={self.prob:g}")
        if self.limit:
            mods.append(f"limit={self.limit}")
        if self.after:
            mods.append(f"after={self.after}")
        return out + ("," + ",".join(mods) if mods else "")

    def to_dict(self) -> dict:
        return {
            "rule": self.text(), "action": self.action,
            "matched": self.matched, "fired": self.fired,
            "limit": self.limit, "alias": self.alias,
        }


def _parse_code(raw: str) -> int:
    from lizardfs_tpu.proto import status as st

    try:
        return int(raw, 0)
    except ValueError:
        code = getattr(st, raw.strip().upper(), None)
        if not isinstance(code, int):
            raise FaultSpecError(f"unknown status {raw!r}") from None
        return code


def parse_rule(text: str, alias: str | None = None) -> FaultRule:
    """``role:site[:op[:peer]] action[=v][,k=v...]`` -> FaultRule."""
    parts = text.strip().split(None, 1)
    if len(parts) != 2:
        raise FaultSpecError(f"rule needs 'match action': {text!r}")
    match, action_text = parts
    # maxsplit=3: the peer pattern is the REST of the match — it may
    # itself contain colons (host:port, the documented dial form)
    fields = (match.split(":", 3) + ["*"] * 4)[:4]
    tokens = [t.strip() for t in action_text.split(",") if t.strip()]
    kind, _, value = tokens[0].partition("=")
    kind = kind.strip().lower()
    if kind not in ACTIONS:
        raise FaultSpecError(f"unknown action {kind!r} in {text!r}")
    ms, code = 0.0, 0
    if kind == "delay":
        try:
            ms = float(value or "0")
        except ValueError:
            raise FaultSpecError(f"bad delay {value!r}") from None
        if ms <= 0:
            raise FaultSpecError("delay needs =MS > 0")
    elif kind == "error":
        code = _parse_code(value) if value else 0
    prob, limit, after = 1.0, 0, 0
    for tok in tokens[1:]:
        key, _, val = tok.partition("=")
        key = key.strip().lower()
        try:
            if key == "p":
                prob = float(val)
                if not 0.0 < prob <= 1.0:
                    raise ValueError
            elif key == "limit":
                limit = int(val)
            elif key == "after":
                after = int(val)
            else:
                raise FaultSpecError(f"unknown key {key!r} in {text!r}")
        except ValueError:
            raise FaultSpecError(f"bad value {tok!r} in {text!r}") from None
    return FaultRule(*fields, kind, ms=ms, code=code, prob=prob,
                     limit=limit, after=after, alias=alias)


def parse_spec(spec: str) -> tuple[int, list[FaultRule]]:
    seed = 0
    rules: list[FaultRule] = []
    for item in spec.split(";"):
        item = item.strip()
        if not item:
            continue
        if item.lower().startswith("seed=") and ":" not in item:
            try:
                seed = int(item[5:], 0)
            except ValueError:
                raise FaultSpecError(f"bad seed {item!r}") from None
            continue
        rules.append(parse_rule(item))
    return seed, rules


class FaultSet:
    def __init__(self, seed: int = 0, rules: list[FaultRule] | None = None):
        self.seed = seed
        self.rules: list[FaultRule] = []
        self._next_index = 0
        for rule in rules or ():
            self.add(rule)

    def add(self, rule: FaultRule) -> None:
        rule.seed(self.seed, self._next_index)
        self._next_index += 1
        self.rules.append(rule)

    def match(self, role: str, site: str, op: str, peer: str):
        for rule in self.rules:
            if rule.matches(role, site, op, peer) and rule.draw():
                return rule
        return None


_SET = FaultSet()


def _refresh_active() -> None:
    global ACTIVE
    ACTIVE = bool(_SET.rules)


def _load_env() -> None:
    spec = os.environ.get("LZ_FAULTS", "")
    if not spec.strip():
        return
    seed, rules = parse_spec(spec)
    install_set(FaultSet(seed, rules))


def install(spec: str, seed: int | None = None) -> None:
    """Replace the process rule set from a spec string (the LZ_FAULTS
    grammar; a leading ``seed=N`` item or the ``seed`` argument seeds
    the deterministic streams)."""
    spec_seed, rules = parse_spec(spec)
    install_set(FaultSet(seed if seed is not None else spec_seed, rules))


def install_set(fault_set: FaultSet) -> None:
    global _SET
    with _LOCK:
        _SET = fault_set
        _refresh_active()


def arm(rule_text: str, alias: str | None = None) -> FaultRule:
    """Add one rule to the live set. ``alias`` names a replaceable slot
    (the ``debug_read_delay_ms`` tweak arms through one): arming the
    same alias again replaces the previous rule instead of stacking."""
    rule = parse_rule(rule_text, alias=alias)
    with _LOCK:
        if alias is not None:
            _SET.rules = [r for r in _SET.rules if r.alias != alias]
        _SET.add(rule)
        _refresh_active()
    return rule


def clear(alias: str | None = None) -> None:
    """Drop every rule (or just an alias's) and the fire log."""
    global _SET
    with _LOCK:
        if alias is None:
            _SET = FaultSet(_SET.seed)
            _EVENTS.clear()
        else:
            _SET.rules = [r for r in _SET.rules if r.alias != alias]
        _refresh_active()


def describe() -> dict:
    """Admin/`faults` view: seed, rules with fire counts, recent events."""
    with _LOCK:
        return {
            "active": ACTIVE,
            "seed": _SET.seed,
            "role": _PROCESS_ROLE,
            "rules": [r.to_dict() for r in _SET.rules],
            "events": list(_EVENTS),
        }


def fired_total() -> int:
    with _LOCK:
        return sum(r.fired for r in _SET.rules)


# --- role plumbing ---------------------------------------------------------


def set_role(role: str) -> None:
    """Process-level default role (daemon ``__main__`` entry points)."""
    global _PROCESS_ROLE
    _PROCESS_ROLE = role


def current_role() -> str:
    return _role_var.get() or _PROCESS_ROLE


@contextlib.contextmanager
def role_scope(role: str):
    """Scope the fault role to the current task tree (a daemon's inbound
    connection handler; context propagates into to_thread workers)."""
    token = _role_var.set(role)
    try:
        yield
    finally:
        _role_var.reset(token)


# --- metrics ---------------------------------------------------------------


def attach_metrics(role: str, metrics) -> None:
    """Register a role's Metrics registry: fires increment its
    ``faults_injected{site,action}`` labeled counter family."""
    _METRICS[role] = metrics


def _count_fire(role: str, site: str, action: str) -> None:
    metrics = _METRICS.get(role)
    if metrics is None and _METRICS:
        # in-process fallbacks (e.g. a bare tool) land on any registry
        # rather than vanishing
        metrics = next(iter(_METRICS.values()))
    if metrics is None:
        return
    try:
        metrics.labeled_counter(
            "faults_injected", {"site": site, "action": action},
            help="injected faults fired, by choke-point site and action",
        ).inc()
    except Exception:  # pragma: no cover — metrics must never hurt faults
        pass


# --- the decision point ----------------------------------------------------


def decide(site: str, op: str = "", peer: str = "",
           role: str | None = None) -> Decision | None:
    """Match the live rule set; None = proceed untouched. Callers gate
    on :data:`ACTIVE` first, so this never runs on the clean path."""
    role = role if role is not None else current_role()
    with _LOCK:
        rule = _SET.match(role, site, op, peer)
        if rule is None:
            return None
        _EVENTS.append({
            "t": time.time(), "role": role, "site": site, "op": op,
            "peer": peer, "action": rule.action, "rule": rule.text(),
        })
    _count_fire(role, site, rule.action)
    return Decision(rule.action, rule.ms, rule.code, rule)


def flip_bit(data: bytes | bytearray, rule: FaultRule,
             lo: int = 0, hi: int | None = None) -> bytes:
    """Flip one deterministic bit of ``data[lo:hi]``."""
    hi = len(data) if hi is None else hi
    if hi <= lo:
        return bytes(data)
    out = bytearray(data)
    pos = lo + rule.rand_index(hi - lo)
    out[pos] ^= 1 << rule.rand_index(8)
    return bytes(out)


async def dial_point(op: str, peer: str, role: str | None = None) -> None:
    """The one outbound-connect choke point (pool dials, RPC links,
    client data-plane connects all call this): delay sleeps before the
    dial, every other action refuses the connection."""
    import asyncio

    dec = decide("dial", op=op, peer=peer, role=role)
    if dec is None:
        return
    if dec.action == "delay":
        await asyncio.sleep(dec.ms / 1e3)
        return
    raise ConnectionRefusedError(
        f"fault injected: {dec.action} dial {peer}"
    )


async def async_point(site: str, op: str = "", peer: str = "",
                      role: str | None = None) -> None:
    """Generic async choke point (e.g. the chunkserver's ``serve_read``
    path): delay sleeps, anything else aborts the exchange."""
    import asyncio

    dec = decide(site, op=op, peer=peer, role=role)
    if dec is None:
        return
    if dec.action == "delay":
        await asyncio.sleep(dec.ms / 1e3)
        return
    raise ConnectionResetError(f"fault injected: {dec.action} {site} {op}")


# --- frame-site helper (proto/framing) -------------------------------------

# encoded frame layout: 8-byte header + 1 version byte + body
_FRAME_BODY_OFF = 9


async def frame_point(site: str, name: str, data: bytes,
                      peer: str = "", writer=None) -> bytes:
    """Apply a matched decision at a frame boundary. Returns the
    (possibly mangled) bytes to proceed with; raises ConnectionResetError
    for drop/error/short; sleeps for delay."""
    import asyncio

    dec = decide(site, op=name, peer=peer)
    if dec is None:
        return data
    if dec.action == "delay":
        await asyncio.sleep(dec.ms / 1e3)
        return data
    if dec.action == "flip":
        # flip inside the body so framing survives and CONTENT corrupts
        # (decode error or payload CRC mismatch at the receiver)
        if site == "frame_send" and len(data) > _FRAME_BODY_OFF:
            return flip_bit(data, dec.rule, lo=_FRAME_BODY_OFF)
        if site == "frame_recv" and len(data) > 1:
            # skip the leading protocol-version byte: like the send
            # side, the flip must corrupt CONTENT (decode error / CRC
            # mismatch), not turn into a version-negotiation failure
            return flip_bit(data, dec.rule, lo=1)
        return data
    if dec.action == "short" and site == "frame_send" and writer is not None:
        # torn write: half a frame on the wire, then the peer sees EOF
        writer.write(data[: max(len(data) // 2, 1)])
        try:
            await asyncio.wait_for(writer.drain(), 5.0)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            pass
        writer.close()
        raise ConnectionResetError(f"fault injected: short {name}")
    # drop / error / recv-side short: kill the exchange
    if writer is not None:
        writer.close()
    raise ConnectionResetError(
        f"fault injected: {dec.action} {site} {name}"
    )


# parse the environment spec once at import (the autoload path real
# multi-process chaos clusters use; tests drive install()/arm() direct)
try:
    _load_env()
except FaultSpecError as e:  # bad spec must be loud, not silent
    raise SystemExit(f"LZ_FAULTS: {e}") from None
