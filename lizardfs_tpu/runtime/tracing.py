"""Request-scoped distributed tracing across the three roles.

The reference ships per-daemon charts and an oplog but nothing
request-scoped; closing a cross-process throughput gap (the ec(8,4)
write target) needs attribution past the client boundary. This module
is the L0 piece: trace ids, span records, a bounded per-process span
ring (oplog-style), and the client-side timeline merge.

Propagation:
  * master RPCs carry the trace id as a skew-tolerant TRAILING field on
    the wire messages (proto/messages.py ``trace_id``; the codec
    default-fills missing trailing fields, so a peer predating the
    field still decodes — version-skew pinned in tests/test_tracing.py),
  * the native data plane carries it as an OPTIONAL trailing u64 on
    request frames (native/wire.h "trace propagation" contract); the
    C++ server records per-op receive/disk/send timestamps into its own
    ring, drained into the chunkserver's SpanRing
    (chunkserver/server.py trace_spans).

Each daemon's ring is dumped over the admin link
(``lizardfs-admin <addr> trace-dump``) and merged client-side with
:func:`merge_timeline` into a per-request timeline, so one ec(8,4)
write rep decomposes into client encode/stage/send, chunkserver
recv/disk-commit, and ack segments across processes.

Cost contract: with ``LZ_TRACE=0`` no ids are issued,
``current_trace_id()`` is 0 everywhere, and every record path is a
single falsy check — the acceptance bound is <1% on the ec(8,4) write
row.

Clocks: spans carry CLOCK_REALTIME epoch seconds (C side: microseconds
via clock_gettime) so same-host cross-process merges line up; durations
inside one process stay monotonic-accurate at the span granularity
(tens of microseconds and up) this subsystem targets.
"""

from __future__ import annotations

import contextvars
import secrets
import time
from collections import deque

# process-wide kill switch: LZ_TRACE=0 disables issuing trace ids, which
# short-circuits every record path (spans are only recorded for nonzero
# trace ids)
from lizardfs_tpu.constants import env_flag

_ENABLED = env_flag("LZ_TRACE")

# (trace_id, parent_span_id) of the request this task is serving
CURRENT: contextvars.ContextVar[tuple[int, int] | None] = (
    contextvars.ContextVar("lz_trace", default=None)
)


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Test/ops hook mirroring the LZ_TRACE env gate."""
    global _ENABLED
    _ENABLED = bool(on)


def new_id() -> int:
    # 63-bit nonzero: fits i64/u64 everywhere, 0 stays "untraced"
    return secrets.randbits(63) | 1


def current_trace_id() -> int:
    cur = CURRENT.get()
    return cur[0] if cur is not None else 0


def start_trace() -> int:
    """Begin a new trace in this task's context; returns the trace id
    (0 when tracing is disabled — callers pass it through untouched)."""
    if not _ENABLED:
        return 0
    tid = new_id()
    CURRENT.set((tid, 0))
    return tid


def ensure_trace() -> int:
    """Current trace id, starting a fresh trace if none is active."""
    tid = current_trace_id()
    return tid if tid else start_trace()


def begin() -> tuple[int, bool]:
    """Join the active trace or start a fresh one.

    Returns ``(trace_id, started)``; pass ``started`` to :func:`end`
    when the operation finishes so an op that STARTED its trace clears
    the context again — otherwise every later top-level op in the same
    task would silently reuse the first op's id and merge unrelated
    requests into one timeline."""
    tid = current_trace_id()
    if tid:
        return tid, False
    return start_trace(), True


def end(started: bool) -> None:
    if started:
        clear_trace()


def adopt_trace(tid: int) -> None:
    """Join an existing trace whose id arrived on the wire (e.g. the
    RebuildEngine's per-rebuild id riding MatocsReplicate) so every
    downstream op in this task propagates it."""
    if _ENABLED and tid:
        CURRENT.set((tid, 0))


def clear_trace() -> None:
    CURRENT.set(None)


class SpanRing:
    """Bounded in-memory span ring, one per daemon/client (the oplog
    model applied to spans). Records are plain dicts so dumps are
    JSON-ready for the admin link.

    ``dropped`` counts spans evicted by the bound — observability of
    the observability layer: silent trace loss under load would
    otherwise read as "the op recorded nothing". Daemons mirror it
    into their registry as ``span_ring_dropped`` so it rides
    ``/metrics`` (``lizardfs_span_ring_dropped_total``)."""

    def __init__(self, maxlen: int = 2048):
        self._ring: deque = deque(maxlen=maxlen)
        self.dropped = 0
        self._drop_counter = None  # optional Metrics counter mirror

    def attach_drop_counter(self, counter) -> None:
        """Mirror evictions into a ``Metrics`` counter (daemon wiring);
        evictions that predate the attach are folded in once."""
        self._drop_counter = counter
        if self.dropped > counter.total:
            counter.inc(self.dropped - counter.total)

    def record(
        self,
        trace_id: int,
        name: str,
        t0: float,
        t1: float,
        role: str = "",
        parent_id: int = 0,
        **attrs,
    ) -> int:
        """Record one finished span; no-op (returns 0) for trace id 0,
        which is what every call site passes when tracing is off."""
        if not trace_id:
            return 0
        if len(self._ring) == self._ring.maxlen:
            self.dropped += 1
            if self._drop_counter is not None:
                self._drop_counter.inc()
        span_id = new_id()
        rec = {
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": parent_id,
            "role": role,
            "name": name,
            "t0": t0,
            "t1": t1,
        }
        if attrs:
            rec["attrs"] = attrs
        self._ring.append(rec)
        return span_id

    def span(self, name: str, role: str = "", trace_id: int | None = None):
        """Context manager timing a block into the ring (sync code)."""
        return _SpanCtx(self, name, role, trace_id)

    def dump(self, trace_id: int | None = None) -> list[dict]:
        if trace_id:
            return [s for s in self._ring if s["trace_id"] == trace_id]
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


class _SpanCtx:
    __slots__ = ("ring", "name", "role", "trace_id", "t0")

    def __init__(self, ring, name, role, trace_id):
        self.ring = ring
        self.name = name
        self.role = role
        self.trace_id = (
            trace_id if trace_id is not None else current_trace_id()
        )

    def __enter__(self):
        self.t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.ring.record(
            self.trace_id, self.name, self.t0, time.time(), role=self.role
        )
        return False


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    """Total length of the union of [t0, t1] intervals."""
    total = 0.0
    end = float("-inf")
    for a, b in sorted(intervals):
        if b <= end:
            continue
        total += b - max(a, end)
        end = b
    return total


def merge_timeline(
    spans: list[dict], trace_id: int | None = None,
    wall_name: str | None = None,
) -> dict:
    """Merge spans (from any number of role rings) into one per-request
    timeline.

    ``wall_name`` names the root span whose [t0, t1] is the rep's wall
    time; it is EXCLUDED from coverage (a root span trivially covers
    100%) — coverage is the union of the remaining segments over the
    wall, the honest "how much of the rep can we attribute" number.
    Without a matching root the wall is the overall span envelope.
    """
    if trace_id:
        spans = [s for s in spans if s["trace_id"] == trace_id]
    if not spans:
        return {"trace_id": trace_id or 0, "segments": [],
                "wall_ms": 0.0, "coverage_pct": 0.0, "by_role_ms": {}}
    root = None
    if wall_name is not None:
        for s in spans:
            if s["name"] == wall_name and (
                root is None or s["t1"] - s["t0"] > root["t1"] - root["t0"]
            ):
                root = s
    segs = [s for s in spans if s is not root]
    t_lo = root["t0"] if root else min(s["t0"] for s in spans)
    t_hi = root["t1"] if root else max(s["t1"] for s in spans)
    wall = max(t_hi - t_lo, 1e-9)
    covered = _union_seconds(
        [(max(s["t0"], t_lo), min(s["t1"], t_hi)) for s in segs
         if s["t1"] > t_lo and s["t0"] < t_hi]
    )
    by_role: dict[str, float] = {}
    segments = []
    for s in sorted(segs, key=lambda x: (x["t0"], x["t1"])):
        dur = s["t1"] - s["t0"]
        by_role[s["role"]] = by_role.get(s["role"], 0.0) + dur
        segments.append({
            "role": s["role"], "name": s["name"],
            "start_ms": round((s["t0"] - t_lo) * 1e3, 3),
            "dur_ms": round(dur * 1e3, 3),
            **({"attrs": s["attrs"]} if "attrs" in s else {}),
        })
    return {
        "trace_id": spans[0]["trace_id"],
        "wall_ms": round(wall * 1e3, 3),
        "coverage_pct": round(100.0 * covered / wall, 1),
        "by_role_ms": {
            r: round(v * 1e3, 3) for r, v in sorted(by_role.items())
        },
        "segments": segments,
    }


def format_timeline(timeline: dict) -> str:
    """Human-readable one-line-per-segment rendering (admin CLI)."""
    lines = [
        # 0x prefix: an all-digit bare hex id would reparse as decimal
        f"trace 0x{timeline.get('trace_id', 0):x}  "
        f"wall {timeline.get('wall_ms', 0.0):.2f} ms  "
        f"coverage {timeline.get('coverage_pct', 0.0):.1f}%"
    ]
    for seg in timeline.get("segments", ()):
        lines.append(
            f"  {seg['start_ms']:>10.3f} ms  +{seg['dur_ms']:<10.3f} "
            f"{seg['role']:<12s} {seg['name']}"
        )
    return "\n".join(lines)


# --- read-phase sink ---------------------------------------------------------
#
# The client activates a sink around each LOGICAL read (read_file /
# read_file_into); deep layers that have no client reference — the
# connection pool's dial, the read executor's socket waits and plan
# postprocess — charge busy-time into whatever sink is ambient. A
# contextvar (not a global) keeps concurrent clients in one process
# (in-process test clusters, gateways) from cross-charging; asyncio
# tasks and to_thread propagate it, run_in_executor does not (native
# executor hops are therefore timed at the await site instead).

PHASE_SINK: contextvars.ContextVar = contextvars.ContextVar(
    "lz_read_phase_sink", default=None
)


def phase_t0() -> tuple[float, float]:
    """(perf_counter, wall) anchor for :func:`charge_phase` — durations
    stay monotonic-accurate while span endpoints stay epoch-aligned."""
    return (time.perf_counter(), time.time())


def charge_phase(phase: str, t0: tuple[float, float]) -> None:
    """Charge [t0, now] to ``phase`` on the ambient read-phase sink;
    free (one contextvar get) when no logical read is in flight."""
    sink = PHASE_SINK.get()
    if sink is not None:
        sink(phase, t0, (time.perf_counter(), time.time()))


def charge_queue_wait(
    metrics, ring, gate: str, tenant: str, t0: tuple[float, float],
    *, role: str = "", trace_id: int | None = None,
) -> float:
    """Charge one finished queue wait: a ``queue_wait{gate,tenant}``
    labeled timing on the owning component's registry plus a
    ``queue_wait:<gate>`` span on its ring (attribution's queue
    bucket). Explicit registry/ring arguments — in-process clusters run
    master + chunkservers + clients in one interpreter, so a
    process-global sink would misattribute the wait. Returns the
    seconds charged."""
    seconds = max(time.perf_counter() - t0[0], 0.0)
    tid = current_trace_id() if trace_id is None else trace_id
    if metrics is not None:
        metrics.labeled_timing(
            "queue_wait", {"gate": gate, "tenant": tenant or "default"},
            help="time ops spent waiting at an admission/credit gate "
                 "(DRR disk gate, write-window credits, shed retries, "
                 "connection dials) before doing any work",
        ).record(seconds, trace_id=tid)
    if ring is not None and tid:
        ring.record(
            tid, f"queue_wait:{gate}", t0[1], t0[1] + seconds,
            role=role, gate=gate,
        )
    return seconds


# --- latency attribution -----------------------------------------------------

ATTRIBUTION_BUCKETS = ("queue", "disk", "net", "compute", "unattributed")

# substring -> bucket, FIRST match wins (specific names before generic
# ones: "read:wait" must hit queue before "read" hits net). Unknown
# names classify to None and their time surfaces as unattributed-gap —
# honest, and exactly what flags a span this table should learn.
_BUCKET_RULES = (
    ("queue_wait", "queue"),
    ("dial", "queue"),
    ("throttle", "queue"),
    ("backoff", "queue"),
    ("read:wait", "queue"),
    ("qos", "queue"),
    ("locate", "net"),
    ("decode", "compute"),
    ("gather", "compute"),
    ("assemble", "compute"),
    ("encode", "compute"),
    ("stage", "compute"),
    ("crc", "compute"),
    ("disk", "disk"),
    ("net", "net"),
    ("send", "net"),
    ("recv", "net"),
    ("ack", "net"),
    ("commit", "net"),
    ("read", "net"),
    ("write", "net"),
)


def classify_segment(name: str) -> "str | None":
    label = str(name).lower()
    for pat, bucket in _BUCKET_RULES:
        if pat in label:
            return bucket
    return None


def _merge_intervals(ivs: list) -> list:
    """Sorted disjoint union of [a, b) intervals."""
    out: list = []
    for a, b in sorted(ivs):
        if out and a <= out[-1][1]:
            if b > out[-1][1]:
                out[-1] = (out[-1][0], b)
        else:
            out.append((a, b))
    return out


def _subtract_intervals(ivs: list, claimed: list) -> list:
    """``ivs`` minus ``claimed`` (both sorted disjoint unions)."""
    out = []
    for a, b in ivs:
        cur = a
        for ca, cb in claimed:
            if cb <= cur or ca >= b:
                continue
            if ca > cur:
                out.append((cur, ca))
            cur = max(cur, cb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


def attribute_timeline(timeline: dict) -> dict:
    """Decompose a :func:`merge_timeline` result into queue / disk /
    net / compute / unattributed-gap milliseconds that sum EXACTLY to
    the op's wall time.

    Every wall instant lands in at most one bucket: per-bucket span
    unions are resolved in priority order (queue > disk > net >
    compute), each later bucket only claiming instants no
    higher-priority bucket covered — overlapping spans can never push
    the sum past 100%. Segments are clamped to the wall window, so a
    clock-skewed ring (a chunkserver span leaking past the client
    wall) cannot produce negative gaps; zero/negative-duration
    segments are skipped. Chunkserver spans carrying the native
    plane's ``queue_us``/``disk_us``/``net_us`` attrs are split into
    synthetic sub-intervals in that order instead of classifying the
    envelope, so one ``cs_read`` op feeds three buckets."""
    wall_ms = float(timeline.get("wall_ms", 0.0) or 0.0)
    buckets = {b: 0.0 for b in ATTRIBUTION_BUCKETS}
    out = {
        "trace_id": timeline.get("trace_id", 0),
        "wall_ms": round(wall_ms, 3),
        "buckets_ms": buckets,
        "pct": {b: 0.0 for b in ATTRIBUTION_BUCKETS},
        "dominant": "unattributed",
    }
    if wall_ms <= 0.0:
        return out
    per_bucket: dict[str, list] = {}
    for seg in timeline.get("segments", ()):
        try:
            s = float(seg.get("start_ms", 0.0))
            e = s + float(seg.get("dur_ms", 0.0))
        except (TypeError, ValueError):
            continue
        s = min(max(s, 0.0), wall_ms)
        e = min(max(e, 0.0), wall_ms)
        if e <= s:
            continue
        attrs = seg.get("attrs") or {}
        if any(k in attrs for k in ("queue_us", "disk_us", "net_us")):
            cursor = s
            for key, bucket in (
                ("queue_us", "queue"), ("disk_us", "disk"),
                ("net_us", "net"),
            ):
                dur = min(
                    max(float(attrs.get(key, 0) or 0), 0.0) / 1e3,
                    e - cursor,
                )
                if dur > 0.0:
                    per_bucket.setdefault(bucket, []).append(
                        (cursor, cursor + dur)
                    )
                    cursor += dur
            continue
        bucket = classify_segment(seg.get("name", ""))
        if bucket is not None:
            per_bucket.setdefault(bucket, []).append((s, e))
    claimed: list = []
    covered = 0.0
    for bucket in ("queue", "disk", "net", "compute"):
        ivs = _merge_intervals(per_bucket.get(bucket, []))
        own = _subtract_intervals(ivs, claimed)
        got = sum(b - a for a, b in own)
        buckets[bucket] = round(got, 3)
        covered += got
        claimed = _merge_intervals(claimed + ivs)
    buckets["unattributed"] = round(max(wall_ms - covered, 0.0), 3)
    out["pct"] = {
        b: round(100.0 * v / wall_ms, 1) for b, v in buckets.items()
    }
    out["dominant"] = max(buckets, key=lambda b: buckets[b])
    return out


def format_attribution(attr: dict) -> str:
    """One-block rendering (`trace-dump --attribute`, slowops)."""
    lines = [
        f"attribution 0x{attr.get('trace_id', 0):x}  "
        f"wall {attr.get('wall_ms', 0.0):.2f} ms  "
        f"dominant {attr.get('dominant', '?')}"
    ]
    buckets = attr.get("buckets_ms", {})
    pct = attr.get("pct", {})
    for b in ATTRIBUTION_BUCKETS:
        lines.append(
            f"  {b:<14s} {buckets.get(b, 0.0):>10.3f} ms "
            f"{pct.get(b, 0.0):>6.1f}%"
        )
    return "\n".join(lines)
