"""Per-session (per-tenant) op accounting — the measurement layer under
the `top` view and the fair-share QoS work that follows (ROADMAP 4).

The reference answers "who is hammering my cluster?" only with
per-mount ``.oplog``/``.stats`` magic files (reference:
src/mount/oplog.cc, client/fuse_mount.py here) — per-process, invisible
cluster-wide. This module threads the session identity the master
already issues through everything a daemon counts:

* :class:`SessionOps` — bounded per-session op/byte/latency accounting
  on top of the registry's labeled families
  (``Metrics.labeled_timing("session_ops", {session, op})`` +
  ``labeled_counter("session_bytes", ...)``), with trace-id exemplars
  so a hot cell links straight to a PR-2 trace. Per-session rates ride
  a 60 s bucketed window (O(1) per record), so `top` shows live rates
  without a sampler thread.
* :meth:`SessionOps.top` — the top-K summary chunkservers fold into
  their heartbeat ``health_json`` and gateways push over
  ``CltomaSessionStats``, giving the master the cluster-wide view
  ``lizardfs-admin top`` renders.
* the process wire-session identity (:func:`set_process_session`) the
  data-plane request stampers read (``CltocsRead.session_id`` etc.),
  mirroring the native plane's thread-local trace id pattern.

Cost contract: ``LZ_TOP=0`` short-circuits :meth:`record` to a single
module-attribute check — no labeled series are created, heartbeat
summaries are empty, and the scrape page is byte-identical to the
pre-accounting one (pinned in tests/test_top.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import time

from lizardfs_tpu.constants import env_flag

_ENABLED = env_flag("LZ_TOP")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Test/ops hook mirroring the LZ_TOP env gate."""
    global _ENABLED
    _ENABLED = bool(on)


# The session id this PROCESS's data-plane requests carry (one cluster
# session per client process: FUSE mount, NFS gateway, S3 gateway).
# Module-global like the native plane's thread-local trace id —
# read_executor and friends are module functions with no client handle.
# A CONTEXTVAR overrides it per top-level client op (task_session below)
# so several Clients sharing one interpreter — the in-process test
# clusters, a colocated NFS+S3 pair — attribute each request to ITS
# owning session instead of whoever registered last.
_PROCESS_SESSION = 0

_TASK_SESSION: contextvars.ContextVar[int] = contextvars.ContextVar(
    "lz_session", default=0
)


def set_process_session(sid: int) -> None:
    global _PROCESS_SESSION
    _PROCESS_SESSION = int(sid)


def wire_session() -> int:
    return _TASK_SESSION.get() or _PROCESS_SESSION


@contextlib.contextmanager
def task_session(sid: int):
    """Scope the wire-session identity to this task (and every task it
    spawns — contextvars copy at task creation): the client wraps its
    public data ops so nested read/write machinery stamps the OWNING
    client's session."""
    token = _TASK_SESSION.set(int(sid))
    try:
        yield
    finally:
        _TASK_SESSION.reset(token)


# rate window: per-second buckets over the last minute
_RATE_SPAN_S = 60
# the window `top` computes live rates over (long enough to smooth
# bucket edges, short enough to track a moving hot spot)
_RATE_WINDOW_S = 10.0


class _Rate:
    """O(1) bucketed (ops, bytes) window; rate() averages the last
    ``_RATE_WINDOW_S`` seconds."""

    __slots__ = ("buckets",)

    def __init__(self):
        # bucket epoch -> [ops, bytes]; bounded by expiry on add
        self.buckets: dict[int, list] = {}

    def add(self, now: float, nbytes: int) -> None:
        epoch = int(now)
        b = self.buckets.get(epoch)
        if b is None:
            self.buckets[epoch] = [1, nbytes]
            if len(self.buckets) > _RATE_SPAN_S:
                lo = epoch - _RATE_SPAN_S
                for e in [e for e in self.buckets if e < lo]:
                    del self.buckets[e]
        else:
            b[0] += 1
            b[1] += nbytes

    def rates(self, now: float) -> tuple[float, float]:
        lo = int(now - _RATE_WINDOW_S)
        ops = by = 0
        for e, (o, b) in self.buckets.items():
            if e >= lo:
                ops += o
                by += b
        return ops / _RATE_WINDOW_S, by / _RATE_WINDOW_S


class SessionOps:
    """Bounded per-session op accounting for one daemon/client role.

    ``record(session, op_class, seconds, nbytes, trace_id)`` charges
    one finished op to its originating session: a labeled latency
    histogram cell (with the trace-id exemplar), a labeled byte
    counter, and the in-memory rate window ``top()`` reads. Sessions
    past ``max_sessions`` fold into the ``"other"`` row — totals stay
    truthful, cardinality stays bounded (the scrape page is the
    expensive surface: each tracked (session, op) cell is a 20-bucket
    histogram)."""

    def __init__(self, metrics, role: str = "", max_sessions: int = 32):
        self.metrics = metrics
        self.role = role
        self.max_sessions = max_sessions
        # session label -> {"rate": _Rate, "ops": int, "bytes": int,
        #                   "classes": {op_class: [ops, bytes]}}
        self._sessions: dict[str, dict] = {}

    def _label(self, session) -> str:
        label = f"s{session}" if isinstance(session, int) else str(session)
        if label not in self._sessions and (
            len(self._sessions) >= self.max_sessions
        ):
            return "other"
        return label

    def record(self, session, op_class: str, seconds: float,
               nbytes: int = 0, trace_id: int = 0) -> None:
        """Account one finished op. The LZ_TOP=0 path is this first
        check and nothing else."""
        if not _ENABLED:
            return
        label = self._label(session)
        self.metrics.labeled_timing(
            "session_ops", {"session": label, "op": op_class},
            help="per-session op latency by op class (exemplar: trace "
                 "id of the slowest recent op)",
        ).record(seconds, trace_id=trace_id)
        if nbytes:
            self.metrics.labeled_counter(
                "session_bytes", {"session": label, "op": op_class},
                help="payload bytes moved per session by op class",
            ).inc(nbytes)
        entry = self._sessions.get(label)
        if entry is None:
            entry = self._sessions[label] = {
                "rate": _Rate(), "ops": 0, "bytes": 0, "classes": {},
            }
        entry["rate"].add(time.monotonic(), nbytes)
        entry["ops"] += 1
        entry["bytes"] += nbytes
        cls = entry["classes"].setdefault(op_class, [0, 0])
        cls[0] += 1
        cls[1] += nbytes

    # --- summaries ---------------------------------------------------------

    def _timing_of(self, label: str, op_class: str):
        variants = self.metrics.labeled_timings.get("session_ops", {})
        return variants.get((("op", op_class), ("session", label)))

    def top(self, k: int = 8) -> list[dict]:
        """Top-K sessions by current op rate (ties: lifetime ops) —
        the summary that rides heartbeats / gateway pushes and feeds
        the master's cluster-wide `top` rollup. JSON-ready."""
        if not _ENABLED:
            return []
        now = time.monotonic()
        rows = []
        for label, entry in self._sessions.items():
            rate_ops, rate_bytes = entry["rate"].rates(now)
            classes = {}
            p99_worst = 0.0
            exemplar = ""
            for op_class, (ops, nbytes) in entry["classes"].items():
                t = self._timing_of(label, op_class)
                p99 = round(t.quantile_us(0.99) / 1e3, 3) if t else 0.0
                p99_worst = max(p99_worst, p99)
                cls = {"ops": ops, "p99_ms": p99}
                if nbytes:
                    cls["bytes"] = nbytes
                if t is not None and t.exemplar_trace_id:
                    cls["exemplar"] = f"0x{t.exemplar_trace_id:x}"
                    exemplar = exemplar or cls["exemplar"]
                classes[op_class] = cls
            row = {
                "session": label,
                "rate_ops": round(rate_ops, 2),
                "rate_bytes": round(rate_bytes, 1),
                "ops": entry["ops"],
                "bytes": entry["bytes"],
                "p99_ms": p99_worst,
                "classes": classes,
            }
            if exemplar:
                row["exemplar"] = exemplar
            rows.append(row)
        rows.sort(key=lambda r: (-r["rate_ops"], -r["ops"], r["session"]))
        return rows[:k]

    def total_rate(self) -> float:
        """Aggregate op rate across tracked sessions (the gauge the
        metrics-history rings retain for `top` trends)."""
        if not _ENABLED:
            return 0.0
        now = time.monotonic()
        return round(
            sum(e["rate"].rates(now)[0] for e in self._sessions.values()), 2
        )

    def active_sessions(self) -> int:
        return len(self._sessions)

    def retire(self, session) -> None:
        """Drop a departed session's aggregates AND its labeled metric
        variants: without the variant cleanup, session churn would fill
        the registry's LABEL_VARIANT_CAP with dead cells and fold every
        future session into "other" (no p99, no exemplar — the `top`
        link this module exists for)."""
        label = f"s{session}" if isinstance(session, int) else str(session)
        self._sessions.pop(label, None)
        self.metrics.drop_labeled("session_ops", "session", label)
        self.metrics.drop_labeled("session_bytes", "session", label)


async def gateway_stats_push_loop(client, doc_fn, interval_s, log) -> None:
    """ONE push loop for every protocol gateway: every ``interval_s``
    seconds, push ``doc_fn()`` to the master as CltomaSessionStats so
    the cluster ``top`` names the protocol-op mix behind the gateway's
    session. Best effort by design — a missed push costs one refresh
    interval, and telemetry must never kill serving. (Shared here so
    the NFS and S3 gateways cannot drift apart on the push contract.)"""
    import asyncio
    import json

    from lizardfs_tpu.proto import messages as m

    while True:
        await asyncio.sleep(interval_s)
        if not _ENABLED:
            continue
        try:
            await client._call(
                m.CltomaSessionStats, stats_json=json.dumps(doc_fn())
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            log.debug("session-stats push failed", exc_info=True)
