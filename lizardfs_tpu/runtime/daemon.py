"""Asyncio daemon harness: serve, timers, reload/terminate hooks.

The analog of the reference's event loop + main harness (reference:
src/common/event_loop.h:47-77 poll loop with timers and reload/exit
hooks; src/main/main.cc daemon scaffolding). One asyncio loop per
daemon; connection handlers and periodic tasks are coroutines.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys

from lizardfs_tpu.runtime import faults as faultsmod
from lizardfs_tpu.runtime import profiler as profmod
from lizardfs_tpu.runtime import retry as retrymod
from lizardfs_tpu.runtime import slo as slomod
from lizardfs_tpu.runtime import tracing
from lizardfs_tpu.runtime.metrics import Metrics
from lizardfs_tpu.runtime.tweaks import Tweaks


def setup_logging(name: str, level: str = "INFO") -> logging.Logger:
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname).1s [" + name + "] %(message)s",
        stream=sys.stderr,
    )
    return logging.getLogger(name)


class Daemon:
    """Base daemon: TCP server + named periodic timers + signal hooks."""

    name = "daemon"

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.log = logging.getLogger(self.name)
        self._server: asyncio.Server | None = None
        self._timers: list[tuple[float, object]] = []
        self._tasks: set[asyncio.Task] = set()
        self._conn_writers: set[asyncio.StreamWriter] = set()
        self._stopping = asyncio.Event()
        self.metrics = Metrics()
        self.tweaks = Tweaks()
        # request-scoped span ring (oplog-style), dumped over the admin
        # link via `trace-dump` and merged client-side into per-request
        # timelines (runtime/tracing.py)
        self.trace_ring = tracing.SpanRing()
        # silent trace loss under load must be visible: ring evictions
        # ride /metrics as lizardfs_span_ring_dropped_total
        self.trace_ring.attach_drop_counter(
            self.metrics.counter(
                "span_ring_dropped",
                help="trace spans evicted from the bounded span ring "
                     "before any dump read them",
            )
        )
        # SLO engine + flight recorder (runtime/slo.py): per-op-class
        # latency objectives whose burn rates/breach counts live in this
        # registry; breaches auto-capture their trace timeline.
        # Subclasses with a disk home point the recorder at an
        # incidents/ dir (slo.recorder.set_dir)
        self.slo = slomod.SloEngine(
            self.metrics, role=self.name, span_source=self.trace_spans
        )
        # always-on sampling profiler (runtime/profiler.py): adaptive
        # interval under a <2% overhead budget, dumped as collapsed
        # stacks via `lizardfs-admin <addr> profile`; an SLO breach
        # arms its incident boost and incident files embed the profile.
        # LZ_PROF=0 = the thread is never started (no hot-path hooks).
        # PROCESS-wide shared instance: a profile is per-process, and
        # in-process test clusters host many daemons — N private
        # samplers would contend on one GIL for N copies of the same
        # stacks (measured ~7% on the ec(8,4) row at 13 daemons; the
        # shared sampler costs <0.5%)
        self.profiler = profmod.process_profiler(role=self.name)
        self.slo.profiler = self.profiler
        self.slo.recorder.profile_source = self.profiler.collapsed
        # challenge-response admin password (None = open admin port)
        self.admin_password: str | None = None
        self.add_timer(1.0, self._sample_metrics)
        # event-loop stall watchdog (loop_watchdog.h analog): a blocked
        # loop is THE latency failure mode of an asyncio daemon — the
        # reference aborts on a stuck poll loop; here a stall is logged
        # with its duration and charted so operators see it. A sampler
        # THREAD grabs the loop thread's stack while the stall is in
        # progress (the loop itself can only notice after the fact), so
        # the warning names a file:line instead of guessing.
        self.watchdog_warn_s = 0.25
        self._wd_last = 0.0
        self._wd_max_lag = 0.0  # worst lag since the last metrics sample
        self._wd_beat = 0.0  # written by the loop tick, read by sampler
        self._wd_loop_ident = 0
        self._wd_sampler_stop: object | None = None
        self._wd_sampler_thread: object | None = None
        self._wd_stall_stack: str | None = None  # set mid-stall by sampler
        self.add_timer(0.1, self._watchdog_tick)

    def _wd_sampler(self) -> None:
        """Watchdog sampler thread: when the loop misses its heartbeat,
        snapshot the loop thread's Python stack (the culprit is whatever
        frame the loop thread is stuck in). One capture per stall; a
        stack parked in select/epoll means GIL starvation by another
        thread rather than an on-loop blocking call."""
        import time as _time
        import traceback as _tb

        captured_for = -1.0
        while not self._wd_sampler_stop.wait(0.05):
            beat = self._wd_beat
            if not beat or beat == captured_for:
                continue
            if _time.monotonic() - beat > self.watchdog_warn_s + 0.1:
                frame = sys._current_frames().get(self._wd_loop_ident)
                # validate AFTER capturing: a beat that moved means the
                # stall ended mid-capture and the frame is an innocent
                # post-stall callback — blaming it would send the
                # operator to the wrong code (GIL-starved stalls end
                # exactly when this thread gets to run again)
                if frame is not None and self._wd_beat == beat:
                    self._wd_stall_stack = "".join(_tb.format_stack(frame))
                    captured_for = beat

    async def _watchdog_tick(self) -> None:
        import time as _time

        now = _time.monotonic()
        # refresh the heartbeat FIRST: the sampler must not attribute
        # this tick's own logging to the stall it is reporting
        last, self._wd_last = self._wd_last, now
        self._wd_beat = now
        if last:
            lag = max(now - last - 0.1, 0.0)
            if lag > self.watchdog_warn_s:
                stack, self._wd_stall_stack = self._wd_stall_stack, None
                self.log.warning(
                    "event loop stalled for %.0f ms%s", lag * 1000,
                    "; loop thread was at:\n" + stack if stack
                    else " (stack not captured)",
                )
                self.metrics.counter("loop_stalls").inc()
            # hold the WORST lag until the 1 Hz sampler reads it —
            # a transient stall must not be erased by the next tick
            self._wd_max_lag = max(self._wd_max_lag, lag)

    async def _sample_metrics(self) -> None:
        self.metrics.gauge("loop_lag_ms").set(self._wd_max_lag * 1000)
        self._wd_max_lag = 0.0
        # burn gauges must decay with the windows, not freeze at the
        # last observed value when traffic stops
        self.slo.refresh_gauges()
        self.metrics.sample_all()

    def handle_admin_basics(self, msg) -> object | None:
        """Shared admin commands every daemon answers (metrics, tweaks).
        Returns a reply message or None if the command is not handled."""
        import json

        from lizardfs_tpu.proto import messages as m
        from lizardfs_tpu.proto import status as st

        command = getattr(msg, "command", None)
        if command in ("metrics", "metrics-csv"):
            try:
                payload = json.loads(msg.json) if msg.json else {}
            except ValueError:
                payload = {}
            from lizardfs_tpu.runtime.metrics import RESOLUTION_NAMES

            resolution = payload.get("resolution", "sec")
            if resolution not in RESOLUTION_NAMES:
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL, json="{}"
                )
            doc = self.metrics.to_dict(resolution)
            if command == "metrics":
                return m.AdminReply(
                    req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
                )
            # charts.cc CSV export analog: one row per series, oldest
            # first; series younger than the window get EMPTY leading
            # cells (a fabricated 0 would read as a real zero sample)
            width = max(
                (len(s.get("points", ())) for s in doc.values()), default=0
            )
            rows = ["series," + ",".join(
                f"t-{i}" for i in range(width, 0, -1)
            )]
            for name, series in doc.items():
                if "points" not in series:
                    continue  # timing histograms export via JSON only
                points = series["points"]
                padded = [""] * (width - len(points)) + [
                    str(v) for v in points
                ]
                rows.append(name + "," + ",".join(padded))
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({"csv": "\n".join(rows) + "\n"}),
            )
        if command in ("metrics-derive", "metrics-define"):
            # charts.h calc-op analog: evaluate (or register) an RPN
            # expression over this daemon's series
            from lizardfs_tpu.runtime.metrics import RESOLUTION_NAMES

            try:
                payload = json.loads(msg.json) if msg.json else {}
                expr = str(payload["expr"])
                resolution = payload.get("resolution", "sec")
                if resolution not in RESOLUTION_NAMES:
                    raise ValueError(resolution)
                if command == "metrics-define":
                    self.metrics.define(str(payload["name"]), expr)
                    doc = {"defined": str(payload["name"]), "expr": expr}
                else:
                    doc = {
                        "expr": expr, "resolution": resolution,
                        "points": self.metrics.eval_rpn(expr, resolution),
                    }
            except (ValueError, KeyError) as e:
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL,
                    json=json.dumps({"error": str(e)}),
                )
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
            )
        if command == "metrics-prom":
            # Prometheus text exposition, relayed as JSON over the admin
            # link (the webui /metrics endpoint unwraps "text" verbatim)
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({"text": self.metrics.to_prometheus()}),
            )
        if command == "trace-dump":
            try:
                payload = json.loads(msg.json) if msg.json else {}
            except ValueError:
                payload = {}
            try:
                trace_id = int(payload.get("trace_id", 0))
            except (TypeError, ValueError):
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL, json="{}"
                )
            spans = self.trace_spans(trace_id or None)
            if trace_id and not spans:
                # flight-recorder fallback: a breached op's spans were
                # captured into the incident ring at breach time, so
                # any id listed by `slowops` renders even after the
                # live span ring moved on
                spans = self.slo.recorder.incident_spans(trace_id) or []
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({"spans": spans}),
            )
        if command == "profile":
            # collapsed-stack flamegraph dump of the always-on sampling
            # profiler (runtime/profiler.py); `lizardfs-admin <addr>
            # profile` prints the text ready for flamegraph.pl
            try:
                payload = json.loads(msg.json) if msg.json else {}
            except ValueError:
                payload = {}
            top = payload.get("top")
            doc = self.profiler.snapshot()
            # the sampler is process-wide; the dump names the surface
            # it was asked through (in-process clusters share one)
            doc["role"] = self.name
            doc["collapsed"] = self.profiler.collapsed(
                int(top) if top else None
            )
            if payload.get("reset"):
                self.profiler.reset()
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
            )
        if command == "top-sessions":
            # this daemon's own per-session accounting summary (the
            # master's `top` aggregates these cluster-wide)
            from lizardfs_tpu.runtime import accounting

            ops = getattr(self, "session_ops", None)
            doc = {
                "role": self.name,
                "enabled": accounting.enabled(),
                "sessions": ops.top(16) if ops is not None else [],
            }
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK, json=json.dumps(doc)
            )
        if command == "slowops":
            # in-memory top-N slowest ops (flight recorder); each entry
            # names the trace id `trace-dump` renders
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({"slowops": self.slo.recorder.slowops()}),
            )
        if command == "health":
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(self.health_snapshot()),
            )
        if getattr(msg, "command", None) == "tweaks":
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(self.tweaks.to_dict()),
            )
        if command == "faults":
            # live fault-injection view: armed rules + fire counts +
            # the bounded event log (runtime/faults.py)
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(faultsmod.describe()),
            )
        if command == "faults-arm":
            # arm one rule (payload {"rule": "..."}) or replace the
            # whole set from a spec (payload {"spec": "...", "seed": N})
            try:
                payload = json.loads(msg.json) if msg.json else {}
                if "spec" in payload:
                    faultsmod.install(
                        str(payload["spec"]), seed=payload.get("seed")
                    )
                else:
                    faultsmod.arm(str(payload["rule"]))
            except (ValueError, KeyError, faultsmod.FaultSpecError) as e:
                return m.AdminReply(
                    req_id=msg.req_id, status=st.EINVAL,
                    json=json.dumps({"error": str(e)}),
                )
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(faultsmod.describe()),
            )
        if command == "faults-clear":
            faultsmod.clear()
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps(faultsmod.describe()),
            )
        if getattr(msg, "command", None) == "tweaks-set":
            try:
                payload = json.loads(msg.json)
                ok = self.tweaks.set(str(payload["name"]), str(payload["value"]))
            except (ValueError, KeyError):
                ok = False
            return m.AdminReply(
                req_id=msg.req_id,
                status=st.OK if ok else st.EINVAL,
                json=json.dumps(self.tweaks.to_dict()),
            )
        return None

    def trace_spans(self, trace_id: int | None = None) -> list[dict]:
        """Spans for `trace-dump` — subclasses that hold spans outside
        the ring (the chunkserver's native data plane) fold them in
        here before dumping."""
        return self.trace_ring.dump(trace_id)

    def health_snapshot(self) -> dict:
        """This daemon's health: SLO burn + stall/span-drop/disk
        signals (runtime/slo.py health_from). Subclasses extend via
        ``_health_extra``; the master aggregates the fleet's snapshots
        into the cluster `health` rollup."""
        snap = slomod.health_from(
            self.name, self.slo,
            loop_stalls=self.metrics.counter("loop_stalls").total,
            span_ring_dropped=self.trace_ring.dropped,
            disk_errors=self._health_disk_errors(),
            extra=self._health_extra(),
        )
        if faultsmod.ACTIVE:
            # incident output must NAME the injected fault: while rules
            # are armed, health carries them (with fire counts) so an
            # operator reading a degraded rollup sees the chaos drill,
            # not a mystery
            desc = faultsmod.describe()
            snap["faults"] = {
                "seed": desc["seed"],
                "rules": [
                    f"{r['rule']} (fired {r['fired']})"
                    for r in desc["rules"]
                ],
            }
        return snap

    def _health_disk_errors(self) -> int:
        return 0

    def _health_extra(self) -> dict:
        return {}

    # --- admin authentication (registered_admin_connection.cc analog) -------
    #
    # Challenge-response over the existing AdminCommand plumbing: the
    # client asks for a nonce ("auth-challenge") and answers with
    # HMAC-SHA256(password, nonce) ("auth"); the password itself never
    # crosses the wire. Privileged commands on a connection that has not
    # authenticated are refused when a password is configured.

    # commands that mutate daemon/cluster state; subclasses extend
    ADMIN_PRIVILEGED: frozenset[str] = frozenset(
        {"tweaks-set", "metrics-define", "faults-arm", "faults-clear"}
    )

    def handle_admin_auth(self, msg, state: dict) -> object | None:
        """Handle auth-challenge / auth commands; None if not one."""
        import hmac as hmac_mod
        import json
        import secrets

        from lizardfs_tpu.proto import messages as m
        from lizardfs_tpu.proto import status as st

        command = getattr(msg, "command", None)
        if command == "auth-challenge":
            nonce = secrets.token_hex(16)
            state["nonce"] = nonce
            return m.AdminReply(
                req_id=msg.req_id, status=st.OK,
                json=json.dumps({"nonce": nonce}),
            )
        if command == "auth":
            nonce = state.pop("nonce", "")
            password = getattr(self, "admin_password", None)
            try:
                payload = json.loads(msg.json)
                digest = str(payload.get("digest", "")) if isinstance(
                    payload, dict) else ""
            except ValueError:
                digest = ""
            if not password:
                # open daemon: auth trivially succeeds so ops scripts can
                # pass --password uniformly across secured/unsecured nodes
                state["authed"] = True
                return m.AdminReply(req_id=msg.req_id, status=st.OK, json="{}")
            if nonce:
                want = hmac_mod.new(
                    password.encode(), nonce.encode(), "sha256"
                ).hexdigest()
                if hmac_mod.compare_digest(want, digest):
                    state["authed"] = True
                    return m.AdminReply(
                        req_id=msg.req_id, status=st.OK, json="{}"
                    )
            return m.AdminReply(req_id=msg.req_id, status=st.EPERM, json="{}")
        return None

    def admin_refused(self, msg, state: dict) -> object | None:
        """EPERM reply if the command is privileged and the connection
        has not authenticated (and a password is configured)."""
        from lizardfs_tpu.proto import messages as m
        from lizardfs_tpu.proto import status as st

        command = getattr(msg, "command", None)
        if (
            getattr(self, "admin_password", None)
            and command in self.ADMIN_PRIVILEGED
            and not state.get("authed")
        ):
            return m.AdminReply(
                req_id=msg.req_id, status=st.EPERM,
                json='{"error": "admin authentication required"}',
            )
        return None

    def admin_gate(self, msg, state: dict) -> object | None:
        """Auth handshake + privilege gate in one step: returns the
        reply to send (challenge/auth result or EPERM refusal), or None
        when the command may proceed."""
        reply = self.handle_admin_auth(msg, state)
        if reply is None:
            reply = self.admin_refused(msg, state)
        return reply

    # --- lifecycle ---------------------------------------------------------

    async def setup(self) -> None:
        """Subclass hook: run before serving."""

    async def teardown(self) -> None:
        """Subclass hook: run on shutdown."""

    def reload(self) -> None:
        """Subclass hook: SIGHUP / admin reload-config."""

    async def handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        raise NotImplementedError

    def add_timer(self, interval: float, coro_fn) -> None:
        """Register a periodic coroutine (event_loop.h timer hook analog)."""
        self._timers.append((interval, coro_fn))

    def spawn(self, coro) -> asyncio.Task:
        """Track a background task; it is cancelled on shutdown."""
        task = asyncio.get_running_loop().create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    async def _run_timer(self, interval: float, coro_fn) -> None:
        while not self._stopping.is_set():
            try:
                await asyncio.wait_for(self._stopping.wait(), timeout=interval)
                return
            except asyncio.TimeoutError:
                pass
            try:
                await coro_fn()
            except asyncio.CancelledError:
                raise
            except Exception:
                self.log.exception("timer %s failed", getattr(coro_fn, "__name__", "?"))

    async def _guarded_connection(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")
        self._conn_writers.add(writer)
        try:
            # fault-role scoping: everything this connection's handler
            # does (incl. to_thread disk work — context propagates) is
            # attributed to THIS daemon's role, so in-process multi-
            # daemon tests match (role, site, op, peer) rules correctly
            with faultsmod.role_scope(self.name):
                await self.handle_connection(reader, writer)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass  # peer went away
        except asyncio.CancelledError:
            raise
        except Exception:
            self.log.exception("connection from %s crashed", peer)
        finally:
            self._conn_writers.discard(writer)
            await retrymod.close_writer(writer, swallow_cancel=True)

    async def start(self) -> None:
        # fault fires attributed to this role land in this registry
        # (faults_injected{site,action}, Prometheus-exported)
        faultsmod.attach_metrics(self.name, self.metrics)
        await self.setup()
        self._server = await asyncio.start_server(
            self._guarded_connection, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        for interval, coro_fn in self._timers:
            self.spawn(self._run_timer(interval, coro_fn))
        import threading

        self._wd_loop_ident = threading.get_ident()
        self._wd_sampler_stop = threading.Event()
        self._wd_sampler_thread = threading.Thread(
            target=self._wd_sampler, name=self.name + "-watchdog", daemon=True
        )
        self._wd_sampler_thread.start()
        # no-op under LZ_PROF=0 (the switch is the start gate)
        self.profiler.start()
        self.log.info("%s listening on %s:%d", self.name, self.host, self.port)

    async def stop(self) -> None:
        self._stopping.set()
        self.profiler.stop()
        if self._wd_sampler_stop is not None:
            self._wd_sampler_stop.set()
            self._wd_sampler_thread.join(timeout=1.0)
        if self._server is not None:
            self._server.close()
            # drop live connections: python 3.12's wait_closed() blocks
            # until every handler's transport is gone
            for w in list(self._conn_writers):
                w.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5.0)
            except asyncio.TimeoutError:
                self.log.warning("server close timed out with handlers alive")
        for task in list(self._tasks):
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        await self.teardown()

    async def run_forever(self) -> None:
        """Start, install signal handlers, run until SIGTERM/SIGINT."""
        # a real daemon process is single-role: make it the fault
        # framework's process default (in-process test clusters rely on
        # the per-connection role_scope instead)
        faultsmod.set_role(self.name)
        loop = asyncio.get_running_loop()
        stop = asyncio.Event()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        loop.add_signal_handler(signal.SIGHUP, self.reload)
        await self.start()
        # lint: waive(unbounded-await): run_forever parks until SIGTERM/SIGINT by design
        await stop.wait()
        self.log.info("shutting down")
        await self.stop()
