"""Runtime-tunable knobs — the `.lizardfs_tweaks` registry, daemon-side.

The reference exposes a registry of named atomics through a magic file
on the mount (reference: src/mount/tweaks.h:29-47). Here every daemon
holds a Tweaks registry readable/settable over the admin protocol
(`lizardfs-admin tweaks` / `tweaks-set`).
"""

from __future__ import annotations


class Tweak:
    def __init__(self, name: str, value, caster, on_set=None):
        self.name = name
        self.value = value
        self._cast = caster
        # side-effect hook: tweaks that alias another subsystem (e.g.
        # debug_read_delay_ms arming a fault-injection rule) react to
        # live admin sets without the daemon polling the value
        self._on_set = on_set

    def set(self, raw: str) -> None:
        self.value = self._cast(raw)
        if self._on_set is not None:
            self._on_set(self.value)


class Tweaks:
    def __init__(self):
        self._tweaks: dict[str, Tweak] = {}

    def register(self, name: str, initial, on_set=None):
        caster = type(initial)
        if caster is bool:
            caster = lambda s: str(s).lower() in ("1", "true", "yes", "on")  # noqa: E731
        t = Tweak(name, initial, caster, on_set=on_set)
        self._tweaks[name] = t
        return t

    def get(self, name: str) -> Tweak | None:
        return self._tweaks.get(name)

    def set(self, name: str, raw: str) -> bool:
        t = self._tweaks.get(name)
        if t is None:
            return False
        t.set(raw)
        return True

    def to_dict(self) -> dict:
        return {name: t.value for name, t in sorted(self._tweaks.items())}
