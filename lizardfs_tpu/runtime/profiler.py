"""Always-on adaptive sampling profiler — collapsed-stack flamegraphs
from any live daemon, with a hard overhead budget.

A plain Python thread wakes on an adaptive interval, snapshots every
thread's frame stack via ``sys._current_frames()`` (one C call, no
tracing hooks, no sys.setprofile cost on the hot path), and collapses
each stack into a ``mod.func;mod.func;...`` key in a bounded table.
``collapsed()`` renders the table in the flamegraph.pl "collapsed
stacks" text format (``stack count`` lines), dumped live via
``lizardfs-admin <addr> profile`` or a gateway's ``GET /profile``.

Self-throttling: every sample measures its own cost and re-derives the
interval so sampling stays under ``overhead_budget`` (default 2%) of
one core — a daemon serving a million-inode namespace pays more per
snapshot than an idle one, so a fixed rate would be a lie on exactly
the processes worth profiling. A FlightRecorder breach arms a
temporary boost window (:meth:`arm_incident`) so incident captures
carry stacks at useful resolution, still under the budget ceiling.

Bounded memory: at most ``max_stacks`` distinct collapsed stacks;
overflow folds into the ``(truncated)`` row and counts ``dropped``.

Cost contract: ``LZ_PROF=0`` means the thread is never started —
byte-equivalent to the pre-profiler tree (there are no hot-path hooks
to disable; the only cost is the thread itself).
"""

from __future__ import annotations

import sys
import threading
import time

from lizardfs_tpu.constants import env_flag

_ENABLED = env_flag("LZ_PROF")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Test/ops hook mirroring the LZ_PROF env gate."""
    global _ENABLED
    _ENABLED = bool(on)


class SamplingProfiler:
    """The sampler. start()/stop() bound the thread's life (refcounted:
    in-process test clusters host many daemons in ONE interpreter, and
    a profile is per-process by nature — N daemons sharing the
    process-wide instance via :func:`process_profiler` pay for ONE
    sampler thread, not N samplers contending on the same GIL).
    Everything else is safe to call any time."""

    # interval clamps: never hotter than 200 Hz, never colder than 4 s
    MIN_INTERVAL_S = 0.005
    MAX_INTERVAL_S = 4.0

    def __init__(self, role: str = "", interval_s: float = 0.025,
                 max_stacks: int = 2048, overhead_budget: float = 0.02):
        self.role = role
        self.base_interval_s = interval_s
        self.max_stacks = max_stacks
        self.overhead_budget = overhead_budget
        self.interval_s = interval_s
        self.samples = 0
        self.dropped = 0
        self.sample_cost_s = 0.0  # EWMA of one snapshot's cost
        self._counts: dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._stop: threading.Event | None = None
        self._thread: threading.Thread | None = None
        self._incident_until = 0.0
        self._starts = 0  # refcount: stop() below start() count is a no-op

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._starts += 1
        if not _ENABLED or self._thread is not None:
            return
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop,
            name=f"{self.role or 'lz'}-profiler",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._starts = max(self._starts - 1, 0)
        if self._thread is None or self._starts > 0:
            return
        self._stop.set()
        self._thread.join(timeout=1.0)
        self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None

    # --- sampling ----------------------------------------------------------

    def arm_incident(self, duration_s: float = 30.0) -> None:
        """Boost the sample rate for an incident window (called by the
        SLO engine on a breach) so the flight-recorded capture carries
        stacks at useful resolution. The overhead throttle still
        applies — arming never exceeds the budget, it only stops the
        idle back-off."""
        self._incident_until = max(
            self._incident_until, time.monotonic() + duration_s
        )

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval_s):
            t0 = time.perf_counter()
            try:
                frames = sys._current_frames()
            except RuntimeError:  # interpreter tearing down
                break
            with self._lock:
                for ident, frame in frames.items():
                    if ident == me:
                        continue
                    stack = []
                    depth = 0
                    while frame is not None and depth < 64:
                        code = frame.f_code
                        mod = code.co_filename.rpartition("/")[2]
                        if mod.endswith(".py"):
                            mod = mod[:-3]
                        stack.append(f"{mod}.{code.co_name}")
                        frame = frame.f_back
                        depth += 1
                    if not stack:
                        continue
                    key = tuple(reversed(stack))  # root first
                    if key not in self._counts and (
                        len(self._counts) >= self.max_stacks
                    ):
                        key = ("(truncated)",)
                        self.dropped += 1
                    self._counts[key] = self._counts.get(key, 0) + 1
                self.samples += 1
            cost = time.perf_counter() - t0
            # EWMA the snapshot cost, then size the interval so
            # cost/interval stays under the budget; incidents pin the
            # interval at the budget-derived floor instead of letting
            # the idle clamp stretch it
            self.sample_cost_s = (
                cost if not self.sample_cost_s
                else 0.8 * self.sample_cost_s + 0.2 * cost
            )
            want = max(
                self.sample_cost_s / self.overhead_budget,
                self.MIN_INTERVAL_S,
            )
            if time.monotonic() >= self._incident_until:
                want = max(want, self.base_interval_s)
            self.interval_s = min(want, self.MAX_INTERVAL_S)

    # --- output ------------------------------------------------------------

    def collapsed(self, top: int | None = None) -> str:
        """flamegraph.pl collapsed-stacks text: one ``a;b;c count``
        line per distinct stack, heaviest first."""
        with self._lock:
            rows = sorted(
                self._counts.items(), key=lambda kv: (-kv[1], kv[0])
            )
        if top is not None:
            rows = rows[:top]
        return "\n".join(f"{';'.join(k)} {n}" for k, n in rows)

    def snapshot(self) -> dict:
        """Stats header for the admin/HTTP dumps."""
        with self._lock:
            stacks = len(self._counts)
        return {
            "role": self.role,
            "enabled": _ENABLED,
            "running": self.running,
            "samples": self.samples,
            "stacks": stacks,
            "dropped": self.dropped,
            "interval_ms": round(self.interval_s * 1e3, 2),
            "sample_cost_us": round(self.sample_cost_s * 1e6, 1),
            "overhead_budget_pct": self.overhead_budget * 100,
            "incident_armed": time.monotonic() < self._incident_until,
        }

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self.samples = 0
            self.dropped = 0


# the process-wide instance every daemon/gateway shares (created on
# first use; the role tags who registered first, purely informational)
_PROCESS: SamplingProfiler | None = None


def process_profiler(role: str = "") -> SamplingProfiler:
    """The per-process shared profiler. Daemons call ``start()``/
    ``stop()`` on it like on a private instance — the refcount keeps
    one sampler thread alive while ANY registrant is running."""
    global _PROCESS
    if _PROCESS is None:
        _PROCESS = SamplingProfiler(role=role or "process")
    return _PROCESS
