"""Multi-tenant QoS: fair-share admission + weighted data-plane queueing.

One hot client must degrade gracefully per-tenant — shed the abuser,
not the fleet (ROADMAP 4). This module holds the three mechanisms every
role composes, built on the PR-12 session-identity substrate and the
in-tree budget primitives (:mod:`lizardfs_tpu.runtime.limiter`):

* :class:`TenantMap` — sessions map to tenants at registration time
  (config-driven fnmatch rules over the client ``info`` string and the
  export root path; everything else lands on the default tenant).
  Identity then rides the existing ``session_id`` plumbing, so the
  data plane needs no new wire fields.
* :class:`FairShare` — the master's admission controller: per-tenant,
  per-op-class (read/write/meta_read/meta_write/locate) weighted token
  buckets over a shared class rate.  Shares are weighted max-min among
  *recently active* tenants, so a lone tenant may use the whole class
  budget while two contending tenants converge to their weight ratio.
  A refused op is shed with the transient ``BUSY`` status carrying a
  retry-after hint; clients retry through the unified RetryPolicy.
* :class:`DrrByteQueue` — the chunkserver's data-plane fair queue:
  weighted deficit-round-robin over a shared in-flight byte budget
  (:class:`~lizardfs_tpu.runtime.limiter.CreditBucket` semantics:
  credits return when the disk work completes).  While the budget has
  headroom admission is immediate; under contention queued tenants are
  granted in DRR order with a quantum proportional to their weight, so
  in-flight disk-queue bytes converge to the weight ratio.  Rebuild
  traffic enters as the reserved ``_rebuild`` pseudo-tenant, capping
  RebuildEngine vs. client bandwidth both ways.

Kill-switch contract: ``LZ_QOS`` (constants.qos_enabled, default ON —
but with NO configuration the engine admits everything, so an
unconfigured cluster is byte-identical either way).  Every enforcement
site checks the switch before touching the engine; off means one
accessor call and nothing else (pinned in tests/test_qos.py).
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque
from fnmatch import fnmatchcase

from lizardfs_tpu.runtime.limiter import CreditBucket, TokenBucket

# the one class vocabulary shared by master admission (locate/meta_*/
# write grants) and the chunkserver data plane (read/write bytes)
OP_CLASSES = ("locate", "read", "write", "meta_read", "meta_write")
# the subset master admission actually maps RPCs onto — "read" is a
# DATA-PLANE class (bytes under the chunkserver's DRR budget, not a
# master ops/s rate); accepting a rates["read"] that silently binds to
# nothing would be a config footgun, so parse_config rejects it
MASTER_RATE_CLASSES = ("locate", "write", "meta_read", "meta_write")

DEFAULT_TENANT = "default"
# reserved pseudo-tenant the chunkserver charges RebuildEngine traffic
# to: rebuilds and clients share the DRR queue, so neither can starve
# the other
REBUILD_TENANT = "_rebuild"

# a tenant counts toward the fair-share split while it sent traffic in
# the last ACTIVE_WINDOW_S (work-conserving: idle tenants donate their
# share instead of wasting it)
ACTIVE_WINDOW_S = 5.0

# retry-after hint clamp (ms): never tell a client "retry now" (it
# would spin on the shed path) nor park it long enough to breach its
# own deadline before the first retry
MIN_RETRY_MS = 10
MAX_RETRY_MS = 1000


def parse_config(text: str) -> dict:
    """Parse a QOS_CFG file (JSON) into the canonical config doc::

        {
          "default_tenant": "default",
          "tenants": {
            "gold":   {"weight": 4, "match": ["vip-*"], "p99_ms": 50},
            "bulk":   {"weight": 1, "match": ["scanner*"]}
          },
          "rates":  {"locate": 2000, "meta_read": 0, ...},  # ops/s, 0=unl
          "data_inflight_mb": 64,     # CS in-flight byte budget (0=off)
          "data_bps": 0,              # optional native per-session pacing
          "rebuild_weight": 1
        }

    Raises ``ValueError`` on malformed input (reload keeps the previous
    config; strict startup load fails loudly)."""
    doc = json.loads(text or "{}")
    if not isinstance(doc, dict):
        raise ValueError("qos config must be a JSON object")
    tenants = doc.get("tenants", {})
    if not isinstance(tenants, dict):
        raise ValueError("qos 'tenants' must be an object")
    for name, t in tenants.items():
        if not isinstance(t, dict):
            raise ValueError(f"qos tenant {name!r} must be an object")
        if float(t.get("weight", 1.0)) <= 0:
            raise ValueError(f"qos tenant {name!r}: weight must be > 0")
    rates = doc.get("rates", {})
    if not isinstance(rates, dict):
        raise ValueError("qos 'rates' must be an object")
    for cls in rates:
        if cls not in MASTER_RATE_CLASSES:
            raise ValueError(
                f"qos rate for op class {cls!r} — master admission "
                f"rates are {MASTER_RATE_CLASSES} (data-plane bytes are "
                "budgeted via data_inflight_mb/data_bps, not a rate)"
            )
    return doc


class TenantMap:
    """Session -> tenant resolution, decided once at registration.

    Rules are ``(pattern, tenant)`` pairs matched with fnmatch against
    the client's ``info`` string first, then the export-root path the
    session registered under; first match wins, no match lands on the
    default tenant."""

    def __init__(self, rules: list[tuple[str, str]] | None = None,
                 default: str = DEFAULT_TENANT):
        self.rules = list(rules or [])
        self.default = default

    @classmethod
    def from_config(cls, doc: dict) -> "TenantMap":
        rules = []
        for name, t in (doc.get("tenants") or {}).items():
            for pat in t.get("match", ()):
                rules.append((str(pat), str(name)))
        return cls(rules, str(doc.get("default_tenant", DEFAULT_TENANT)))

    def tenant_of(self, info: str = "", export_path: str = "") -> str:
        for pat, tenant in self.rules:
            if fnmatchcase(info, pat) or (
                export_path and fnmatchcase(export_path, pat)
            ):
                return tenant
        return self.default


class FairShare:
    """Per-tenant, per-op-class weighted admission over shared class
    rates (the master's RPC-loop controller).

    Each configured op class has a total rate (ops/s).  Active tenants
    split it by weight into per-(tenant, class) ``TokenBucket``s;
    shares recompute when the active set changes (or every second).
    ``admit`` returns ``None`` (admitted) or a retry-after hint in ms
    (shed)."""

    def __init__(self, now_fn=time.monotonic):
        self._now = now_fn
        self.weights: dict[str, float] = {}
        self.rates: dict[str, float] = {c: 0.0 for c in OP_CLASSES}
        # per-tenant latency objective (ms) the health rollup evaluates
        self.objectives: dict[str, float] = {}
        self._buckets: dict[tuple[str, str], TokenBucket] = {}
        self._last_seen: dict[str, float] = {}
        self._shares_at = 0.0
        self._active_key: tuple = ()
        # shed accounting for health/`top`: tenant -> [count, last_ts]
        self.sheds: dict[str, list] = {}
        self.generation = 0

    # --- config ------------------------------------------------------------

    def configure(self, doc: dict) -> None:
        """Install a parsed config doc (SIGHUP / admin / tweak path)."""
        tenants = doc.get("tenants") or {}
        self.weights = {
            str(n): float(t.get("weight", 1.0)) for n, t in tenants.items()
        }
        self.objectives = {
            str(n): float(t["p99_ms"]) for n, t in tenants.items()
            if "p99_ms" in t
        }
        rates = doc.get("rates") or {}
        self.rates = {
            c: float(rates.get(c, 0.0)) for c in OP_CLASSES
        }
        self._buckets.clear()
        self._shares_at = 0.0
        self.generation += 1

    def set_weight(self, tenant: str, weight: float) -> None:
        if weight <= 0:
            raise ValueError("weight must be > 0")
        self.weights[str(tenant)] = float(weight)
        self._shares_at = 0.0
        self.generation += 1

    def set_rate(self, op_class: str, rate: float) -> None:
        if op_class not in MASTER_RATE_CLASSES:
            raise ValueError(f"unknown admission op class {op_class!r}")
        self.rates[op_class] = max(float(rate), 0.0)
        self._shares_at = 0.0
        self.generation += 1

    @property
    def armed(self) -> bool:
        """True when any class has a finite rate — an unconfigured
        engine admits everything without creating buckets."""
        return any(r > 0 for r in self.rates.values())

    # --- admission ---------------------------------------------------------

    def _weight_of(self, tenant: str) -> float:
        return self.weights.get(tenant, 1.0)

    def _recompute_shares(self, now: float) -> None:
        lo = now - ACTIVE_WINDOW_S
        active = sorted(
            t for t, ts in self._last_seen.items() if ts >= lo
        )
        key = tuple(active)
        if key == self._active_key and now - self._shares_at < 1.0:
            return
        self._active_key = key
        self._shares_at = now
        total_w = sum(self._weight_of(t) for t in active) or 1.0
        for cls, rate in self.rates.items():
            if rate <= 0:
                continue
            for t in active:
                share = rate * self._weight_of(t) / total_w
                bucket = self._buckets.get((t, cls))
                if bucket is None:
                    # burst = one second of the tenant's share (min 1):
                    # short bursts ride through, sustained floods pace
                    self._buckets[(t, cls)] = TokenBucket(
                        share, max(share, 1.0), now_fn=self._now
                    )
                else:
                    bucket.rate = share
                    bucket.burst = max(share, 1.0)
        # drop buckets of tenants that went idle (their share returns
        # to the pool at the next recompute; state stays bounded)
        for t, cls in [k for k in self._buckets if k[0] not in key]:
            del self._buckets[(t, cls)]

    def admit(self, tenant: str, op_class: str,
              cost: float = 1.0) -> int | None:
        """Admit one op or return a retry-after hint in ms (shed)."""
        rate = self.rates.get(op_class, 0.0)
        now = self._now()
        self._last_seen[tenant] = now
        if len(self._last_seen) > 4096:
            lo = now - ACTIVE_WINDOW_S
            self._last_seen = {
                t: ts for t, ts in self._last_seen.items() if ts >= lo
            }
            self._last_seen[tenant] = now
        if rate <= 0:
            return None
        self._recompute_shares(now)
        bucket = self._buckets.get((tenant, op_class))
        if bucket is None:
            self._shares_at = 0.0  # brand-new tenant: force a split
            self._recompute_shares(now)
            bucket = self._buckets.get((tenant, op_class))
            if bucket is None:  # pragma: no cover — rate raced to 0
                return None
        if bucket.try_acquire(cost):
            return None
        # deficit in tokens -> ms until the bucket can cover the cost
        deficit = cost - bucket._tokens
        retry_ms = int(deficit / max(bucket.rate, 1e-6) * 1000.0)
        retry_ms = max(MIN_RETRY_MS, min(retry_ms, MAX_RETRY_MS))
        shed = self.sheds.setdefault(tenant, [0, 0.0])
        shed[0] += 1
        shed[1] = now
        return retry_ms

    # --- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-ready state for `lizardfs-admin qos` / health."""
        now = self._now()
        lo = now - ACTIVE_WINDOW_S
        return {
            "armed": self.armed,
            "rates": {c: r for c, r in self.rates.items() if r > 0},
            "weights": dict(self.weights),
            "objectives_ms": dict(self.objectives),
            "active_tenants": sorted(
                t for t, ts in self._last_seen.items() if ts >= lo
            ),
            "sheds": {
                t: {"count": c, "age_s": round(max(now - ts, 0.0), 1)}
                for t, (c, ts) in self.sheds.items()
            },
            "generation": self.generation,
        }

    def throttled_tenants(self, within_s: float = 10.0) -> list[str]:
        """Tenants shed within the last ``within_s`` — what health and
        `top` NAME as currently throttled."""
        now = self._now()
        return sorted(
            t for t, (_c, ts) in self.sheds.items()
            if now - ts <= within_s
        )


class DrrByteQueue:
    """Weighted deficit-round-robin admission of data-plane byte work
    over a shared in-flight credit budget.

    ``admit(tenant, nbytes)`` takes ``nbytes`` credits out; ``done``
    puts them back when the disk work completed (CreditBucket
    semantics — the budget bounds outstanding WORK, not a rate).  While
    credits cover the request and nobody queues, admission is one dict
    lookup.  Under contention each tenant's waiters queue FIFO and the
    drain grants across tenants in DRR order: every round a tenant's
    deficit grows by ``quantum * weight`` and its head waiters are
    granted while the deficit (and shared credits) cover them — so
    in-flight bytes converge to the weight ratio, and a tenant with
    jumbo requests cannot lock out small ones for more than a round."""

    # one DRR visit's base quantum (bytes), multiplied by weight — at
    # the 64 KiB block scale so weights bite at request granularity (a
    # chunk-sized quantum would let arrival order decide instead)
    QUANTUM = 64 * 1024

    def __init__(self, capacity: float = 0.0):
        self.bucket = CreditBucket(capacity)
        self.weights: dict[str, float] = {}
        # tenant -> deque[(nbytes, future)]
        self._queues: dict[str, deque] = {}
        self._deficit: dict[str, float] = {}
        # round-robin order over tenants with queued work
        self._rr: deque[str] = deque()
        # True when the front tenant is OWED its per-visit quantum: a
        # credit-blocked drain resumes mid-service WITHOUT re-crediting
        # (re-adding per resume would bank unbounded deficit and defeat
        # the weights entirely)
        self._fresh_visit = True
        self.throttle_waits = 0  # ops that had to queue
        self.granted_bytes: dict[str, int] = {}

    def configure(self, weights: dict[str, float],
                  capacity_bytes: float) -> None:
        self.weights = {str(t): float(w) for t, w in weights.items()}
        # preserve outstanding work across a live resize: credits track
        # the NEW capacity minus what is still in flight (a shrink can
        # go to zero; in-flight done() calls pay the debt back)
        outstanding = max(self.bucket.capacity - self.bucket._credits, 0.0)
        self.bucket.capacity = float(capacity_bytes)
        self.bucket._credits = max(float(capacity_bytes) - outstanding, 0.0)
        self._drain()  # a grown budget may unblock queued waiters

    @property
    def armed(self) -> bool:
        return self.bucket.capacity > 0

    def _weight_of(self, tenant: str) -> float:
        return self.weights.get(tenant, 1.0)

    async def admit(self, tenant: str, nbytes: int) -> bool:
        """Take ``nbytes`` in-flight credits for ``tenant``; returns
        True iff the caller had to queue (throttle observability, the
        CreditBucket.acquire contract)."""
        if self.bucket.capacity <= 0 or nbytes <= 0:
            return False
        n = min(float(nbytes), self.bucket.capacity)
        if not self._queues and self.bucket.try_acquire(n):
            self.granted_bytes[tenant] = (
                self.granted_bytes.get(tenant, 0) + nbytes
            )
            return False
        self.throttle_waits += 1
        fut = asyncio.get_running_loop().create_future()
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            self._deficit.setdefault(tenant, 0.0)
            self._rr.append(tenant)
        q.append((n, fut))
        # drain now: the queue may hold only cancelled husks (or this
        # waiter may fit the current credits under DRR order) and with
        # nothing in flight no done() would ever run — a parked waiter
        # with a full bucket is the deadlock this call forecloses
        self._drain()
        try:
            await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # granted concurrently with the cancellation: the
                # caller will never run `done()`, return the credits
                self.bucket.release(n)
            else:
                try:
                    q.remove((n, fut))
                except ValueError:
                    pass
            raise
        self.granted_bytes[tenant] = (
            self.granted_bytes.get(tenant, 0) + nbytes
        )
        return True

    def done(self, tenant: str, nbytes: int) -> None:
        if self.bucket.capacity <= 0 or nbytes <= 0:
            return
        n = min(float(nbytes), self.bucket.capacity)
        self.bucket.release(n)
        self._drain()

    def _drop_front(self) -> None:
        t = self._rr.popleft()
        self._queues.pop(t, None)
        self._deficit.pop(t, None)
        self._fresh_visit = True

    def _drain(self) -> None:
        """Grant queued waiters in weighted-DRR order (classic DRR:
        one quantum x weight per VISIT, leftover deficit persists while
        the queue stays backlogged, resets when it empties). Returns
        when the front waiter is blocked on CREDITS — the next
        ``done()`` resumes exactly where service stopped, mid-visit,
        without re-crediting the quantum. A head blocked only on its
        tenant's deficit keeps lapping: deficits grow per lap, so
        progress is guaranteed."""
        while True:
            # prune tenants whose queue emptied (incl. cancellations)
            while self._rr and not self._queues.get(self._rr[0]):
                self._drop_front()
            if not self._rr:
                return
            granted = False
            for _ in range(len(self._rr)):
                tenant = self._rr[0]
                q = self._queues.get(tenant)
                if not q:
                    self._drop_front()
                    continue
                if self._fresh_visit:
                    self._deficit[tenant] = (
                        self._deficit.get(tenant, 0.0)
                        + self.QUANTUM * self._weight_of(tenant)
                    )
                    self._fresh_visit = False
                while q:
                    n, fut = q[0]
                    if fut.done():  # cancelled waiter left behind
                        q.popleft()
                        continue
                    if n > self._deficit[tenant]:
                        break  # visit over: deficit spent
                    if not self.bucket.try_acquire(n):
                        # credit-blocked MID-VISIT: resume here on the
                        # next done() (fresh stays False — no re-credit)
                        return
                    q.popleft()
                    self._deficit[tenant] -= n
                    fut.set_result(None)
                    granted = True
                if not q:
                    self._drop_front()
                else:
                    self._rr.rotate(-1)
                    self._fresh_visit = True
            if not granted:
                # a full lap granted nothing and nobody was credit-
                # blocked: every head is deficit-blocked — lap again
                # (each lap accrues one quantum per tenant, so the
                # largest clamped request is reached in finite laps)
                continue

    def waiting(self) -> dict[str, int]:
        return {t: len(q) for t, q in self._queues.items() if q}

    def snapshot(self) -> dict:
        return {
            "armed": self.armed,
            "capacity": self.bucket.capacity,
            "available": round(self.bucket.available, 1),
            "weights": dict(self.weights),
            "waiting": self.waiting(),
            "throttle_waits": self.throttle_waits,
            "granted_bytes": dict(self.granted_bytes),
        }


def busy_backoff_s(retry_after_ms: int, attempt: int, rng=None) -> float:
    """Jittered sleep before retrying a BUSY-shed op: honor the
    server's hint, escalate with the attempt count, and jitter so a
    thundering herd of shed clients doesn't re-arrive in phase."""
    import random as _random

    rng = rng or _random
    base = (retry_after_ms / 1000.0) if retry_after_ms > 0 else 0.05
    delay = min(base * (1.5 ** attempt), 2.0)
    return delay * (0.5 + rng.random())
