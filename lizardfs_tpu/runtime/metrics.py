"""In-process time-series metrics — the charts subsystem, modernized.

The reference keeps RRD-like fixed-range in-memory series per daemon and
renders them to GIF/CSV over the admin protocol (reference:
src/common/charts.cc, chartsdata.cc registrations). Same data model
here — counters and gauges sampled into fixed-size rings at three
resolutions (seconds/minutes/hours) — exported as JSON over the admin
link instead of server-rendered images.
"""

from __future__ import annotations

import time
from collections import deque


RESOLUTIONS = (("sec", 1.0, 120), ("min", 60.0, 120), ("hour", 3600.0, 120))


class Series:
    def __init__(self, name: str, kind: str = "counter"):
        self.name = name
        self.kind = kind  # counter: rate per tick; gauge: last value
        self.total = 0.0
        self.value = 0.0  # gauges
        self._rings = {
            rname: deque(maxlen=size) for rname, _, size in RESOLUTIONS
        }
        self._last_total = {rname: 0.0 for rname, _, _ in RESOLUTIONS}
        self._last_ts = {rname: 0.0 for rname, _, _ in RESOLUTIONS}

    def inc(self, n: float = 1.0) -> None:
        self.total += n

    def set(self, v: float) -> None:
        self.value = v

    def sample(self, now: float) -> None:
        for rname, period, _ in RESOLUTIONS:
            if now - self._last_ts[rname] >= period:
                if self.kind == "counter":
                    self._rings[rname].append(self.total - self._last_total[rname])
                    self._last_total[rname] = self.total
                else:
                    self._rings[rname].append(self.value)
                self._last_ts[rname] = now

    def to_dict(self, resolution: str = "sec") -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "total": self.total if self.kind == "counter" else self.value,
            "resolution": resolution,
            "points": list(self._rings.get(resolution, ())),
        }


class Metrics:
    def __init__(self):
        self.series: dict[str, Series] = {}

    def counter(self, name: str) -> Series:
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = Series(name, "counter")
        return s

    def gauge(self, name: str) -> Series:
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = Series(name, "gauge")
        return s

    def sample_all(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        for s in self.series.values():
            s.sample(now)

    def to_dict(self, resolution: str = "sec") -> dict:
        return {
            name: s.to_dict(resolution) for name, s in sorted(self.series.items())
        }
