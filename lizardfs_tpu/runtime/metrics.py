"""In-process time-series metrics — the charts subsystem, modernized.

The reference keeps RRD-like fixed-range in-memory series per daemon and
renders them to GIF/CSV over the admin protocol (reference:
src/common/charts.cc, chartsdata.cc registrations). Same data model
here — counters and gauges sampled into fixed-size rings at five
resolutions spanning two minutes to three months — exported as JSON over
the admin link instead of server-rendered images.

Derived series reproduce the reference's chart calc ops (reference:
src/common/charts.h:26-42 CHARTS_CALC / ADD/SUB/MIN/MAX/MUL/DIV and
charts.cc get_dataf): an RPN expression over series names and constants,
evaluated elementwise at any resolution, either ad hoc
(:meth:`Metrics.eval_rpn`) or registered by name
(:meth:`Metrics.define`) so it exports like a first-class series.
"""

from __future__ import annotations

import time
from collections import deque

# (name, sampling period s, ring length) — spans: 2 min, 3 h, 1 day,
# 1 week, 3 months (the reference's short/medium/long/verylong ranges,
# charts.cc RANGE sampling)
RESOLUTIONS = (
    ("sec", 1.0, 120),
    ("min", 60.0, 180),
    ("tenmin", 600.0, 144),
    ("hour", 3600.0, 168),
    ("day", 86400.0, 92),
)

RESOLUTION_NAMES = tuple(r[0] for r in RESOLUTIONS)

RPN_OPS = ("ADD", "SUB", "MUL", "DIV", "MIN", "MAX")


def _prom_name(name: str) -> str:
    """Series name -> valid Prometheus metric-name fragment."""
    return "".join(
        c if c.isalnum() or c == "_" else "_" for c in name
    )


def _prom_value(v: float) -> str:
    # integral values print without the trailing ".0" scrapers choke on
    # less often than one would hope
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _prom_help(text: str) -> str:
    """Escape a HELP string per exposition format 0.0.4 (backslash and
    line feed are the only escapes on HELP lines)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


class Series:
    def __init__(self, name: str, kind: str = "counter"):
        self.name = name
        self.kind = kind  # counter: rate per tick; gauge: last value
        self.total = 0.0
        self.value = 0.0  # gauges
        self._rings = {
            rname: deque(maxlen=size) for rname, _, size in RESOLUTIONS
        }
        self._last_total = {rname: 0.0 for rname, _, _ in RESOLUTIONS}
        self._last_ts = {rname: 0.0 for rname, _, _ in RESOLUTIONS}

    def inc(self, n: float = 1.0) -> None:
        self.total += n

    def set(self, v: float) -> None:
        self.value = v

    def sample(self, now: float) -> None:
        for rname, period, _ in RESOLUTIONS:
            if now - self._last_ts[rname] >= period:
                if self.kind == "counter":
                    self._rings[rname].append(self.total - self._last_total[rname])
                    self._last_total[rname] = self.total
                else:
                    self._rings[rname].append(self.value)
                self._last_ts[rname] = now

    def to_dict(self, resolution: str = "sec") -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "total": self.total if self.kind == "counter" else self.value,
            "resolution": resolution,
            "points": list(self._rings.get(resolution, ())),
        }


class Timing:
    """Latency histogram with log2 buckets (request_log.h scope-timing
    analog): record() costs one int_log2 + two adds; export gives
    count/sum/max plus per-bucket counts for percentile estimates.

    A nonzero ``trace_id`` passed to :meth:`record` becomes the
    histogram's EXEMPLAR — the trace of the slowest recent op — so a
    hot cell on the metrics page links straight to a ``trace-dump``
    timeline. The exemplar decays: a newer op replaces it when it is at
    least as slow, or when the stored one is older than a minute (a
    one-off spike must not pin a stale id forever)."""

    # bucket i covers [2^i, 2^(i+1)) microseconds; 20 buckets = 1us..1s+
    NBUCKETS = 20
    EXEMPLAR_TTL_S = 60.0

    __slots__ = ("name", "count", "total_us", "max_us", "buckets",
                 "exemplar_trace_id", "exemplar_us", "exemplar_ts")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total_us = 0.0
        self.max_us = 0.0
        self.buckets = [0] * self.NBUCKETS
        self.exemplar_trace_id = 0
        self.exemplar_us = 0.0
        self.exemplar_ts = 0.0

    def record(self, seconds: float, trace_id: int = 0) -> None:
        us = seconds * 1e6
        self.count += 1
        self.total_us += us
        if us > self.max_us:
            self.max_us = us
        b = max(int(us), 1).bit_length() - 1
        self.buckets[min(b, self.NBUCKETS - 1)] += 1
        if trace_id:
            now = time.monotonic()
            if (
                us >= self.exemplar_us
                or now - self.exemplar_ts > self.EXEMPLAR_TTL_S
            ):
                self.exemplar_trace_id = trace_id
                self.exemplar_us = us
                self.exemplar_ts = now

    def quantile_us(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile latency from the log2
        buckets (the p99 the `top` view renders). Exact to within one
        bucket (a factor of 2), which is the honest resolution a
        20-bucket histogram has."""
        if not self.count:
            return 0.0
        want = q * self.count
        cum = 0
        for i, n in enumerate(self.buckets):
            cum += n
            if cum >= want:
                return float(2 ** (i + 1))
        return self.max_us

    def to_dict(self) -> dict:
        out = {
            "name": self.name, "kind": "timing", "count": self.count,
            "avg_us": round(self.total_us / self.count, 1) if self.count
            else 0.0,
            "max_us": round(self.max_us, 1),
            "buckets_us_log2": list(self.buckets),
        }
        if self.exemplar_trace_id:
            out["exemplar_trace_id"] = f"0x{self.exemplar_trace_id:x}"
            out["exemplar_us"] = round(self.exemplar_us, 1)
        return out


class PhaseBreakdown:
    """Per-phase busy-time accounting for a multi-phase operation (the
    client write pipeline's encode/stage/send/commit split).

    Each ``add`` charges wall-clock seconds spent *inside* one phase;
    ``add_wall`` closes one rep (one whole operation) with its end-to-end
    time. In a serial execution the phase totals sum to ~the wall total;
    in a pipelined execution phases overlap, so the sum legitimately
    exceeds wall time — the gap IS the overlap win. ``snapshot`` returns
    cumulative totals; subtract two snapshots (:func:`phase_delta`) to
    scope the breakdown to a measured interval (bench reps)."""

    __slots__ = ("name", "phase_names", "totals_s", "wall_s", "reps")

    def __init__(self, name: str, phase_names: tuple[str, ...]):
        self.name = name
        self.phase_names = tuple(phase_names)
        self.totals_s = {p: 0.0 for p in self.phase_names}
        self.wall_s = 0.0
        self.reps = 0

    def add(self, phase: str, seconds: float) -> None:
        self.totals_s[phase] += seconds

    def add_wall(self, seconds: float) -> None:
        self.wall_s += seconds
        self.reps += 1

    def snapshot(self) -> dict:
        out = {f"{p}_ms": round(v * 1e3, 2) for p, v in self.totals_s.items()}
        out["wall_ms"] = round(self.wall_s * 1e3, 2)
        out["reps"] = self.reps
        return out


def phase_delta(after: dict, before: dict) -> dict:
    """Elementwise ``after - before`` of two :meth:`PhaseBreakdown.snapshot`
    dicts (same keys), rounded back to centi-ms."""
    return {
        k: round(after[k] - before.get(k, 0), 2) if k != "reps"
        else after[k] - before.get(k, 0)
        for k in after
    }


def _label_value(v) -> str:
    """Sanitize a label value for the 0.0.4 exposition (quotes and
    backslashes would need escaping; names stay simpler without them)."""
    return "".join(
        c if c not in '"\\\n' else "_" for c in str(v)
    )


# Per-family cap on distinct label combinations: a label value drawn
# from an unbounded domain (session ids, file names) must not grow the
# registry — and the scrape page — without bound. Past the cap, new
# combinations fold into the same label NAMES with every value
# "other", so totals stay truthful while cardinality stays fixed.
LABEL_VARIANT_CAP = 256


class Metrics:
    def __init__(self):
        self.series: dict[str, Series] = {}
        self.derived: dict[str, str] = {}  # name -> RPN expression
        self.timings: dict[str, Timing] = {}
        # labeled counter families (faults_injected{site,action} style):
        # family name -> {sorted (label, value) tuple -> Series}. One
        # HELP/TYPE block per family on the Prometheus page, one sample
        # line per label combination.
        self.labeled: dict[str, dict[tuple, Series]] = {}
        # labeled Timing families (session_ops{session,op} style): one
        # HELP/TYPE histogram block per family, per-combination
        # bucket/_sum/_count samples, trace-id exemplars on +Inf
        self.labeled_timings: dict[str, dict[tuple, Timing]] = {}
        # per-series HELP text (Prometheus exposition); series without
        # an explicit entry export an auto-generated line so every
        # scraped metric carries help (the metrics-lint contract)
        self.help: dict[str, str] = {}

    def describe(self, name: str, help: str | None) -> None:
        if help:
            self.help[name] = help

    def help_for(self, name: str, kind: str = "series") -> str:
        return self.help.get(name) or f"lizardfs {kind} {name}"

    def timing(self, name: str, help: str | None = None) -> Timing:
        t = self.timings.get(name)
        if t is None:
            t = self.timings[name] = Timing(name)
        self.describe(name, help)
        return t

    def counter(self, name: str, help: str | None = None) -> Series:
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = Series(name, "counter")
        self.describe(name, help)
        return s

    def gauge(self, name: str, help: str | None = None) -> Series:
        s = self.series.get(name)
        if s is None:
            s = self.series[name] = Series(name, "gauge")
        self.describe(name, help)
        return s

    @staticmethod
    def _label_key(variants: dict, labels: dict) -> tuple:
        """Sorted, sanitized (label, value) key for one combination,
        folding NEW combinations past LABEL_VARIANT_CAP into the
        all-"other" overflow bucket (same label names, bounded page)."""
        key = tuple(sorted(
            (str(k), _label_value(v)) for k, v in labels.items()
        ))
        if key not in variants and len(variants) >= LABEL_VARIANT_CAP:
            key = tuple((k, "other") for k, _ in key)
        return key

    def labeled_counter(
        self, family: str, labels: dict, help: str | None = None
    ) -> Series:
        """One Series per (family, label-set) combination, exported as a
        single Prometheus counter family with per-combination samples."""
        variants = self.labeled.setdefault(family, {})
        key = self._label_key(variants, labels)
        s = variants.get(key)
        if s is None:
            decorated = family + "{" + ",".join(
                f'{k}="{v}"' for k, v in key
            ) + "}"
            s = variants[key] = Series(decorated, "counter")
        self.describe(family, help)
        return s

    def labeled_timing(
        self, family: str, labels: dict, help: str | None = None
    ) -> Timing:
        """One :class:`Timing` per (family, label-set) combination —
        the labeled-histogram family behind per-session op accounting.
        Exports as ONE Prometheus histogram family whose per-
        combination ``_bucket``/``_sum``/``_count`` samples carry the
        labels, with the slowest recent op's trace id as an OpenMetrics
        exemplar on the ``+Inf`` bucket (so a hot cell links straight
        to ``trace-dump``). Cardinality is bounded by
        ``LABEL_VARIANT_CAP`` — overflow combinations fold into the
        all-"other" bucket."""
        variants = self.labeled_timings.setdefault(family, {})
        key = self._label_key(variants, labels)
        t = variants.get(key)
        if t is None:
            decorated = family + "{" + ",".join(
                f'{k}="{v}"' for k, v in key
            ) + "}"
            t = variants[key] = Timing(decorated)
        self.describe(family, help)
        return t

    def define(self, name: str, expr: str, help: str | None = None) -> None:
        """Register a derived series: RPN over series names/constants,
        e.g. ``"bytes_read bytes_written ADD"``. Validated eagerly by a
        full evaluation (shape errors, unknown names, nesting depth)."""
        if name in self.series:
            raise ValueError(f"{name!r} is an existing series")
        self.eval_rpn(expr)  # raises ValueError on malformed exprs
        self.derived[name] = expr
        self.describe(name, help)

    def drop_labeled(self, family: str, label: str, value) -> None:
        """Retire every variant of ``family`` (counter or timing) whose
        label set carries ``label="value"``. Departed-session cleanup:
        a long-lived master with session churn would otherwise fill the
        LABEL_VARIANT_CAP with dead variants and fold every NEW
        session into "other" — losing exactly the p99/exemplar cells
        the `top` view exists for. Prometheus handles series
        disappearing (same as a process restart)."""
        pair = (str(label), _label_value(value))
        for table in (self.labeled, self.labeled_timings):
            variants = table.get(family)
            if not variants:
                continue
            for key in [k for k in variants if pair in k]:
                del variants[key]

    def history(self, name: str, resolution: str = "sec") -> list[float]:
        """One series' retained ring at a resolution (the metrics-
        history view `top`/`health` trends render; [] for unknown
        names). Counters yield per-tick rates, gauges sampled values —
        exactly what the rings hold."""
        s = self.series.get(name)
        if s is None:
            return []
        return [float(v) for v in s._rings.get(resolution, ())]

    def sample_all(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        for s in self.series.values():
            s.sample(now)
        for variants in self.labeled.values():
            for s in variants.values():
                s.sample(now)

    # --- derived-series evaluation (charts.h calc ops) -------------------

    def _parse_rpn(self, expr: str) -> list[str]:
        tokens = expr.split()
        if not tokens:
            raise ValueError("empty RPN expression")
        depth = 0
        for t in tokens:
            if t in RPN_OPS:
                if depth < 2:
                    raise ValueError(f"RPN stack underflow at {t!r}")
                depth -= 1
            else:
                if t not in self.series and t not in self.derived:
                    try:
                        float(t)
                    except ValueError:
                        raise ValueError(f"unknown series {t!r}") from None
                depth += 1
        if depth != 1:
            raise ValueError(f"RPN leaves {depth} values on the stack")
        return tokens

    def eval_rpn(self, expr: str, resolution: str = "sec",
                 _depth: int = 0) -> list[float]:
        """Evaluate an RPN expression elementwise at one resolution.

        Series are right-aligned (most recent sample last); a shorter
        operand is padded with leading zeros. DIV by zero yields 0,
        matching the reference's chart division semantics."""
        if _depth > 8:
            # catches definition cycles too (a cycle can only arise via
            # redefinition; to_dict degrades that series to an error)
            raise ValueError("derived series nested too deeply")
        # stack entries: (is_constant, points) — only true constants
        # broadcast; a series that happens to hold one sample right-
        # aligns and zero-pads like any other series
        stack: list[tuple[bool, list[float]]] = []
        for t in self._parse_rpn(expr):
            if t in RPN_OPS:
                (cb, b), (ca, a) = stack.pop(), stack.pop()
                n = max(len(a), len(b))
                a = a * n if ca and n > 1 else [0.0] * (n - len(a)) + a
                b = b * n if cb and n > 1 else [0.0] * (n - len(b)) + b
                if t == "ADD":
                    r = [x + y for x, y in zip(a, b)]
                elif t == "SUB":
                    r = [x - y for x, y in zip(a, b)]
                elif t == "MUL":
                    r = [x * y for x, y in zip(a, b)]
                elif t == "DIV":
                    r = [x / y if y else 0.0 for x, y in zip(a, b)]
                elif t == "MIN":
                    r = [min(x, y) for x, y in zip(a, b)]
                else:  # MAX
                    r = [max(x, y) for x, y in zip(a, b)]
                stack.append((ca and cb, r))
            elif t in self.series:
                stack.append(
                    (False,
                     [float(v) for v in self.series[t]._rings[resolution]])
                )
            elif t in self.derived:
                stack.append(
                    (False,
                     self.eval_rpn(self.derived[t], resolution, _depth + 1))
                )
            else:
                stack.append((True, [float(t)]))
        return stack[0][1]

    def to_prometheus(self, prefix: str = "lizardfs") -> str:
        """Prometheus text exposition (format 0.0.4) of the registry.

        Counters export as ``<prefix>_<name>_total``, gauges as
        ``<prefix>_<name>``, derived series as gauges of their most
        recent value, and :class:`Timing` histograms as native
        Prometheus histograms in microseconds: bucket i of the log2
        table covers [2^i, 2^(i+1)) us, so the cumulative ``le`` bound
        of bucket i is 2^(i+1). Served at the webui ``/metrics``
        endpoint and over the admin link (``metrics-prom``)."""
        lines: list[str] = []

        def emit(name: str, mtype: str, value, help_text: str = "",
                 suffix: str = "") -> None:
            lines.append(f"# HELP {name} {_prom_help(help_text or name)}")
            lines.append(f"# TYPE {name} {mtype}")
            lines.append(f"{name}{suffix} {_prom_value(value)}")

        for name, s in sorted(self.series.items()):
            pname = f"{prefix}_{_prom_name(name)}"
            if s.kind == "counter":
                emit(pname + "_total", "counter", s.total,
                     self.help_for(name, "counter"))
            else:
                emit(pname, "gauge", s.value, self.help_for(name, "gauge"))
        for family, variants in sorted(self.labeled.items()):
            pname = f"{prefix}_{_prom_name(family)}_total"
            lines.append(
                f"# HELP {pname} "
                f"{_prom_help(self.help_for(family, 'counter'))}"
            )
            lines.append(f"# TYPE {pname} counter")
            for key, s in sorted(variants.items()):
                suffix = "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"
                lines.append(f"{pname}{suffix} {_prom_value(s.total)}")
        for name, expr in sorted(self.derived.items()):
            pname = f"{prefix}_{_prom_name(name)}"
            try:
                points = self.eval_rpn(expr)
            except ValueError:
                continue  # a bad redefinition must not poison the page
            emit(pname, "gauge", points[-1] if points else 0.0,
                 self.help_for(name, "derived series"))
        for name, t in sorted(self.timings.items()):
            pname = f"{prefix}_timing_{_prom_name(name)}_us"
            lines.append(
                f"# HELP {pname} "
                f"{_prom_help(self.help_for(name, 'latency histogram'))}"
            )
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for i, n in enumerate(t.buckets):
                cum += n
                lines.append(f'{pname}_bucket{{le="{2 ** (i + 1)}"}} {cum}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {t.count}')
            lines.append(f"{pname}_sum {_prom_value(t.total_us)}")
            lines.append(f"{pname}_count {t.count}")
        for family, variants in sorted(self.labeled_timings.items()):
            pname = f"{prefix}_{_prom_name(family)}_us"
            lines.append(
                f"# HELP {pname} "
                f"{_prom_help(self.help_for(family, 'latency histogram'))}"
            )
            lines.append(f"# TYPE {pname} histogram")
            for key, t in sorted(variants.items()):
                lbl = ",".join(f'{k}="{v}"' for k, v in key)
                cum = 0
                for i, n in enumerate(t.buckets):
                    cum += n
                    lines.append(
                        f'{pname}_bucket{{{lbl},le="{2 ** (i + 1)}"}} {cum}'
                    )
                inf = f'{pname}_bucket{{{lbl},le="+Inf"}} {t.count}'
                if t.exemplar_trace_id:
                    # OpenMetrics exemplar: the slowest recent op's
                    # trace id + its latency, the hot-cell -> trace-dump
                    # link (0.0.4-only scrapers may drop the suffix;
                    # metrics-lint validates the syntax)
                    inf += (
                        f' # {{trace_id="0x{t.exemplar_trace_id:x}"}} '
                        f"{_prom_value(round(t.exemplar_us, 1))}"
                    )
                lines.append(inf)
                lines.append(f"{pname}_sum{{{lbl}}} {_prom_value(t.total_us)}")
                lines.append(f"{pname}_count{{{lbl}}} {t.count}")
        return "\n".join(lines) + "\n"

    def to_dict(self, resolution: str = "sec") -> dict:
        out = {
            name: s.to_dict(resolution)
            for name, s in sorted(self.series.items())
        }
        for variants in self.labeled.values():
            for s in variants.values():
                out[s.name] = s.to_dict(resolution)
        for name, expr in sorted(self.derived.items()):
            try:
                points = self.eval_rpn(expr, resolution)
                err = None
            except ValueError as e:
                # a bad redefinition must not poison the whole export
                points, err = [], str(e)
            out[name] = {
                "name": name, "kind": "derived", "expr": expr,
                "total": points[-1] if points else 0.0,
                "resolution": resolution, "points": points,
            }
            if err is not None:
                out[name]["error"] = err
        for name, t in sorted(self.timings.items()):
            out[f"timing.{name}"] = t.to_dict()
        for variants in self.labeled_timings.values():
            for t in variants.values():
                out[f"timing.{t.name}"] = t.to_dict()
        return out
