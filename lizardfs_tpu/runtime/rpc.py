"""Client-side RPC connection: pipelined request/response over one stream.

The analog of the reference's mastercomm packet pump (reference:
src/mount/mastercomm.cc): one persistent connection, concurrent in-flight
requests matched to responses by ``req_id``, push messages (e.g. the
changelog stream) dispatched to registered handlers.
"""

from __future__ import annotations

import asyncio
import itertools

from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto.codec import Message
from lizardfs_tpu.proto.status import StatusError
from lizardfs_tpu.runtime import faults as _faults
from lizardfs_tpu.runtime import retry as _retry


class RpcConnection:
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._req_ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._push_handlers: dict[type, object] = {}
        self._handler_tasks: set[asyncio.Task] = set()
        self._pump_task: asyncio.Task | None = None
        self._closed = asyncio.Event()

    # dial bound (unbounded-await audit): an RPC link to a blackholed
    # peer fails in seconds, not the OS SYN timeout; ambient RetryPolicy
    # deadlines (runtime/retry.py) shrink it further
    DIAL_TIMEOUT = 5.0

    @classmethod
    async def connect(cls, host: str, port: int) -> "RpcConnection":
        if _faults.ACTIVE:
            await _faults.dial_point("rpc", f"{host}:{port}")
        reader, writer = await _retry.bounded_wait(
            asyncio.open_connection(host, port), cls.DIAL_TIMEOUT
        )
        conn = cls(reader, writer)
        conn.start()
        return conn

    def start(self) -> None:
        # detached: the pump (and the push-handler tasks it spawns)
        # outlives any RetryPolicy attempt that dialed this connection —
        # it must not inherit that attempt's deadline budget
        self._pump_task = _retry.spawn_detached(self._pump())

    def on_push(self, msg_cls: type, handler) -> None:
        """Register an async handler for unsolicited messages of a type."""
        self._push_handlers[msg_cls] = handler

    async def _pump(self) -> None:
        try:
            while True:
                msg = await framing.read_message(self.reader)
                # push types FIRST: peer-initiated requests (e.g. master
                # commands) carry their own req_id space which would
                # otherwise collide with our call ids on a bidirectional
                # link. Push handlers run as tasks so a slow handler
                # (e.g. a replication) never stalls the pump.
                handler = self._push_handlers.get(type(msg))
                if handler is not None:
                    task = asyncio.get_running_loop().create_task(handler(msg))
                    self._handler_tasks.add(task)
                    task.add_done_callback(self._handler_tasks.discard)
                    continue
                req_id = getattr(msg, "req_id", None)
                fut = self._pending.pop(req_id, None) if req_id is not None else None
                if fut is not None and not fut.done():
                    fut.set_result(msg)
                # unsolicited + unhandled messages are dropped
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._closed.set()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("connection lost"))
            self._pending.clear()

    async def call(
        self, msg_cls, *, timeout: float = 30.0, **fields
    ) -> Message:
        """Send a request (auto req_id) and await its response."""
        if self._closed.is_set():
            # the pump is gone: nothing will ever resolve the future.
            # Failing fast here is what makes client failover prompt —
            # without it every call on a dead connection burns the full
            # timeout before the reconnect path runs.
            raise ConnectionError("connection lost")
        req_id = next(self._req_ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            await framing.send_message(self.writer, msg_cls(req_id=req_id, **fields))
            # the per-call timeout is additionally clamped by any
            # ambient RetryPolicy deadline: nested retries share one
            # end-to-end budget instead of multiplying their waits
            return await asyncio.wait_for(
                fut, max(_retry.budget(timeout), 0.001)
            )
        finally:
            self._pending.pop(req_id, None)

    async def call_ok(self, msg_cls, *, timeout: float = 30.0, **fields) -> Message:
        """``call`` that raises StatusError on non-OK status replies."""
        reply = await self.call(msg_cls, timeout=timeout, **fields)
        st = getattr(reply, "status", 0)
        if st != 0:
            # BUSY sheds carry the admission controller's backoff hint
            # (MatoclStatusReply.retry_after_ms); surface it on the
            # exception so the client's busy-retry loop can honor it
            raise StatusError(
                st, msg_cls.__name__,
                retry_after_ms=getattr(reply, "retry_after_ms", 0),
            )
        return reply

    async def send(self, msg: Message) -> None:
        """Fire-and-forget (reports, acks)."""
        await framing.send_message(self.writer, msg)

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    async def close(self) -> None:
        if self._pump_task is not None:
            self._pump_task.cancel()
        for task in list(self._handler_tasks):
            task.cancel()
        await _retry.close_writer(self.writer, swallow_cancel=True)
        self._closed.set()
