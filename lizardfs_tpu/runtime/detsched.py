"""Seeded deterministic asyncio scheduling — the interleaving explorer.

The cross-await-race lint rule finds *candidate* interleavings
statically; this module makes them *reproducible* dynamically. A
:class:`DetEventLoop` is a SelectorEventLoop whose ready-callback order
is permuted by a seeded RNG:

* every ``call_soon`` lands the new handle at a seeded position within
  the currently-pending ready callbacks instead of FIFO-appending, so
  two tasks racing toward the same awaited state run in a
  seed-determined order — a different seed explores a different
  interleaving of the same program;
* ``run_in_executor`` (and therefore ``asyncio.to_thread``) runs the
  function INLINE at a seeded later point on the loop thread instead of
  on a worker thread, so "thread completion order" is permuted by the
  same mechanism and — crucially — stops depending on OS scheduling.
  (Executor jobs that block on loop progress would deadlock under this;
  the tree's ``to_thread`` bodies are disk/CPU work, which is exactly
  the class worth permuting. ``detsched`` is a test harness, never a
  production mode.)
* every scheduling decision appends ``step:callback-label`` to a
  schedule log; :func:`schedule_digest` hashes it. Same seed => the
  log, and therefore the execution order of every callback, is
  byte-identical across runs — a failure replays exactly from its
  printed seed (``tools/racehunt.py`` prints the replay command).

Sources of nondeterminism the loop CANNOT tame: real sockets/
subprocesses (kernel timing decides readiness), timer callbacks racing
wall time, and ``call_soon_threadsafe`` from threads the loop does not
own. Pure-asyncio tests (locks, gather, queues, ``to_thread``) — the
race-explorer target class — are fully deterministic under it.

Usage::

    detsched.run(coro_fn(), seed=7)            # asyncio.run equivalent
    with detsched.policy(seed=7): ...          # install for a block
    LZ_DETSCHED=7 python -m pytest tests/ ...  # conftest routes async
                                               # tests through run()

``LZ_DETSCHED`` is the seed (an int); unset means the stock loop runs
(zero overhead, zero change — the kill-switch discipline).
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import os
import random
import re
import selectors

_ADDR_RE = re.compile(r" at 0x[0-9a-fA-F]+")


def detsched_seed() -> int | None:
    """The ONE accessor for LZ_DETSCHED (kill-switch inventory): the
    explorer seed, or None = stock scheduling."""
    raw = os.environ.get("LZ_DETSCHED", "").strip()
    if not raw:
        return None
    try:
        return int(raw, 0)
    except ValueError:
        raise ValueError(
            f"LZ_DETSCHED={raw!r}: expected an integer seed"
        ) from None


def _fn_label(fn) -> str:
    """Address-free name for an executor callable (``to_thread`` wraps
    the user function in ``partial(ctx.run, func)`` — dig it out)."""
    if hasattr(fn, "func"):  # functools.partial
        for a in getattr(fn, "args", ()):
            if callable(a):
                return _fn_label(a)
        return _fn_label(fn.func)
    return getattr(fn, "__qualname__", type(fn).__name__)


def _label(handle) -> str:
    cb = getattr(handle, "_callback", None)
    # a Task step callback names the coroutine — the label a human
    # reads in the schedule log to see WHICH task won the race
    task = getattr(cb, "__self__", None)
    coro = getattr(task, "get_coro", None)
    if coro is not None:
        try:
            return getattr(coro(), "__qualname__", repr(coro()))
        except Exception:
            pass
    return getattr(cb, "__qualname__", repr(cb))


class DetEventLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop with seeded ready-queue permutation, inline
    deterministic executors, and a schedule log."""

    def __init__(self, seed: int):
        super().__init__(selectors.DefaultSelector())
        self.det_seed = seed
        self._det_rng = random.Random(0xD5C0DE ^ (seed * 0x9E3779B1))
        self._det_steps = 0
        self._det_log = hashlib.sha1(str(seed).encode())
        self._det_tail: list[str] = []  # bounded human-readable tail

    # -- schedule accounting -------------------------------------------------
    def _det_note(self, event: str) -> None:
        self._det_steps += 1
        # labels must never carry object addresses: the digest is the
        # byte-identical replay contract across PROCESSES
        entry = f"{self._det_steps}:{_ADDR_RE.sub('', event)}"
        self._det_log.update(entry.encode())
        self._det_tail.append(entry)
        if len(self._det_tail) > 64:
            del self._det_tail[:32]

    def schedule_digest(self) -> str:
        """Digest over every scheduling decision so far: byte-identical
        for the same seed + same program, the replay contract racehunt
        pins."""
        return self._det_log.hexdigest()

    def schedule_tail(self) -> list[str]:
        return list(self._det_tail)

    # -- seeded permutation --------------------------------------------------
    def _det_place(self, handle) -> None:
        """Move the just-appended handle to a seeded position among the
        pending ready callbacks (permuting arrival order is exactly
        permuting the execution order asyncio would otherwise FIFO)."""
        ready = self._ready
        pos = self._det_rng.randrange(len(ready)) if len(ready) > 1 else 0
        if pos != len(ready) - 1:
            ready.insert(pos, ready.pop())
        self._det_note(f"{_label(handle)}@{pos}")

    def call_soon(self, callback, *args, context=None):
        handle = super().call_soon(callback, *args, context=context)
        self._det_place(handle)
        return handle

    # NOT overridden: call_soon_threadsafe. A foreign thread's arrival
    # time is outside the loop's control; permuting it would only add
    # noise to the digest. detsched determinism holds for the loop's
    # own scheduling (which includes every executor completion, below).

    def run_in_executor(self, executor, func, *args):
        """Deterministic executor: run ``func`` inline at a seeded later
        point on the loop thread. Completion order of concurrent
        ``to_thread`` jobs becomes a seeded permutation instead of an
        OS scheduling accident."""
        fut = self.create_future()

        def _runner():
            if fut.cancelled():
                return
            try:
                fut.set_result(func(*args))
            except BaseException as e:  # mirrors executor behavior
                fut.set_exception(e)

        _runner.__qualname__ = f"to_thread:{_fn_label(func)}"
        self.call_soon(_runner)
        return fut


class DetEventLoopPolicy(asyncio.DefaultEventLoopPolicy):
    def __init__(self, seed: int):
        super().__init__()
        self._seed = seed

    def new_event_loop(self):
        return DetEventLoop(self._seed)


@contextlib.contextmanager
def policy(seed: int):
    """Install the deterministic policy for a block (asyncio.run inside
    the block builds DetEventLoops)."""
    old = asyncio.get_event_loop_policy()
    asyncio.set_event_loop_policy(DetEventLoopPolicy(seed))
    try:
        yield
    finally:
        asyncio.set_event_loop_policy(old)


def run(coro, seed: int, return_digest: bool = False):
    """``asyncio.run`` under a seeded deterministic loop. With
    ``return_digest`` the result is ``(result, schedule_digest)`` so
    tests can pin byte-identical schedules."""
    loop = DetEventLoop(seed)
    try:
        asyncio.set_event_loop(loop)
        result = loop.run_until_complete(coro)
        digest = loop.schedule_digest()
    finally:
        try:
            _cancel_all(loop)
        finally:
            asyncio.set_event_loop(None)
            loop.close()
    return (result, digest) if return_digest else result


def _cancel_all(loop) -> None:
    pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
    for t in pending:
        t.cancel()
    if pending:
        loop.run_until_complete(
            asyncio.gather(*pending, return_exceptions=True)
        )
    loop.run_until_complete(loop.shutdown_asyncgens())
