"""``KEY = VALUE`` config files with typed getters and reload.

Functional mirror of the reference's cfg system (reference:
src/common/cfg.h:28-113): plain text config, typed accessors with
defaults and range validation, reloadable in place (SIGHUP handling
lives in the daemon harness).
"""

from __future__ import annotations

import os


class ConfigError(ValueError):
    pass


class Config:
    def __init__(self, path: str | None = None, defaults: dict | None = None):
        self.path = path
        self._values: dict[str, str] = {}
        self._defaults = {k: str(v) for k, v in (defaults or {}).items()}
        if path is not None:
            self.reload()

    @classmethod
    def from_dict(cls, values: dict) -> "Config":
        cfg = cls()
        cfg._values = {k: str(v) for k, v in values.items()}
        return cfg

    def reload(self) -> None:
        if self.path is None:
            return
        values: dict[str, str] = {}
        if os.path.exists(self.path):
            with open(self.path) as f:
                for lineno, raw in enumerate(f, 1):
                    line = raw.split("#", 1)[0].strip()
                    if not line:
                        continue
                    if "=" not in line:
                        raise ConfigError(f"{self.path}:{lineno}: missing '='")
                    key, value = line.split("=", 1)
                    values[key.strip()] = value.strip()
        self._values = values

    def _raw(self, key: str, default=None):
        if key in self._values:
            return self._values[key]
        if key in self._defaults:
            return self._defaults[key]
        return default

    def get_str(self, key: str, default: str | None = None) -> str:
        v = self._raw(key, default)
        if v is None:
            raise ConfigError(f"missing config key {key}")
        return v

    def get_int(
        self,
        key: str,
        default: int | None = None,
        min_value: int | None = None,
        max_value: int | None = None,
    ) -> int:
        v = self._raw(key, None)
        if v is None:
            if default is None:
                raise ConfigError(f"missing config key {key}")
            value = default
        else:
            try:
                value = int(str(v), 0)
            except ValueError:
                raise ConfigError(f"config key {key}={v!r} is not an int") from None
        if min_value is not None and value < min_value:
            raise ConfigError(f"{key}={value} below minimum {min_value}")
        if max_value is not None and value > max_value:
            raise ConfigError(f"{key}={value} above maximum {max_value}")
        return value

    def get_float(
        self,
        key: str,
        default: float | None = None,
        min_value: float | None = None,
    ) -> float:
        v = self._raw(key, None)
        if v is None:
            if default is None:
                raise ConfigError(f"missing config key {key}")
            value = default
        else:
            try:
                value = float(str(v))
            except ValueError:
                raise ConfigError(
                    f"config key {key}={v!r} is not a number"
                ) from None
        # ranged validation like get_int: a zero/negative timer interval
        # busy-loops the daemon instead of failing fast
        if min_value is not None and value < min_value:
            raise ConfigError(f"config key {key}={value} below {min_value}")
        return value

    def get_bool(self, key: str, default: bool | None = None) -> bool:
        v = self._raw(key, None)
        if v is None:
            if default is None:
                raise ConfigError(f"missing config key {key}")
            return default
        s = str(v).strip().lower()
        if s in ("1", "true", "yes", "on"):
            return True
        if s in ("0", "false", "no", "off"):
            return False
        raise ConfigError(f"config key {key}={v!r} is not a bool")

    def as_dict(self) -> dict[str, str]:
        out = dict(self._defaults)
        out.update(self._values)
        return out
