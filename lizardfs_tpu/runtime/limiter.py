"""Rate and flow-control primitives for the data plane.

:class:`TokenBucket` — time-refilled rate limiting for replication /
IO bandwidth (reference: src/common/token_bucket.h client QoS
smoothing, src/chunkserver/replication_bandwidth_limiter.cc
replication cap). Async: ``acquire`` sleeps until enough tokens
accumulate; a rate of 0 means unlimited.

:class:`CreditBucket` — explicitly-returned credits bounding in-flight
work (the write window's per-chunkserver frame credits and shared
staging-byte budget): credits come back on acknowledgment, not with
time.
"""

from __future__ import annotations

import asyncio
import time


class TokenBucket:
    def __init__(self, rate: float, burst: float | None = None,
                 now_fn=time.monotonic):
        """rate: tokens (bytes) per second; burst: bucket size.
        ``now_fn`` injects a clock for deterministic tests (the QoS
        fair-share suite drives refills on virtual time)."""
        self.rate = rate
        self.burst = burst if burst is not None else max(rate, 1.0)
        self._now = now_fn
        self._tokens = self.burst
        self._last = now_fn()

    def _refill(self) -> None:
        now = self._now()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    def try_acquire(self, n: float) -> bool:
        if self.rate <= 0:
            return True
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    async def acquire(self, n: float) -> None:
        """Debt model: requests larger than the burst still pace at
        ``rate`` instead of deadlocking — tokens go negative and the
        caller sleeps the debt off."""
        if self.rate <= 0:
            return
        self._refill()
        self._tokens -= n
        if self._tokens < 0:
            await asyncio.sleep(-self._tokens / self.rate)


class CreditBucket:
    """Counting credits with explicit put-back — the flow-control twin
    of :class:`TokenBucket` (which refills by TIME and models a rate).
    Credits model in-flight WORK: ``acquire`` takes credits out,
    ``release`` puts them back when the work is acknowledged, so the
    bucket bounds how much is outstanding rather than how fast it
    flows. Used by the client's adaptive write window: one bucket per
    chunkserver caps unacknowledged bulk frames per connection, one
    shared bucket caps total staged bytes across every in-flight
    chunk write.

    A request larger than ``capacity`` is clamped (mirroring the token
    bucket's debt model: a jumbo segment must pace, not deadlock).
    Waiters are FIFO. ``capacity <= 0`` disables accounting entirely.
    """

    def __init__(self, capacity: float):
        self.capacity = capacity
        self._credits = capacity
        from collections import deque

        self._waiters: deque = deque()

    @property
    def available(self) -> float:
        return self._credits

    def try_acquire(self, n: float = 1.0) -> bool:
        if self.capacity <= 0:
            return True
        n = min(n, self.capacity)
        if not self._waiters and self._credits >= n:
            self._credits -= n
            return True
        return False

    async def acquire(self, n: float = 1.0) -> bool:
        """Take ``n`` credits, waiting FIFO until available. Returns
        True iff the caller had to wait (backpressure observability:
        the window exports a credit-wait counter)."""
        if self.try_acquire(n):
            return False
        n = min(n, self.capacity)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._waiters.append((fut, n))
        try:
            await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # granted and cancelled in the same tick: put it back
                self.release(n)
            else:
                try:
                    self._waiters.remove((fut, n))
                except ValueError:
                    pass
            raise
        return True

    def release(self, n: float = 1.0) -> None:
        if self.capacity <= 0:
            return
        self._credits = min(self._credits + min(n, self.capacity),
                            self.capacity)
        while self._waiters:
            fut, need = self._waiters[0]
            if fut.cancelled():
                self._waiters.popleft()
                continue
            if self._credits < need:
                break
            self._waiters.popleft()
            self._credits -= need
            fut.set_result(True)
