"""Token-bucket rate limiting for replication / IO bandwidth.

Reference: src/common/token_bucket.h (client QoS smoothing) and
src/chunkserver/replication_bandwidth_limiter.cc (replication cap).
Async: ``acquire`` sleeps until enough tokens accumulate; a rate of 0
means unlimited.
"""

from __future__ import annotations

import asyncio
import time


class TokenBucket:
    def __init__(self, rate: float, burst: float | None = None):
        """rate: tokens (bytes) per second; burst: bucket size."""
        self.rate = rate
        self.burst = burst if burst is not None else max(rate, 1.0)
        self._tokens = self.burst
        self._last = time.monotonic()

    def _refill(self) -> None:
        now = time.monotonic()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    def try_acquire(self, n: float) -> bool:
        if self.rate <= 0:
            return True
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    async def acquire(self, n: float) -> None:
        """Debt model: requests larger than the burst still pace at
        ``rate`` instead of deadlocking — tokens go negative and the
        caller sleeps the debt off."""
        if self.rate <= 0:
            return
        self._refill()
        self._tokens -= n
        if self._tokens < 0:
            await asyncio.sleep(-self._tokens / self.rate)
