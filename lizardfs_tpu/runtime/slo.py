"""Per-op-class latency SLOs, burn-rate accounting, and the flight
recorder that turns raw telemetry (PR 2) into answers.

Three pieces, one module:

* :class:`Objective` / :class:`SloEngine` — per-op-class latency
  objectives (read/write/locate/replicate/nfs) with MULTI-WINDOW
  burn-rate accounting (the SRE fast/slow window pattern: a fast
  window catches an acute regression in seconds, the slow window
  separates it from a blip). Burn rate = observed breach fraction
  over the window divided by the error budget (1 - target); burn 1.0
  means the objective is being spent exactly at the rate that
  exhausts its budget, >1 means degrading. Objectives register
  gauges/counters into the daemon's existing ``Metrics`` registry, so
  burn rates and breach counts ride the PR-2 Prometheus exporter and
  charts with zero extra plumbing.

* :class:`FlightRecorder` — when an op breaches its objective, its
  merged trace timeline (``tracing.merge_timeline`` over the daemon's
  span ring) is captured automatically: into an in-memory top-N
  slowest-ops ring (``lizardfs-admin slowops``) and, when the daemon
  has a disk home, into a bounded on-disk incident ring
  (``incidents/inc_<trace_id>.json``, oldest rotated out). A slow op
  no longer has to be caught live with ``trace-dump`` — the id in
  ``slowops`` renders after the fact because ``trace-dump`` falls
  back to the incident store when the span ring has moved on.

* :func:`health_from` — folds an engine snapshot plus daemon-level
  signals (stall-watchdog hits, span-ring drops, disk errors) into
  the per-daemon health snapshot that chunkservers ship in
  heartbeats and the master aggregates into the cluster ``health``
  rollup.

Cost contract: ``LZ_SLO=0`` (or ``set_enabled(False)``) short-circuits
``observe()`` to a single attribute check — no ring math, no breach
tests, no capture — and the engine registers nothing while disabled at
construction. The bench's ec(8,4) row is the regression fiducial.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

from lizardfs_tpu.constants import env_flag
from lizardfs_tpu.runtime import tracing

_ENABLED = env_flag("LZ_SLO")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Test/ops hook mirroring the LZ_SLO env gate."""
    global _ENABLED
    _ENABLED = bool(on)


OP_CLASSES = ("read", "write", "locate", "replicate", "nfs", "s3")

# objective defaults: threshold_ms is the per-op latency bound, target
# the fraction of ops that must meet it. Deliberately loose for
# localhost dev boxes; production tunes per class via the constructor,
# tweaks (slo_<class>_threshold_ms), or LZ_SLO_<CLASS>_MS.
DEFAULT_OBJECTIVES = {
    "read": (1000.0, 0.999),
    "write": (2000.0, 0.999),
    "locate": (500.0, 0.999),
    "replicate": (30000.0, 0.99),
    "nfs": (1000.0, 0.999),
    # object ops span one HTTP request end-to-end (a multi-MB PUT or a
    # recall-triggering GET is one op), so the bound is looser than nfs
    "s3": (2000.0, 0.999),
}

# burn-rate windows (seconds): fast catches acute pain, slow provides
# the corroborating context (multiwindow alerting pattern)
FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 600.0
_BUCKET_S = 5.0

# health status thresholds on the FAST burn rate
BURN_DEGRADED = 1.0
BURN_CRITICAL = 6.0

STATUS_ORDER = ("ok", "degraded", "critical")


def worst_status(*statuses: str) -> str:
    idx = 0
    for s in statuses:
        try:
            idx = max(idx, STATUS_ORDER.index(s))
        except ValueError:
            idx = len(STATUS_ORDER) - 1  # unknown reads as critical
    return STATUS_ORDER[idx]


class _Window:
    """Bucketed (total, breached) counts over a sliding window.

    Running tallies are maintained on add/expire so :meth:`rates` is
    O(1) amortized — it runs on every hot-path op via
    :meth:`SloEngine.observe`, where an O(#buckets) sum would be
    steady-state waste."""

    __slots__ = ("span_s", "_buckets", "_total", "_breached")

    def __init__(self, span_s: float):
        self.span_s = span_s
        # (bucket_epoch, total, breached), oldest first
        self._buckets: deque = deque()
        self._total = 0
        self._breached = 0

    def add(self, now: float, breached: bool) -> None:
        epoch = int(now // _BUCKET_S)
        hit = 1 if breached else 0
        if self._buckets and self._buckets[-1][0] == epoch:
            e, t, b = self._buckets[-1]
            self._buckets[-1] = (e, t + 1, b + hit)
        else:
            self._buckets.append((epoch, 1, hit))
        self._total += 1
        self._breached += hit
        self._expire(epoch)

    def _expire(self, epoch: int) -> None:
        lo = epoch - int(self.span_s // _BUCKET_S)
        while self._buckets and self._buckets[0][0] < lo:
            _, t, b = self._buckets.popleft()
            self._total -= t
            self._breached -= b

    def rates(self, now: float) -> tuple[int, int]:
        self._expire(int(now // _BUCKET_S))
        return self._total, self._breached


class Objective:
    """One op class's latency objective + its burn windows."""

    __slots__ = (
        "op_class", "threshold_s", "target", "ops", "breaches",
        "_fast", "_slow",
    )

    def __init__(self, op_class: str, threshold_ms: float, target: float):
        self.op_class = op_class
        self.threshold_s = threshold_ms / 1e3
        self.target = target
        self.ops = 0
        self.breaches = 0
        self._fast = _Window(FAST_WINDOW_S)
        self._slow = _Window(SLOW_WINDOW_S)

    @property
    def budget(self) -> float:
        return max(1.0 - self.target, 1e-6)

    def observe(self, seconds: float, now: float) -> bool:
        breached = seconds > self.threshold_s
        self.ops += 1
        if breached:
            self.breaches += 1
        self._fast.add(now, breached)
        self._slow.add(now, breached)
        return breached

    def burn(self, now: float) -> tuple[float, float]:
        """(fast, slow) burn rates: breach fraction over each window
        divided by the error budget. 0 when the window saw no ops."""
        out = []
        for w in (self._fast, self._slow):
            total, breached = w.rates(now)
            out.append((breached / total / self.budget) if total else 0.0)
        return out[0], out[1]

    def status(self, now: float) -> str:
        fast, slow = self.burn(now)
        # the SLOW window must corroborate before we page CRITICAL —
        # a single breach in an idle minute is a degraded signal, not
        # a cluster emergency
        if fast >= BURN_CRITICAL and slow > 0:
            return "critical"
        if fast >= BURN_DEGRADED:
            return "degraded"
        return "ok"


class FlightRecorder:
    """Top-N slowest-ops ring + bounded on-disk incident ring."""

    def __init__(self, incident_dir: str | None = None,
                 top_n: int = 16, max_incidents: int = 32):
        self.incident_dir = incident_dir
        self.top_n = top_n
        self.max_incidents = max_incidents
        # optional () -> str of collapsed stacks (runtime/profiler.py):
        # incident files then carry WHERE the process was spending its
        # time while the breach happened, not just the trace spans
        self.profile_source = None
        # slowest ops seen, sorted slowest-first, bounded to top_n
        self._slow: list[dict] = []
        # disk-write rate limit: capture runs synchronously on the
        # serving loop, and a breach STORM is precisely when the disk
        # is slow — one incident per interval keeps the recorder from
        # amplifying the outage it exists to diagnose (the in-memory
        # slowops ring still records every breach)
        self.min_write_interval_s = 1.0
        self._last_write = 0.0

    def set_dir(self, path: str | None) -> None:
        self.incident_dir = path

    def record(self, op_class: str, name: str, seconds: float,
               trace_id: int, spans: list[dict]) -> dict:
        entry = {
            "trace_id": trace_id,
            "op_class": op_class,
            "name": name,
            "ms": round(seconds * 1e3, 3),
            "ts": time.time(),
            "captured": bool(spans),
        }
        if spans:
            # auto-attribution: every captured breach names where its
            # milliseconds went (queue/disk/net/compute/unattributed) —
            # slowops rows and incident files carry it without anyone
            # having to re-run trace-dump --attribute by hand
            try:
                entry["attribution"] = tracing.attribute_timeline(
                    tracing.merge_timeline(spans, trace_id, wall_name=name)
                )
            except Exception:  # noqa: BLE001 — capture is best effort
                pass
        self._slow.append(entry)
        self._slow.sort(key=lambda e: -e["ms"])
        del self._slow[self.top_n:]
        if spans and self.incident_dir and trace_id:
            now = time.monotonic()
            if now - self._last_write < self.min_write_interval_s:
                entry["captured"] = False  # rate-limited, ring has it
            else:
                self._last_write = now
                try:
                    self._write_incident(entry, spans)
                except OSError:
                    entry["captured"] = False  # disk trouble must not bite
        return entry

    def _write_incident(self, entry: dict, spans: list[dict]) -> None:
        os.makedirs(self.incident_dir, exist_ok=True)
        path = os.path.join(
            self.incident_dir, f"inc_{entry['trace_id']:016x}.json"
        )
        doc = {**entry, "spans": spans}
        if self.profile_source is not None:
            # bounded: the heaviest stacks only — an incident file is a
            # ring slot, not an archive
            try:
                doc["profile"] = self.profile_source(32)
            except Exception:  # noqa: BLE001 — capture is best effort
                pass
        with open(path, "w") as f:
            json.dump(doc, f)
        self._rotate()

    def _rotate(self) -> None:
        files = sorted(
            (
                os.path.join(self.incident_dir, n)
                for n in os.listdir(self.incident_dir)
                if n.startswith("inc_") and n.endswith(".json")
            ),
            key=os.path.getmtime,
        )
        for path in files[: max(len(files) - self.max_incidents, 0)]:
            try:
                os.unlink(path)
            except OSError:
                pass

    def slowops(self) -> list[dict]:
        return list(self._slow)

    def incident_spans(self, trace_id: int) -> list[dict] | None:
        """Spans of a captured incident, or None — the `trace-dump`
        fallback that lets any slowops id render after the live span
        ring has moved on."""
        if not self.incident_dir or not trace_id:
            return None
        path = os.path.join(self.incident_dir, f"inc_{trace_id:016x}.json")
        try:
            with open(path) as f:
                return json.load(f).get("spans") or None
        except (OSError, ValueError):
            return None


class SloEngine:
    """Per-daemon SLO accounting wired into a ``Metrics`` registry.

    ``span_source(trace_id) -> list[dict]`` supplies the spans captured
    on breach (a daemon passes its ``trace_spans``); None disables
    capture (objectives and burn gauges still work).
    """

    def __init__(self, metrics=None, role: str = "",
                 objectives: dict[str, tuple[float, float]] | None = None,
                 span_source=None, incident_dir: str | None = None):
        self.metrics = metrics
        self.role = role
        self.span_source = span_source
        self.recorder = FlightRecorder(incident_dir)
        # optional SamplingProfiler (runtime/profiler.py): a breach
        # arms its incident boost window so slowops captures come with
        # stacks, and incident files embed the collapsed profile
        self.profiler = None
        # optional second auto-arm action (the heat loop's SLO→QoS
        # chain): ``qos_arm(op_class, trace_id)`` is called on every
        # breach — the master wires a rate-limited hook that arms QoS
        # pressure on the top-offender tenant (master/server.py
        # _slo_qos_arm). None (the default, and the LZ_HEAT-off state)
        # keeps breach handling exactly as before.
        self.qos_arm = None
        # per-op-class attribution rollup: breached ops' bucketed
        # milliseconds (tracing.attribute_timeline) accumulated across
        # captures, so an SLO breach names WHERE the time went, not
        # just that a threshold was crossed
        self.attribution_ms: dict[str, dict[str, float]] = {}
        self.objectives: dict[str, Objective] = {}
        for op_class, (thresh_ms, target) in {
            **DEFAULT_OBJECTIVES, **(objectives or {})
        }.items():
            env = os.environ.get(f"LZ_SLO_{op_class.upper()}_MS")
            if env:
                try:
                    thresh_ms = float(env)
                except ValueError:
                    pass
            self.objectives[op_class] = Objective(op_class, thresh_ms, target)
        # registration honors the kill switch: a disabled engine must
        # not export 15 dead-but-live-looking slo_* series per daemon
        # (a runtime set_enabled(True) still works — observe() creates
        # the series lazily, with auto help text)
        if metrics is not None and _ENABLED:
            for op_class, obj in self.objectives.items():
                metrics.counter(
                    f"slo_{op_class}_breaches",
                    help=f"{op_class} ops that exceeded their latency "
                         f"objective ({obj.threshold_s * 1e3:.0f} ms)",
                )
                metrics.gauge(
                    f"slo_{op_class}_burn_fast",
                    help=f"{op_class} SLO burn rate over the "
                         f"{FAST_WINDOW_S:.0f}s window (1.0 = spending "
                         "the error budget exactly at the sustainable "
                         "rate)",
                )
                metrics.gauge(
                    f"slo_{op_class}_burn_slow",
                    help=f"{op_class} SLO burn rate over the "
                         f"{SLOW_WINDOW_S:.0f}s window",
                )

    def set_threshold(self, op_class: str, threshold_ms: float) -> None:
        obj = self.objectives.get(op_class)
        if obj is not None:
            obj.threshold_s = float(threshold_ms) / 1e3

    def refresh_gauges(self) -> None:
        """Recompute the burn gauges from the current windows — called
        from the daemon's 1 Hz sampler so burn DECAYS on the metrics
        page when traffic stops (observe() only refreshes the class it
        just touched; without this, an idle daemon would export its
        last, possibly alarming, burn value forever)."""
        if not _ENABLED or self.metrics is None:
            return
        now = time.monotonic()
        for op_class, obj in self.objectives.items():
            fast, slow = obj.burn(now)
            self.metrics.gauge(f"slo_{op_class}_burn_fast").set(fast)
            self.metrics.gauge(f"slo_{op_class}_burn_slow").set(slow)

    def observe(self, op_class: str, seconds: float,
                trace_id: int = 0, name: str = "") -> bool:
        """Account one finished op; returns True when it breached its
        objective (and was flight-recorded). The LZ_SLO=0 path is this
        first check and nothing else."""
        if not _ENABLED:
            return False
        obj = self.objectives.get(op_class)
        if obj is None:
            return False
        now = time.monotonic()
        breached = obj.observe(seconds, now)
        if self.metrics is not None:
            fast, slow = obj.burn(now)
            self.metrics.gauge(f"slo_{op_class}_burn_fast").set(fast)
            self.metrics.gauge(f"slo_{op_class}_burn_slow").set(slow)
            if breached:
                self.metrics.counter(f"slo_{op_class}_breaches").inc()
        if breached:
            if self.profiler is not None:
                # incident auto-arm: the profiler holds its boosted
                # sample rate for the capture window so the incident's
                # collapsed stacks have useful resolution
                self.profiler.arm_incident()
            if self.qos_arm is not None:
                try:
                    self.qos_arm(op_class, trace_id)
                except Exception:  # noqa: BLE001 — auto-arm is best effort
                    pass
            spans: list[dict] = []
            if self.span_source is not None and trace_id:
                try:
                    spans = self.span_source(trace_id)
                except Exception:  # noqa: BLE001 — capture is best effort
                    spans = []
            entry = self.recorder.record(
                op_class, name or op_class, seconds, trace_id, spans
            )
            attr = entry.get("attribution")
            if attr:
                roll = self.attribution_ms.setdefault(
                    op_class,
                    {b: 0.0 for b in tracing.ATTRIBUTION_BUCKETS},
                )
                for b, v in attr.get("buckets_ms", {}).items():
                    roll[b] = roll.get(b, 0.0) + v
        return breached

    def snapshot(self) -> dict:
        """Per-class burn/breach state for health rollups (JSON-ready)."""
        now = time.monotonic()
        out = {}
        for op_class, obj in self.objectives.items():
            fast, slow = obj.burn(now)
            out[op_class] = {
                "threshold_ms": round(obj.threshold_s * 1e3, 1),
                "target": obj.target,
                "ops": obj.ops,
                "breaches": obj.breaches,
                "burn_fast": round(fast, 3),
                "burn_slow": round(slow, 3),
                "status": obj.status(now),
            }
            roll = self.attribution_ms.get(op_class)
            if roll:
                out[op_class]["attribution_ms"] = {
                    b: round(v, 3) for b, v in roll.items()
                }
                out[op_class]["attribution_dominant"] = max(
                    roll, key=lambda b: roll[b]
                )
        return out

    def status(self) -> str:
        now = time.monotonic()
        return worst_status(
            *(obj.status(now) for obj in self.objectives.values())
        )


def health_from(role: str, slo: SloEngine, *,
                loop_stalls: float = 0.0, span_ring_dropped: int = 0,
                disk_errors: int = 0, extra: dict | None = None) -> dict:
    """One daemon's health snapshot: SLO burn + the daemon-level
    degradation signals. Chunkservers fold this into heartbeats; the
    master aggregates the fleet into the `health` rollup."""
    slo_snap = slo.snapshot() if _ENABLED else {}
    status = slo.status() if _ENABLED else "ok"
    if disk_errors:
        status = worst_status(status, "degraded")
    snap = {
        "role": role,
        "status": status,
        "slo": slo_snap,
        "breaches_total": sum(s["breaches"] for s in slo_snap.values()),
        "slow_ops": len(slo.recorder.slowops()),
        "loop_stalls": int(loop_stalls),
        "span_ring_dropped": int(span_ring_dropped),
        "disk_errors": int(disk_errors),
    }
    if extra:
        snap.update(extra)
    return snap
