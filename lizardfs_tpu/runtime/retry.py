"""Unified retry/backoff/deadline policy for every dial and RPC loop.

Before this module each role hand-rolled its own loop: the client's
``_retry_transient`` and failover reconnect, the chunkserver's master
and mirror dials, the master's shadow-follow link, the NFS gateway's
startup connect. Each had its own backoff shape and — worse — its own
idea of "how long is too long", so stacked layers could multiply their
budgets (a client retrying an op that retries a dial that retries a
connect could spend attempts * attempts * timeout wall-clock).

:class:`RetryPolicy` centralizes the shape (jittered exponential
backoff, attempt cap) and :class:`Deadline` threads ONE end-to-end
budget through nested calls via a contextvar: an inner ``run()`` (or
:func:`bounded_wait`) inherits the tightest enclosing deadline, so
retries deeper in the stack can only ever spend what the outermost
caller budgeted. The reference's analogs: the mount's fs_reconnect loop
and its nrtomaxtimeout connect budget (src/mount/mastercomm.cc).
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import random
import time

_DEADLINE: contextvars.ContextVar["Deadline | None"] = contextvars.ContextVar(
    "lz_retry_deadline", default=None
)

_log = logging.getLogger("retry")


class RetryError(Exception):
    """Transient failures exhausted the policy (attempts or deadline).
    ``last`` holds the final underlying exception, if any."""

    def __init__(self, what: str, last: Exception | None):
        self.what = what
        self.last = last
        super().__init__(
            f"{what} failed after retries"
            + (f": {last}" if last is not None else " (deadline)")
        )


class Deadline:
    """A monotonic point in time the whole (nested) operation must not
    outlive."""

    __slots__ = ("at",)

    def __init__(self, seconds: float):
        self.at = time.monotonic() + seconds

    def remaining(self) -> float:
        return self.at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0


def current_deadline() -> Deadline | None:
    return _DEADLINE.get()


def budget(cap: float | None = None) -> float | None:
    """Seconds left in the ambient deadline, clamped by ``cap``.
    None = unbounded (no deadline and no cap)."""
    d = _DEADLINE.get()
    if d is None:
        return cap
    rem = max(d.remaining(), 0.0)
    return rem if cap is None else min(rem, cap)


def spawn_detached(coro) -> asyncio.Task:
    """Create a task with NO inherited deadline. Long-lived tasks born
    inside a policy-scoped attempt (an RPC connection's pump, a probe
    loop) must not carry the attempt's budget for the rest of their
    lives — a task context copies the deadline at creation and an
    expired one would turn every later bounded wait into an instant
    timeout."""
    token = _DEADLINE.set(None)
    try:
        return asyncio.get_running_loop().create_task(coro)
    finally:
        _DEADLINE.reset(token)


async def bounded_wait(awaitable, cap: float | None = None):
    """``await`` bounded by min(cap, ambient deadline budget). The
    workhorse of the unbounded-await audit: every dial and lone
    ``conn.call`` in the tree goes through here (or a policy) so a
    blackholed peer can cost at most the budget, never an OS timeout."""
    t = budget(cap)
    if t is None:
        return await awaitable
    return await asyncio.wait_for(awaitable, max(t, 0.001))


async def close_writer(writer, cap: float = 5.0, *,
                       swallow_cancel: bool = False) -> None:
    """THE teardown idiom: ``close()`` + bounded ``wait_closed()``,
    swallowing transport errors and the timeout. ``wait_closed`` on a
    peer that never drains FIN-ACKs can park forever; teardown paths
    must not inherit that hang (unbounded-await audit). Cancellation
    propagates by default; sites whose callers historically absorbed
    cancellation mid-close pass ``swallow_cancel=True`` — one helper,
    one cap, one exception policy, instead of seven drifting inline
    copies."""
    try:
        writer.close()
        t = budget(cap)
        await asyncio.wait_for(
            writer.wait_closed(), max(t if t is not None else cap, 0.001)
        )
    except (ConnectionError, OSError, asyncio.TimeoutError):
        pass
    except asyncio.CancelledError:
        if not swallow_cancel:
            raise


class RetryPolicy:
    """Jittered exponential backoff with an attempt cap and an optional
    end-to-end deadline.

    ``transient``: predicate deciding whether an exception is worth a
    retry (default: connection/OS/timeout errors). Non-transient errors
    surface immediately. When attempts or the deadline run out,
    :class:`RetryError` carries the last transient failure.

    ``run()`` PUBLISHES its (possibly inherited, always tightest)
    deadline to the ambient context, so nested policies and
    :func:`bounded_wait` calls inside the attempt share the same budget
    instead of amplifying it.
    """

    def __init__(
        self,
        attempts: int = 5,
        base_delay: float = 0.1,
        max_delay: float = 2.0,
        multiplier: float = 2.0,
        jitter: float = 0.1,
        deadline: float | None = None,
        attempt_timeout: float | None = None,
        transient=None,
    ):
        self.attempts = max(attempts, 1)
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.deadline = deadline
        self.attempt_timeout = attempt_timeout
        self.transient = transient or self._default_transient

    @staticmethod
    def _default_transient(e: Exception) -> bool:
        return isinstance(e, (ConnectionError, OSError, asyncio.TimeoutError))

    def _backoff(self, attempt: int) -> float:
        delay = min(
            self.base_delay * self.multiplier ** (attempt - 1), self.max_delay
        )
        if self.jitter:
            delay *= 1.0 + self.jitter * (2 * random.random() - 1)
        return max(delay, 0.0)

    async def run(self, attempt_fn, *, what: str = "op", log=None):
        """Run ``attempt_fn`` (no-arg coroutine function) under the
        policy; returns its result."""
        log = log or _log
        outer = _DEADLINE.get()
        dl = outer
        if self.deadline is not None:
            mine = Deadline(self.deadline)
            # the TIGHTEST deadline wins: a nested policy can shrink the
            # budget but never extend what the outer caller allowed
            dl = mine if outer is None or mine.at < outer.at else outer
        token = _DEADLINE.set(dl)
        try:
            last: Exception | None = None
            for attempt in range(self.attempts):
                if attempt:
                    delay = self._backoff(attempt)
                    if dl is not None and dl.remaining() <= delay:
                        break  # budget can't even cover the backoff
                    await asyncio.sleep(delay)
                cap = self.attempt_timeout
                if dl is not None:
                    rem = dl.remaining()
                    if rem <= 0:
                        break
                    cap = rem if cap is None else min(cap, rem)
                try:
                    if cap is None:
                        return await attempt_fn()
                    return await asyncio.wait_for(attempt_fn(), max(cap, 0.001))
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — classified below
                    if not self.transient(e):
                        raise
                    last = e
                    log.info("%s retry %d/%d: %s", what, attempt + 1,
                             self.attempts, e)
            raise RetryError(what, last)
        finally:
            _DEADLINE.reset(token)
