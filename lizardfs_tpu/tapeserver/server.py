"""Tape server daemon (matotsserv.cc peer, src/common/tape_* analog).

Protocol: ``TstomaRegister`` -> master, then the master pushes
``MatotsPutFile`` commands; the daemon reads the file's current content
via a regular cluster client session and writes it to the archive
directory, acking with ``TstomaPutDone`` carrying the content stamp
(length, mtime) it actually archived — the master only records the tape
copy if the stamp still matches the live file (no torn archives of
concurrently-written files).

Archive layout: ``<archive>/<inode>_<mtime>_<length>.tape`` plus a
``.json`` sidecar with the original path for operator recovery.
"""

from __future__ import annotations

import asyncio
import json
import os

from lizardfs_tpu.client.client import Client
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime.daemon import Daemon
from lizardfs_tpu.runtime.rpc import RpcConnection


class TapeServer(Daemon):
    name = "tapeserver"

    def __init__(
        self,
        archive_dir: str,
        master_addr: tuple[str, int],
        label: str = "_",
        heartbeat_interval: float = 5.0,
    ) -> None:
        # the admin/metrics port; tape data flows over the master link
        super().__init__()
        self.archive_dir = archive_dir
        self.master_addr = master_addr
        self.label = label
        self.heartbeat_interval = heartbeat_interval
        self.master: RpcConnection | None = None
        self.client: Client | None = None
        self.ts_id = 0
        # test hook: when set, _cmd_put parks here AFTER reading the
        # file and BEFORE archiving/acking — the window the master's
        # stamp-mismatch defense exists for (a concurrent write must
        # not be recorded as archived)
        self.put_barrier: asyncio.Event | None = None
        os.makedirs(archive_dir, exist_ok=True)

    async def setup(self) -> None:
        self.add_timer(self.heartbeat_interval, self._keepalive)

    async def start(self) -> None:
        await super().start()
        await self._connect()

    async def _connect(self) -> None:
        self.client = Client(*self.master_addr)
        # lint: waive(unbounded-await): delegates to Client.connect — dials via the 5 s-bounded RpcConnection.connect and a 30 s-capped register RPC
        await self.client.connect(info=f"tapeserver:{self.label}")
        self.master = await RpcConnection.connect(*self.master_addr)
        self.master.on_push(m.MatotsPutFile, self._cmd_put)
        self.master.on_push(m.MatotsDeleteFile, self._cmd_delete)
        self.master.on_push(m.MatotsRecallFile, self._cmd_recall)
        reply = await self.master.call_ok(
            m.TstomaRegister, label=self.label, capacity=0,
            session_id=self.client.session_id,
        )
        self.ts_id = reply.ts_id
        self.log.info("registered with master as tape server %d", self.ts_id)

    async def _keepalive(self) -> None:
        """Reconnect the master link after a failover/restart."""
        if self.master is None or self.master.closed:
            try:
                if self.client is not None:
                    await self.client.close()
                await self._connect()
            except (OSError, ConnectionError, st.StatusError,
                    asyncio.TimeoutError):
                pass

    def _archive_path(self, inode: int, mtime: int, length: int) -> str:
        return os.path.join(
            self.archive_dir, f"{inode}_{mtime}_{length}.tape"
        )

    async def _cmd_put(self, msg: m.MatotsPutFile) -> None:
        code = st.OK
        length, mtime = 0, 0
        try:
            attr = await self.client.getattr(msg.inode)
            length, mtime = attr.length, attr.mtime
            data = await self.client.read_file(msg.inode, 0, attr.length)
            if self.put_barrier is not None:
                # test hook: hold the read-to-ack window open so a
                # concurrent mutation can race the archive
                await asyncio.wait_for(self.put_barrier.wait(), 30.0)
            dest = self._archive_path(msg.inode, mtime, length)
            tmp = dest + ".tmp"
            await asyncio.to_thread(self._write_archive, tmp, dest, data, {
                "inode": msg.inode, "path": msg.path,
                "length": length, "mtime": mtime, "label": self.label,
            })
            self.metrics.counter("tape_archived_bytes").inc(float(len(data)))
            self.metrics.counter("tape_files").inc()
        except st.StatusError as e:
            code = e.code
        except (OSError, ConnectionError, asyncio.TimeoutError):
            self.log.exception("archiving inode %d failed", msg.inode)
            code = st.EIO
        await self.master.send(m.TstomaPutDone(
            req_id=msg.req_id, inode=msg.inode, status=code,
            length=length, mtime=mtime,
        ))

    async def _cmd_recall(self, msg: m.MatotsRecallFile) -> None:
        """Restore a demoted file from the archive: stream the exact
        stamped version back through the cluster client session. The
        master only sends this while it holds the inode in
        recall-inflight state (writes allowed, reads still fenced)."""
        code = st.OK
        try:
            path = self._archive_path(msg.inode, msg.mtime, msg.length)
            data = await asyncio.to_thread(self._read_archive, path)
            if data is None:
                code = st.ENOENT
            else:
                await self.client.write_file(msg.inode, data)
                self.metrics.counter("tape_recalled_bytes").inc(
                    float(len(data))
                )
                self.metrics.counter("tape_recalls").inc()
        except st.StatusError as e:
            code = e.code
        except (OSError, ConnectionError, asyncio.TimeoutError):
            self.log.exception("recalling inode %d failed", msg.inode)
            code = st.EIO
        await self.master.send(m.TstomaRecallDone(
            req_id=msg.req_id, inode=msg.inode, status=code,
            length=msg.length, mtime=msg.mtime,
        ))

    @staticmethod
    def _read_archive(path: str) -> bytes | None:
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    async def _cmd_delete(self, msg: m.MatotsDeleteFile) -> None:
        """Reclaim archives: keep only the (keep_mtime, keep_length)
        version; (0, 0) removes every version of the inode."""
        keep = None
        if msg.keep_mtime or msg.keep_length:
            keep = f"{msg.inode}_{msg.keep_mtime}_{msg.keep_length}.tape"

        def reclaim() -> int:
            n = 0
            prefix = f"{msg.inode}_"
            for name in os.listdir(self.archive_dir):
                base = name[:-5] if name.endswith(".json") else name
                if not (base.startswith(prefix) and base.endswith(".tape")):
                    continue
                if keep is not None and base == keep:
                    continue
                try:
                    os.unlink(os.path.join(self.archive_dir, name))
                    n += 1
                except OSError:
                    pass
            return n

        removed = await asyncio.to_thread(reclaim)
        if removed:
            self.metrics.counter("tape_reclaimed").inc(float(removed))

    @staticmethod
    def _write_archive(tmp: str, dest: str, data: bytes, meta: dict) -> None:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dest)
        with open(dest + ".json", "w") as f:
            json.dump(meta, f)

    async def stop(self) -> None:
        if self.master is not None:
            await self.master.close()
        if self.client is not None:
            await self.client.close()
        await super().stop()
