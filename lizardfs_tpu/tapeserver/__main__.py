"""Run a tape server: python -m lizardfs_tpu.tapeserver [config]

Config keys: DATA_PATH (archive directory), MASTER_HOST, MASTER_PORT,
LABEL, LOG_LEVEL.
"""

import asyncio
import sys

from lizardfs_tpu.runtime.config import Config
from lizardfs_tpu.runtime.daemon import setup_logging
from lizardfs_tpu.tapeserver.server import TapeServer


def main() -> None:
    cfg = Config(sys.argv[1] if len(sys.argv) > 1 else None)
    setup_logging("tapeserver", cfg.get_str("LOG_LEVEL", "INFO"))
    server = TapeServer(
        archive_dir=cfg.get_str("DATA_PATH", "./tape-archive"),
        master_addr=(
            cfg.get_str("MASTER_HOST", "127.0.0.1"),
            cfg.get_int("MASTER_PORT", 9420),
        ),
        label=cfg.get_str("LABEL", "_"),
    )

    asyncio.run(server.run_forever())


if __name__ == "__main__":
    main()
