"""Tape server: archival whole-file copies for goals with a $tape slice.

The reference's tape support (src/master/matotsserv.cc + src/common/
tape_*, ~600 LoC) lets goals request copies on tape servers in addition
to disk replication. This package is the framework's tape daemon: it
registers with the master, receives "archive this file" commands, reads
the file through the normal client data path, and stores it in its
archive directory (the "tape library" — any cold medium mounted there).
"""

from lizardfs_tpu.tapeserver.server import TapeServer

__all__ = ["TapeServer"]
