"""Idle-connection reuse pool for chunkserver links.

The reference keeps a pool of idle TCP connections to chunkservers and
reuses them across read operations (reference:
src/common/connection_pool.{h,cc}, chunk_connector.{h,cc}). Same here:
``acquire`` hands out an idle (reader, writer) pair or dials a new one;
``release`` returns it after a fully-drained exchange. Connections are
validated cheaply on acquire (EOF check) and expire after an idle TTL.
"""

from __future__ import annotations

import asyncio
import time

from lizardfs_tpu.runtime import faults as _faults
from lizardfs_tpu.runtime import retry as _retry
from lizardfs_tpu.runtime import tracing as _tracing

# dial bound: a blackholed chunkserver (SYN dropped) must cost a read
# attempt seconds, not the OS connect timeout; tighter ambient
# RetryPolicy deadlines shrink this further (runtime/retry.py)
DIAL_TIMEOUT = 5.0


class PooledConnection:
    __slots__ = ("reader", "writer", "idle_since", "loop")

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.idle_since = 0.0
        self.loop = asyncio.get_running_loop()


class ConnectionPool:
    def __init__(self, max_idle_per_addr: int = 4, idle_ttl: float = 5.0):
        self.max_idle = max_idle_per_addr
        self.idle_ttl = idle_ttl
        self._idle: dict[tuple[str, int], list[PooledConnection]] = {}

    async def acquire(self, addr: tuple[str, int]) -> PooledConnection:
        bucket = self._idle.get(addr, [])
        now = time.monotonic()
        loop = asyncio.get_running_loop()
        while bucket:
            conn = bucket.pop()
            # streams are bound to the loop that created them; a pooled
            # pair from another (possibly closed) loop is unusable
            if conn.loop is not loop:
                try:
                    conn.writer.close()
                except RuntimeError:
                    pass
                continue
            if now - conn.idle_since > self.idle_ttl:
                conn.writer.close()
                continue
            if conn.reader.at_eof() or conn.writer.is_closing():
                conn.writer.close()
                continue
            return conn
        if _faults.ACTIVE:
            await _faults.dial_point("cs", f"{addr[0]}:{addr[1]}")
        # pool miss: the dial is read-phase "dial" busy-time (and the
        # `dial` queue-wait gate) on whatever logical read is ambient;
        # free when no read-phase sink is active
        t0 = _tracing.phase_t0()
        reader, writer = await _retry.bounded_wait(
            asyncio.open_connection(*addr), DIAL_TIMEOUT
        )
        _tracing.charge_phase("dial", t0)
        return PooledConnection(reader, writer)

    def release(self, addr: tuple[str, int], conn: PooledConnection) -> None:
        """Return a connection after a complete request/response cycle."""
        try:
            same_loop = conn.loop is asyncio.get_running_loop()
        except RuntimeError:
            same_loop = False
        if not same_loop or conn.writer.is_closing() or conn.reader.at_eof():
            conn.writer.close()
            return
        bucket = self._idle.setdefault(addr, [])
        if len(bucket) >= self.max_idle:
            conn.writer.close()
            return
        conn.idle_since = time.monotonic()
        bucket.append(conn)

    def discard(self, conn: PooledConnection) -> None:
        """Drop a connection whose stream state is unknown (errors)."""
        conn.writer.close()

    def close_all(self) -> None:
        for bucket in self._idle.values():
            for conn in bucket:
                try:
                    conn.writer.close()
                except RuntimeError:
                    # stream bound to a dead loop (see acquire): the
                    # socket died with its loop, nothing left to close
                    pass
        self._idle.clear()


# module-level default pool shared by read executors in one process
GLOBAL_POOL = ConnectionPool()
