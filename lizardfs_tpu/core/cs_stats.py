"""Per-chunkserver health scores shared across reads.

The analog of the reference's ChunkserverStats (reference:
src/common/chunkserver_stats.cc; consumed by read_plan_executor.cc:95
and chunk_read_planner.cc): every data-plane exchange records success
or failure per server address; defects DECAY exponentially with time so
a server that recovered stops being penalized. Planners and replica
choice consult ``score`` (1.0 = healthy, approaching 0 = repeatedly
failing) so a flaky or slow chunkserver is demoted everywhere at once
instead of per-connection.
"""

from __future__ import annotations

import threading
import time


class ChunkserverStats:
    HALF_LIFE = 30.0  # seconds for a defect to decay to half weight
    FAILURE_WEIGHT = 1.0
    # successes actively repair the score so one good exchange after a
    # blip recovers faster than pure decay
    SUCCESS_REPAIR = 0.25

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        # addr -> (decayed defect weight, last update timestamp)
        self._defects: dict[tuple[str, int], tuple[float, float]] = {}

    def _decayed(self, addr: tuple[str, int], now: float) -> float:
        entry = self._defects.get(addr)
        if entry is None:
            return 0.0
        weight, ts = entry
        return weight * 0.5 ** ((now - ts) / self.HALF_LIFE)

    def record_failure(self, addr: tuple[str, int]) -> None:
        now = self._clock()
        with self._lock:
            w = self._decayed(addr, now) + self.FAILURE_WEIGHT
            self._defects[addr] = (w, now)

    def record_success(self, addr: tuple[str, int]) -> None:
        now = self._clock()
        with self._lock:
            w = self._decayed(addr, now)
            if w <= 0.01:
                self._defects.pop(addr, None)
                return
            self._defects[addr] = (max(w - self.SUCCESS_REPAIR, 0.0), now)

    def defects(self, addr: tuple[str, int]) -> float:
        with self._lock:
            return self._decayed(addr, self._clock())

    def score(self, addr: tuple[str, int]) -> float:
        """1.0 = healthy; halves per recent defect (never reaches 0 so
        a degraded server stays usable when it is the only one)."""
        return 0.5 ** min(self.defects(addr), 10.0)


# process-wide registry: clients, FUSE mounts, and the replicator in one
# process share what they learn about chunkserver health
GLOBAL_STATS = ChunkserverStats()
