"""Slice/goal geometry: slice types, chunk part types, part-size math.

Functional re-implementation of the reference's goal/slice model
(reference: src/common/goal.h:108-166 slice-type ids,
src/common/chunk_part_type.h:143-198 part-id packing,
src/common/slice_traits.h part geometry). The wire/disk encodings are
kept identical so on-disk chunk names and protocol ids are compatible:

  * slice type id: std=0, tape=1, xor2..xor9=2..9,
    ec(k,m) = 10 + 32*(k-2) + (m-1)  (k in [2,32], m in [1,32])
  * chunk part id: type_id * 64 + part_index
  * xor slices: part 0 is parity, parts 1..N are data
  * ec slices: parts 0..k-1 are data, k..k+m-1 are parity

Everything here is a pure function over ints — no state, trivially
jit-safe when needed host-side.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from lizardfs_tpu.constants import (
    EC_MAX_DATA,
    EC_MAX_PARITY,
    EC_MIN_DATA,
    EC_MIN_PARITY,
    MFSBLOCKSINCHUNK,
    MFSBLOCKSIZE,
    XOR_MAX_LEVEL,
    XOR_MIN_LEVEL,
)

# --- slice type ids (goal.h:108-120) ---------------------------------------

STANDARD = 0
TAPE = 1
XOR_FIRST = 2  # xor2
XOR_LAST = 9  # xor9
EC_FIRST = 10
EC_LAST = EC_FIRST + 31 * 32 - 1  # ec(32,32)
TYPE_COUNT = EC_LAST + 1

MAX_PARTS_PER_SLICE = 64  # chunk_part_type.h:145


class SliceType(int):
    """A slice type id with geometry accessors."""

    def is_valid(self) -> bool:
        return STANDARD <= self < TYPE_COUNT

    @property
    def is_standard(self) -> bool:
        return self == STANDARD

    @property
    def is_tape(self) -> bool:
        return self == TAPE

    @property
    def is_xor(self) -> bool:
        return XOR_FIRST <= self <= XOR_LAST

    @property
    def is_ec(self) -> bool:
        return EC_FIRST <= self <= EC_LAST

    @property
    def xor_level(self) -> int:
        assert self.is_xor
        return self - XOR_FIRST + XOR_MIN_LEVEL

    @property
    def data_parts(self) -> int:
        """Number of data parts (slice_traits.h:227-235)."""
        if self.is_xor:
            return self.xor_level
        if self.is_ec:
            return EC_MIN_DATA + (self - EC_FIRST) // 32
        return 1

    @property
    def parity_parts(self) -> int:
        if self.is_xor:
            return 1
        if self.is_ec:
            return EC_MIN_PARITY + (self - EC_FIRST) % 32
        return 0

    @property
    def expected_parts(self) -> int:
        """Total parts in a full slice (goal.h:148-152)."""
        if self.is_ec:
            return self.data_parts + self.parity_parts
        if self.is_xor:
            return self.xor_level + 1
        return 1

    def __repr__(self) -> str:
        return f"SliceType({self.to_string()})"

    def to_string(self) -> str:
        if self.is_ec:
            return f"ec({self.data_parts},{self.parity_parts})"
        if self.is_xor:
            return f"xor{self.xor_level}"
        return {STANDARD: "std", TAPE: "tape"}.get(int(self), f"?{int(self)}")


def xor_type(level: int) -> SliceType:
    if not XOR_MIN_LEVEL <= level <= XOR_MAX_LEVEL:
        raise ValueError(f"xor level {level} out of range")
    return SliceType(XOR_FIRST + level - XOR_MIN_LEVEL)


def ec_type(k: int, m: int) -> SliceType:
    """ec(k,m) slice type id (slice_traits.h:148-151)."""
    if not (EC_MIN_DATA <= k <= EC_MAX_DATA and EC_MIN_PARITY <= m <= EC_MAX_PARITY):
        raise ValueError(f"ec({k},{m}) out of range")
    return SliceType(EC_FIRST + 32 * (k - EC_MIN_DATA) + (m - EC_MIN_PARITY))


@dataclass(frozen=True, order=True)
class ChunkPartType:
    """(slice type, part index) packed as id = type*64 + part."""

    type: SliceType
    part: int

    @property
    def id(self) -> int:
        return int(self.type) * MAX_PARTS_PER_SLICE + self.part

    @classmethod
    def from_id(cls, part_id: int) -> "ChunkPartType":
        return cls(
            SliceType(part_id // MAX_PARTS_PER_SLICE),
            part_id % MAX_PARTS_PER_SLICE,
        )

    def is_valid(self) -> bool:
        return self.type.is_valid() and 0 <= self.part < self.type.expected_parts

    # part-role accessors (slice_traits.h:213-295)
    @property
    def is_parity(self) -> bool:
        if self.type.is_xor:
            return self.part == 0  # xor parity is part 0
        if self.type.is_ec:
            return self.part >= self.type.data_parts
        return False

    @property
    def is_data(self) -> bool:
        return not self.is_parity

    @property
    def data_part_index(self) -> int:
        """Stripe position of a data part (xor data parts are 1-based)."""
        if self.type.is_xor:
            return self.part - 1
        return self.part

    @property
    def parity_part_index(self) -> int:
        if self.type.is_ec:
            return self.part - self.type.data_parts
        return 0

    def to_string(self) -> str:
        return f"{self.type.to_string()}:{self.part}"

    def __repr__(self) -> str:
        return f"ChunkPartType({self.to_string()})"


def standard_part() -> ChunkPartType:
    return ChunkPartType(SliceType(STANDARD), 0)


def number_of_blocks_in_part(cpt: ChunkPartType, blocks_in_chunk: int = MFSBLOCKSINCHUNK) -> int:
    """Blocks stored in a given part (slice_traits.h:311-316).

    Blocks are striped round-robin over data parts; parity parts are as
    long as the longest (first) data part.
    """
    d = cpt.type.data_parts
    idx = cpt.data_part_index if cpt.is_data else 0
    return (blocks_in_chunk + (d - idx - 1)) // d


def chunk_length_to_part_length(cpt: ChunkPartType, chunk_length: int) -> int:
    """Byte length of a part given total chunk length
    (slice_traits.h:332-349)."""
    d = cpt.type.data_parts
    if d == 1:
        return chunk_length
    full_stripe = chunk_length // (d * MFSBLOCKSIZE)
    base_len = full_stripe * MFSBLOCKSIZE
    rest = chunk_length - base_len * d
    idx = cpt.data_part_index if cpt.is_data else 0
    part_rest = max(rest - idx * MFSBLOCKSIZE, 0)
    return base_len + min(part_rest, MFSBLOCKSIZE)


def stripe_size(cpt: ChunkPartType) -> int:
    return cpt.type.data_parts


def required_parts_to_recover(t: SliceType) -> int:
    return t.data_parts


# --- goals ------------------------------------------------------------------

WILDCARD_LABEL = "_"
MAX_GOAL_NAME = 32
MAX_LABELS_PER_SLICE = 40
GOAL_ID_MIN, GOAL_ID_MAX = 1, 40  # reference goal id range (goal.h:40-44)

_NAME_RE = re.compile(r"^[A-Za-z0-9_]{1,32}$")


@dataclass(frozen=True)
class Slice:
    """One slice of a goal: a type plus per-part label->count maps.

    The reference stores, for every part, a map of labels to copy counts
    (goal.h Slice). For std slices there is one part whose label counts
    describe the desired copies; for xor/ec slices each part usually has
    exactly one label (possibly the wildcard).
    """

    type: SliceType
    part_labels: tuple[tuple[tuple[str, int], ...], ...]  # per part: ((label, count),...)

    @classmethod
    def make(cls, type_: SliceType, labels_per_part: list[dict[str, int]]) -> "Slice":
        return cls(
            type_,
            tuple(tuple(sorted(d.items())) for d in labels_per_part),
        )

    @property
    def size(self) -> int:
        return len(self.part_labels)

    def labels_of_part(self, part: int) -> dict[str, int]:
        return dict(self.part_labels[part])


@dataclass(frozen=True)
class Goal:
    """A named replication goal: a set of slices (goal.h Goal)."""

    name: str
    slices: tuple[Slice, ...]

    def expected_copies(self) -> int:
        """Chunkserver copies the goal wants (disk slices only — tape
        copies are whole-file archives, not chunk placements)."""
        total = 0
        for s in self.slices:
            if s.type.is_tape:
                continue
            for part in s.part_labels:
                total += sum(c for _, c in part)
        return total

    def disk_slice(self) -> "Slice | None":
        """The slice that places chunk parts on chunkservers."""
        for s in self.slices:
            if not s.type.is_tape:
                return s
        return None

    def tape_copies(self) -> int:
        """Archival copies requested from tape servers (goal.h tape
        labels; served by the matotsserv analog)."""
        return len(self.tape_labels())

    def tape_labels(self) -> list[str]:
        """One entry per requested tape copy: a named label means a
        server with that label; the wildcard means any tape server."""
        out: list[str] = []
        for s in self.slices:
            if s.type.is_tape:
                for part in s.part_labels:
                    for lab, c in part:
                        out.extend([lab] * c)
        return out


def default_goals() -> dict[int, Goal]:
    """Goals 1..5 default to N plain copies (reference behavior)."""
    out = {}
    for gid in range(GOAL_ID_MIN, 6):
        s = Slice.make(SliceType(STANDARD), [{WILDCARD_LABEL: gid}])
        out[gid] = Goal(str(gid), (s,))
    for gid in range(6, GOAL_ID_MAX + 1):
        s = Slice.make(SliceType(STANDARD), [{WILDCARD_LABEL: 1}])
        out[gid] = Goal(str(gid), (s,))
    return out


class GoalConfigError(ValueError):
    pass


def parse_goal_line(line: str) -> tuple[int, Goal] | None:
    """Parse one mfsgoals.cfg line: ``id name : slice [| slice ...]``
    where a slice is ``[$type[(k,m)]] [{ labels } | labels]``.

    Grammar per doc/mfsgoals.cfg.5.txt:47-98, extended with the
    reference's multi-slice goals (goal.h Goal = set of slices): a
    ``$tape`` slice after ``|`` requests archival copies from tape
    servers (matotsserv.cc) in addition to the disk slice. Returns None
    for blank or comment lines.
    """
    line = line.split("#", 1)[0].strip()
    if not line:
        return None
    m = re.match(r"^(\d+)\s+(\S+)\s*:\s*(.*)$", line)
    if not m:
        raise GoalConfigError(f"malformed goal line: {line!r}")
    gid = int(m.group(1))
    name = m.group(2)
    rest = m.group(3).strip()
    if not (GOAL_ID_MIN <= gid <= GOAL_ID_MAX):
        raise GoalConfigError(f"goal id {gid} out of range [1,40]")
    if not _NAME_RE.match(name):
        raise GoalConfigError(f"invalid goal name {name!r}")

    slices = tuple(
        _parse_slice_segment(seg.strip(), line) for seg in rest.split("|")
    )
    disk = [s for s in slices if not s.type.is_tape]
    tape = [s for s in slices if s.type.is_tape]
    if len(disk) != 1:
        raise GoalConfigError(
            f"goal needs exactly one disk slice (std/xor/ec): {line!r}"
        )
    if len(tape) > 1:
        raise GoalConfigError(f"at most one $tape slice per goal: {line!r}")
    if tape and slices[0].type.is_tape:
        raise GoalConfigError(f"disk slice must come first: {line!r}")
    return gid, Goal(name, slices)


def _parse_slice_segment(rest: str, line: str) -> Slice:
    type_ = SliceType(STANDARD)
    labels_str = rest
    tm = re.match(r"^\$(\w+)(?:\(\s*(\d+)\s*,\s*(\d+)\s*\))?\s*(.*)$", rest)
    if tm:
        tname = tm.group(1)
        if tname == "std":
            type_ = SliceType(STANDARD)
        elif tname == "tape":
            type_ = SliceType(TAPE)
        elif tname.startswith("xor"):
            try:
                type_ = xor_type(int(tname[3:]))
            except ValueError as e:
                raise GoalConfigError(str(e)) from None
        elif tname == "ec":
            if tm.group(2) is None:
                raise GoalConfigError(f"ec goal needs (k,m): {line!r}")
            try:
                type_ = ec_type(int(tm.group(2)), int(tm.group(3)))
            except ValueError as e:
                raise GoalConfigError(str(e)) from None
        else:
            raise GoalConfigError(f"unknown goal type ${tname}")
        labels_str = tm.group(4).strip()
        if labels_str:
            bm = re.match(r"^\{\s*([^}]*)\s*\}$", labels_str)
            if not bm:
                raise GoalConfigError(f"labels for typed goal must be braced: {line!r}")
            labels_str = bm.group(1).strip()

    labels = labels_str.split() if labels_str else []
    for lab in labels:
        if lab != WILDCARD_LABEL and not _NAME_RE.match(lab):
            raise GoalConfigError(f"invalid label {lab!r}")
    if len(labels) > MAX_LABELS_PER_SLICE:
        raise GoalConfigError("too many labels (max 40)")

    if type_.is_standard or type_.is_tape:
        # tape: each label = one archival copy on a matching tape server
        counts: dict[str, int] = {}
        for lab in labels or [WILDCARD_LABEL]:
            counts[lab] = counts.get(lab, 0) + 1
        if type_.is_tape:
            # copies are recorded per server label, so a repeated NAMED
            # label could never be satisfied; wildcards may repeat
            # (distinct servers carry distinct labels)
            dup = [lab for lab, c in counts.items()
                   if lab != WILDCARD_LABEL and c > 1]
            if dup:
                raise GoalConfigError(
                    f"repeated tape label {dup[0]!r}: {line!r}"
                )
        return Slice.make(type_, [counts])
    nparts = type_.expected_parts
    if labels and len(labels) > nparts:
        raise GoalConfigError(
            f"{type_.to_string()} takes at most {nparts} labels, got {len(labels)}"
        )
    per_part = []
    for i in range(nparts):
        lab = labels[i] if i < len(labels) else WILDCARD_LABEL
        per_part.append({lab: 1})
    return Slice.make(type_, per_part)


def load_goal_config(text: str) -> dict[int, Goal]:
    """Parse a whole mfsgoals.cfg; unspecified ids keep defaults."""
    goals = default_goals()
    for lineno, line in enumerate(text.splitlines(), 1):
        try:
            parsed = parse_goal_line(line)
        except GoalConfigError as e:
            raise GoalConfigError(f"line {lineno}: {e}") from None
        if parsed:
            gid, goal = parsed
            goals[gid] = goal
    return goals
