"""The ChunkEncoder plugin boundary — the seam between the file system and
the erasure-coding compute backend.

Per the north star, everything in the framework that touches EC math
(client write path computing parity, client read path recovering erased
parts, chunkserver replicator rebuilding parts, chunkserver CRC
verify/update) dispatches through this interface, with interchangeable
backends:

  * ``CpuChunkEncoder`` — numpy golden path
    (:mod:`lizardfs_tpu.ops.rs`), byte-identical to the reference's
    ISA-L/galois_field codec. Correctness oracle and small-request path.
  * ``TpuChunkEncoder`` — JAX/XLA bit-plane kernels
    (:mod:`lizardfs_tpu.ops.jax_ec`) with fused encode+CRC dispatch.

The API mirrors the surface of the reference's ``ReedSolomon`` +
``mycrc32`` pair (reference: src/common/reed_solomon.h:87-155,
src/common/crc.h) with batching over whole parts, plus the fused
encode+checksum entry point used by the chunkserver write pipeline.
"""

from __future__ import annotations

import abc
import os

import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.ops import crc32, rs


class ChunkEncoder(abc.ABC):
    """EC compute backend interface.

    Parts are equal-length 1-D uint8 arrays (byte streams of chunk
    parts); part indices are global: 0..k-1 data, k..k+m-1 parity.
    """

    name: str

    @abc.abstractmethod
    def encode(
        self, k: int, m: int, data_parts: list[np.ndarray | None]
    ) -> list[np.ndarray]:
        """Compute the m parity parts from the k data parts (None = zeros)."""

    @abc.abstractmethod
    def recover(
        self,
        k: int,
        m: int,
        parts: dict[int, np.ndarray | None],
        wanted: list[int],
    ) -> dict[int, np.ndarray]:
        """Recover ``wanted`` global part indices from any >=k available parts."""

    @abc.abstractmethod
    def checksum(self, blocks: np.ndarray) -> np.ndarray:
        """CRC32 of each row of a (n, block_size) uint8 array -> (n,) uint32."""

    @abc.abstractmethod
    def encode_with_checksums(
        self, k: int, m: int, data: np.ndarray, block_size: int = MFSBLOCKSIZE
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Fused parity + per-block CRCs of data and parity.

        data: (k, N) with N a multiple of block_size. Returns
        (parity (m, N), data_crcs (k, N//bs), parity_crcs (m, N//bs)).
        """

    def xor_parity(self, parts: list[np.ndarray]) -> np.ndarray:
        """XOR parity (xor2..xor9 goals)."""
        return rs.xor_parity(parts)

    def encode_into(
        self,
        k: int,
        m: int,
        data_parts: list[np.ndarray],
        out: list[np.ndarray],
    ) -> None:
        """``encode`` writing the m parity streams into caller buffers.

        ``out`` holds m contiguous uint8 arrays (typically row slices of
        one send buffer) each the length of a data part. Backends that
        can emit parity in place override this to skip the staging copy
        (the client's pipelined write path sends straight from ``out``);
        this default stays correct everywhere else.
        """
        parity = self.encode(k, m, data_parts)
        for dst, src in zip(out, parity):
            np.copyto(dst, src)

    def xor_parity_into(
        self, parts: list[np.ndarray], out: np.ndarray
    ) -> None:
        """``xor_parity`` writing into a caller buffer (see encode_into)."""
        np.copyto(out, parts[0])
        for p in parts[1:]:
            np.bitwise_xor(out, p, out=out)


class CpuChunkEncoder(ChunkEncoder):
    """Golden numpy backend (reference-identical bytes)."""

    name = "cpu"

    def encode(self, k, m, data_parts):
        return rs.encode(k, m, data_parts)

    def recover(self, k, m, parts, wanted):
        return rs.recover(k, m, parts, wanted)

    def checksum(self, blocks):
        return crc32.block_crcs_golden(np.ascontiguousarray(blocks))

    def encode_with_checksums(self, k, m, data, block_size=MFSBLOCKSIZE):
        n = data.shape[1]
        nb = n // block_size
        parity = rs.encode(k, m, list(data))
        parity_arr = np.stack(parity)
        data_crcs = self.checksum(data.reshape(k * nb, block_size)).reshape(k, nb)
        parity_crcs = self.checksum(parity_arr.reshape(m * nb, block_size)).reshape(
            m, nb
        )
        return parity_arr, data_crcs, parity_crcs


def _tpu_allow_cpu() -> bool:
    """LZ_TPU_ALLOW_CPU escape hatch (default OFF). Routed through the
    one spelling-parity accessor: the old bare-truthiness read meant
    ``LZ_TPU_ALLOW_CPU=0`` *enabled* the hatch (set, therefore truthy)
    — the exact inversion the kill-switch lint exists to prevent."""
    from lizardfs_tpu.constants import env_flag

    return env_flag("LZ_TPU_ALLOW_CPU", default=False)


class TpuChunkEncoder(ChunkEncoder):
    """JAX/XLA backend: bit-plane MXU matmuls, fused encode+CRC.

    Lazily imports jax so pure-CPU deployments never pay for it.

    Refuses to bind a CPU-platform JAX device unless explicitly forced
    (``force_cpu=True`` or ``LZ_TPU_ALLOW_CPU=1``): on a JAX-installed
    box without real silicon the XLA bit-plane path is the SLOWEST
    correct backend (measured 3.8x vs the C++ SIMD encoder, VERDICT r05
    weak #2), so "tpu" must mean TPU — the auto ladder degrades to
    cpp/cpu instead of silently landing here.
    """

    name = "tpu"

    def __init__(self, device=None, *, force_cpu: bool = False):
        import jax

        from lizardfs_tpu.ops import jax_ec

        self._jax = jax
        self._ops = jax_ec
        self._device = device if device is not None else jax.devices()[0]
        if (
            not force_cpu
            and not _tpu_allow_cpu()
            and getattr(self._device, "platform", "cpu") == "cpu"
        ):
            raise RuntimeError(
                "TpuChunkEncoder bound a CPU-platform JAX device — the "
                "XLA bit-plane path is ~4x slower than the native SIMD "
                "backend on CPUs; pass force_cpu=True (tests/numerics) "
                "or set LZ_TPU_ALLOW_CPU=1 to override"
            )

    def _put(self, arr: np.ndarray):
        return self._jax.device_put(np.ascontiguousarray(arr), self._device)

    def encode(self, k, m, data_parts):
        import jax.numpy as jnp

        nonzero = [i for i, p in enumerate(data_parts) if p is not None]
        if not nonzero:
            raise ValueError("at least one data part must be non-None")
        if len(data_parts) != k:
            raise ValueError(f"expected {k} data parts, got {len(data_parts)}")
        bigm = self._ops.encoding_bitmatrix(k, m)
        if len(nonzero) < k:
            cols = np.concatenate([np.arange(8 * i, 8 * i + 8) for i in nonzero])
            bigm = bigm[:, cols]
        stacked = np.stack([np.asarray(data_parts[i]) for i in nonzero])
        out = self._ops.apply_gf(self._put(bigm), self._put(stacked))
        return list(np.asarray(out))

    def recover(self, k, m, parts, wanted):
        from lizardfs_tpu.ops import gf256

        used, _ = gf256.recovery_selection(k, m, list(parts.keys()), wanted)
        bigm = self._ops.recovery_bitmatrix(k, m, tuple(used), tuple(wanted))
        nonzero_pos = [j for j, i in enumerate(used) if parts[i] is not None]
        if not nonzero_pos:
            raise ValueError("at least one available part must be non-None")
        if len(nonzero_pos) < len(used):
            cols = np.concatenate(
                [np.arange(8 * j, 8 * j + 8) for j in nonzero_pos]
            )
            bigm = bigm[:, cols]
        stacked = np.stack([np.asarray(parts[used[j]]) for j in nonzero_pos])
        out = np.asarray(self._ops.apply_gf(self._put(bigm), self._put(stacked)))
        return {w: out[i] for i, w in enumerate(wanted)}

    def _pallas(self):
        from lizardfs_tpu.ops import pallas_ec

        return pallas_ec if pallas_ec.supported() else None

    def checksum(self, blocks):
        blocks = np.ascontiguousarray(blocks)
        pe = self._pallas()
        ops = pe if pe is not None else self._ops
        return np.asarray(
            ops.block_crcs(self._put(blocks), blocks.shape[1])
        ).astype(np.uint32)

    def xor_parity(self, parts):
        stacked = np.stack([np.asarray(p) for p in parts])
        return np.asarray(self._ops.xor_reduce(self._put(stacked)))

    def encode_with_checksums(self, k, m, data, block_size=MFSBLOCKSIZE):
        bigm = self._ops.encoding_bitmatrix(k, m)
        pe = self._pallas()
        fused = pe.fused_encode_crc if pe is not None else self._ops.fused_encode_crc
        parity, dcrc, pcrc = fused(self._put(bigm), self._put(data), block_size)
        return (
            np.asarray(parity),
            np.asarray(dcrc).astype(np.uint32),
            np.asarray(pcrc).astype(np.uint32),
        )


class ShardedTpuChunkEncoder(TpuChunkEncoder):
    """Mesh-sharded wide-stripe backend: ``recover`` rides the device
    mesh (parallel/recovery.py psum-scatter reconstruct) whenever the
    geometry divides it, falling back to the single-chip TPU kernels
    otherwise.  This is the chunkserver replicator's rebuild backend on
    multichip boxes — the auto ladder tries it before plain "tpu" when
    a mesh is available; ``LZ_SHARDED_RECOVERY=0`` kills it (the
    constructor refuses AND a live instance degrades to single-chip at
    call time, so the switch works mid-flight).
    """

    name = "sharded"

    def __init__(self, mesh=None, *, force_cpu: bool = False):
        from lizardfs_tpu.parallel import recovery as rec

        if not rec.enabled():
            raise RuntimeError("sharded recovery disabled "
                               "(LZ_SHARDED_RECOVERY=0)")
        super().__init__(force_cpu=force_cpu)
        if mesh is None:
            if len(self._jax.devices()) < 2:
                raise RuntimeError("mesh-sharded recovery needs >= 2 "
                                   "devices")
            from lizardfs_tpu.parallel import sharded as sh

            mesh = sh.make_mesh()
        self._mesh = mesh
        self._n_mesh = int(np.prod(list(self._mesh.shape.values())))
        # reconstruct step cache: the shard_map closure (and its jit
        # cache) is reused per (geometry, erasure pattern) — the
        # replicator's steady state is a handful of patterns
        self._rec_steps: dict[tuple, object] = {}

    def _mesh_recover_step(self, k, m, avail, wanted, block_size):
        key = (k, m, avail, wanted, block_size)
        step = self._rec_steps.get(key)
        if step is None:
            from lizardfs_tpu.parallel import recovery as rec

            step = rec.sharded_reconstruct_with_crcs(
                self._mesh, k, m, list(avail), list(wanted), block_size
            )
            if len(self._rec_steps) > 64:
                self._rec_steps.clear()  # unbounded-pattern guard
            self._rec_steps[key] = step
        return step

    def recover(self, k, m, parts, wanted):
        from lizardfs_tpu.parallel import recovery as rec

        nbytes = next(
            (len(p) for p in parts.values() if p is not None), 0
        )
        # the mesh path needs: the kill switch open, k parts dividing
        # the stripe axis, byte length dividing the mesh into CRC-able
        # (64-byte multiple) blocks, and no elided (None) inputs
        block = nbytes // self._n_mesh if self._n_mesh else 0
        if (
            not rec.enabled()
            or k % self._n_mesh
            or nbytes == 0
            or nbytes % self._n_mesh
            or block % 64
            or any(p is None for p in parts.values())
        ):
            return super().recover(k, m, parts, wanted)
        avail = tuple(sorted(parts.keys()))
        wanted = list(wanted)
        step = self._mesh_recover_step(k, m, avail, tuple(wanted), block)
        stacked = np.stack([np.asarray(parts[i]) for i in step.used])
        out, _crcs = step(stacked)
        out = np.asarray(out).reshape(len(wanted), -1)
        return {w: out[i] for i, w in enumerate(wanted)}


_ENCODERS: dict[str, ChunkEncoder] = {}


def get_encoder(name: str | None = None) -> ChunkEncoder:
    """Encoder registry. ``name``: "cpu", "cpp", "tpu", "sharded", or
    None/"auto".

    Auto degrades sharded (REAL silicon mesh with >= 2 devices and
    LZ_SHARDED_RECOVERY unset) -> tpu (real silicon only —
    TpuChunkEncoder refuses a CPU-platform JAX device) -> cpp (native
    SIMD) -> cpu (numpy golden), honoring the LIZARDFS_TPU_ENCODER env
    override — the analog of the reference keeping ISA-L as default
    with the plugin boundary on top. A JAX-without-TPU box therefore
    resolves auto to "cpp", not the 3.8x-slower XLA-on-CPU path.
    """
    if name is None:
        name = os.environ.get("LIZARDFS_TPU_ENCODER", "auto")
    if name == "auto":
        for candidate in ("sharded", "tpu", "cpp", "cpu"):
            try:
                return get_encoder(candidate)
            except Exception:
                continue
        name = "cpu"
    if name not in _ENCODERS:
        if name == "cpu":
            _ENCODERS[name] = CpuChunkEncoder()
        elif name == "cpp":
            from lizardfs_tpu.core.native import CppChunkEncoder

            _ENCODERS[name] = CppChunkEncoder()
        elif name == "tpu":
            _ENCODERS[name] = TpuChunkEncoder()
        elif name == "sharded":
            _ENCODERS[name] = ShardedTpuChunkEncoder()
        else:
            raise ValueError(f"unknown encoder backend {name!r}")
    return _ENCODERS[name]
