"""Wave-scheduled network execution of read plans.

The async analog of the reference's ReadPlanExecutor (reference:
src/common/read_plan_executor.cc): start wave 0's reads, fire the next
wave when a wave timeout expires or a read fails, finish as soon as the
plan says enough parts arrived, then post-process (recovery). Used by
the client read path and by the chunkserver replicator (both read chunk
parts from chunkservers).
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from lizardfs_tpu.core.plans import SliceReadPlan
from lizardfs_tpu.ops import crc32 as crc_mod
from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import accounting
from lizardfs_tpu.runtime import faults as _faults
from lizardfs_tpu.runtime import tracing

log = logging.getLogger("read_executor")

DEFAULT_WAVE_TIMEOUT = 0.5
DEFAULT_TOTAL_TIMEOUT = 30.0


class ReadError(Exception):
    """``crc`` marks end-to-end checksum rejections (the part's bytes
    arrived but are corrupt) — the signal the client's damaged-part
    reporting keys off, distinct from a merely unreachable holder."""

    def __init__(self, msg: str, crc: bool = False):
        self.crc = crc
        super().__init__(msg)


async def read_part_range(
    addr: tuple[str, int],
    chunk_id: int,
    version: int,
    part_id: int,
    offset: int,
    size: int,
    into: np.ndarray | None = None,
    into_offset: int = 0,
) -> np.ndarray:
    """Read one range of one part from one chunkserver, verifying piece
    CRCs (ReadOperationExecutor analog). Connections come from the
    process-wide pool and are returned after a clean, fully-drained
    exchange (ConnectionPool analog). Every outcome feeds the shared
    per-chunkserver health scores (chunkserver_stats.cc analog)."""
    from lizardfs_tpu.core.conn_pool import GLOBAL_POOL
    from lizardfs_tpu.core.cs_stats import GLOBAL_STATS

    out = into if into is not None else np.zeros(size, dtype=np.uint8)
    if size == 0:
        return out[into_offset:into_offset]

    # bulk reads run the whole exchange in C++ off the event loop
    # (framing + CRC + scatter with the GIL released)
    from lizardfs_tpu.core import native_io

    if (
        native_io.available()
        and size >= native_io.NATIVE_READ_THRESHOLD
        # armed faults: the C++ exchange cannot be instrumented, so the
        # hookable asyncio path below serves (LZ_FAULTS unset: no change)
        and not _faults.ACTIVE
    ):
        # scatter straight into the caller's buffer whenever it is
        # contiguous: each op owns a disjoint region, and the cancel
        # path below aborts the socket and JOINS the executor thread, so
        # by the time execute_plan's finally finishes (it gathers every
        # cancelled task) no thread can still be writing the plan buffer
        # that post-processing reads. This removes a private-buffer
        # allocation + an on-loop memcpy per part (64 MiB per EC chunk).
        scatter_direct = (
            into is not None and out.flags.c_contiguous
            and out.dtype == np.uint8
        )
        if scatter_direct:
            tmp = out[into_offset : into_offset + size]  # view, no copy
        else:
            tmp = np.empty(size, dtype=np.uint8)
        # when scattering into the CALLER's buffer, the uninterruptible
        # executor thread must not outlive this coroutine: a cancelled
        # or failed attempt would otherwise keep writing `out` while a
        # retry refills the same region. The cell lets us shut the
        # socket down (killing the thread's recv) and join it.
        cell: dict = {}
        fut = asyncio.get_running_loop().run_in_executor(
            native_io.EXECUTOR,
            # partial_with_trace: carries the request trace id into the
            # worker thread (plain run_in_executor drops context)
            native_io.partial_with_trace(
                native_io.read_part_blocking,
                addr, chunk_id, version, part_id, offset, size, tmp,
                cell if scatter_direct else None,
            ),
        )
        # run_in_executor drops the phase-sink context too: the native
        # exchange is timed here and charged as read-phase net (parallel
        # part reads overlap, so net busy-time may exceed wall — the
        # PhaseBreakdown pipelining contract)
        t0 = tracing.phase_t0()
        try:
            await asyncio.shield(fut)
            tracing.charge_phase("net", t0)
            GLOBAL_STATS.record_success(addr)
            if not scatter_direct:
                out[into_offset : into_offset + size] = tmp
            return out
        except asyncio.CancelledError:
            if scatter_direct:
                native_io.abort_read(cell)
                try:
                    await asyncio.wait_for(asyncio.shield(fut), 10.0)
                except (Exception, asyncio.CancelledError):
                    pass
            raise
        except native_io.NativeIOError as e:
            GLOBAL_STATS.record_failure(addr)
            raise ReadError(str(e), crc="crc" in str(e).lower()) from None
        except (OSError, ConnectionError) as e:
            GLOBAL_STATS.record_failure(addr)
            raise ReadError(f"native read failed: {e}") from None

    conn = await GLOBAL_POOL.acquire(addr)
    clean = False
    cancelled = False
    # the whole framed exchange (request send + piece recv/CRC loop) is
    # read-phase net busy-time on the ambient logical read
    t0 = tracing.phase_t0()
    try:
        await framing.send_message(
            conn.writer,
            m.CltocsRead(
                req_id=1,
                chunk_id=chunk_id,
                version=version,
                part_id=part_id,
                offset=offset,
                size=size,
                trace_id=tracing.current_trace_id(),
                # per-session attribution on the chunkserver: the
                # process-wide session identity (accounting.py), the
                # module-function analog of the thread-local trace id
                session_id=accounting.wire_session(),
            ),
        )
        received = 0
        while True:
            msg = await framing.read_message(conn.reader)
            if isinstance(msg, m.CstoclReadData):
                data = np.frombuffer(msg.data, dtype=np.uint8)
                if crc_mod.crc32(msg.data) != msg.crc:
                    raise ReadError(
                        "piece CRC mismatch from chunkserver", crc=True
                    )
                rel = msg.offset - offset
                if rel < 0 or rel + len(data) > size:
                    raise ReadError("piece outside requested range")
                out[into_offset + rel : into_offset + rel + len(data)] = data
                received += len(data)
            elif isinstance(msg, m.CstoclReadStatus):
                clean = True  # stream fully drained, even on error status
                if msg.status != st.OK:
                    GLOBAL_STATS.record_failure(addr)
                    raise ReadError(
                        f"read failed: {st.name(msg.status)}",
                        crc=msg.status == st.CRC_ERROR,
                    )
                if received < size:
                    GLOBAL_STATS.record_failure(addr)
                    raise ReadError(
                        f"short read: {received} of {size} bytes"
                    )
                GLOBAL_STATS.record_success(addr)
                tracing.charge_phase("net", t0)
                return out
            else:
                raise ReadError(f"unexpected message {type(msg).__name__}")
    except asyncio.CancelledError:
        cancelled = True
        raise
    finally:
        if clean:
            GLOBAL_POOL.release(addr, conn)
        else:
            # a CANCELLED read (wave straggler made redundant, plan
            # aborted by a different part's failure) is not this
            # server's defect — only real failures count
            if not cancelled:
                GLOBAL_STATS.record_failure(addr)
            GLOBAL_POOL.discard(conn)


async def execute_plan(
    plan: SliceReadPlan,
    chunk_id: int,
    version: int,
    locations: dict[int, tuple[tuple[str, int], int]],
    wave_timeout: float = DEFAULT_WAVE_TIMEOUT,
    total_timeout: float = DEFAULT_TOTAL_TIMEOUT,
    buffer: np.ndarray | None = None,
    on_part_failure=None,
) -> np.ndarray:
    """Execute a plan; returns the post-processed result bytes.

    locations: slice part index -> ((host, port), wire part_id).
    ``buffer`` (optional, C-contiguous uint8 of plan.buffer_size) lets
    the caller provide the scatter target so successful single-op plans
    write the result in place.
    ``on_part_failure`` (optional ``fn(part, wire_part_id, addr, exc)``)
    observes every per-part failure as it happens — the client threads
    its damaged-part reporter through here so a CRC-rejected part is
    reported to the master even when the read itself recovers.
    """
    if buffer is None:
        buffer = np.zeros(plan.buffer_size, dtype=np.uint8)
    else:
        assert buffer.size == plan.buffer_size and buffer.dtype == np.uint8
    available: list[int] = []
    unreadable: list[int] = []
    pending: dict[asyncio.Task, int] = {}
    max_wave = max((op.wave for op in plan.read_operations), default=0)
    loop = asyncio.get_running_loop()
    deadline = loop.time() + total_timeout
    current_wave = -1

    def start_wave(w: int):
        for op in plan.read_operations:
            if op.wave != w:
                continue
            if op.part not in locations:
                unreadable.append(op.part)
                continue
            addr, wire_part_id = locations[op.part]
            task = asyncio.ensure_future(
                read_part_range(
                    addr,
                    chunk_id,
                    version,
                    wire_part_id,
                    op.request_offset,
                    op.request_size,
                    into=buffer,
                    into_offset=op.buffer_offset,
                )
            )
            pending[task] = op.part

    current_wave = 0
    start_wave(0)
    wave_start = loop.time()
    try:
        while not plan.is_reading_finished(available):
            if not pending:
                # everything in flight resolved; fire the next wave now
                if current_wave >= max_wave:
                    raise ReadError(
                        f"no more parts to try (available={available}, "
                        f"unreadable={unreadable})"
                    )
                current_wave += 1
                start_wave(current_wave)
                wave_start = loop.time()
                continue
            now = loop.time()
            if now >= deadline:
                raise ReadError("read plan timed out")
            if current_wave < max_wave:
                timeout = min(wave_start + wave_timeout - now, deadline - now)
            else:
                timeout = deadline - now
            done, _ = await asyncio.wait(
                pending.keys(),
                timeout=max(timeout, 0.001),
                return_when=asyncio.FIRST_COMPLETED,
            )
            for task in done:
                part = pending.pop(task)
                exc = task.exception()
                if exc is None:
                    available.append(part)
                else:
                    log.debug("part %d failed: %s", part, exc)
                    if on_part_failure is not None and part in locations:
                        addr, wire_part_id = locations[part]
                        try:
                            on_part_failure(part, wire_part_id, addr, exc)
                        except Exception:  # noqa: BLE001
                            log.debug("part-failure observer failed",
                                      exc_info=True)
                    unreadable.append(part)
                    if not plan.is_finishing_possible(unreadable):
                        raise ReadError(f"too many failed parts: {unreadable}")
            # wave timeout: stragglers trigger the next wave (reference
            # startReadsForWave, read_plan_executor.cc:162-176)
            if (
                current_wave < max_wave
                and loop.time() - wave_start >= wave_timeout
            ):
                current_wave += 1
                start_wave(current_wave)
                wave_start = loop.time()
    finally:
        for task in pending:
            task.cancel()
        if pending:
            await asyncio.gather(*pending.keys(), return_exceptions=True)

    # postprocess is the decode leg: parity recovery / block CRC checks
    # for striped plans (a plain pass-through for healthy std reads)
    t0 = tracing.phase_t0()
    result = plan.postprocess(buffer, available)
    tracing.charge_phase("decode", t0)
    return result
