"""ctypes bindings for the native C++ EC kernels (native/libec_native.so).

Provides ``CppChunkEncoder`` — the ISA-L-class CPU backend: same bytes
as the golden numpy path, SIMD speed. Used as the default chunkserver/
client encoder when present and as the honest CPU baseline in bench.py.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.core.encoder import ChunkEncoder
from lizardfs_tpu.ops import gf256

_LIB_PATHS = tuple(
    p for p in (
        # LZ_NATIVE_SO: load an alternate build (the ASAN/TSAN targets
        # in native/Makefile) without touching the production .so
        os.environ.get("LZ_NATIVE_SO", ""),
        os.path.join(
            os.path.dirname(__file__), "..", "..", "native",
            "libec_native.so",
        ),
        "libec_native.so",
    ) if p
)


def _load() -> ctypes.CDLL | None:
    for path in _LIB_PATHS:
        try:
            lib = ctypes.CDLL(os.path.abspath(path) if os.sep in path else path)
        except OSError:
            continue
        lib.lz_ec_encode.argtypes = [
            ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.lz_ec_encode.restype = None
        lib.lz_crc32.argtypes = [
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t
        ]
        lib.lz_crc32.restype = ctypes.c_uint32
        lib.lz_crc32_blocks.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.lz_crc32_blocks.restype = None
        try:
            lib.lz_stripe_scatter.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
                ctypes.c_uint32, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.lz_stripe_scatter.restype = None
            lib.lz_stripe_gather.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_uint32,
                ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.lz_stripe_gather.restype = None
        except AttributeError:
            pass  # stale .so without the stripe helpers: numpy fallback
        try:
            lib.lz_ec_encode_mt.argtypes = [
                ctypes.c_size_t, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_int,
            ]
            lib.lz_ec_encode_mt.restype = None
        except AttributeError:
            pass  # stale .so: single-threaded encode only
        return lib
    return None


_lib = _load()


def available() -> bool:
    return _lib is not None


def _ptr_array(arrays: list[np.ndarray]) -> ctypes.Array:
    ptrs = (ctypes.c_void_p * len(arrays))()
    for i, a in enumerate(arrays):
        ptrs[i] = a.ctypes.data_as(ctypes.c_void_p).value
    return ptrs


# worker threads for whole-chunk encodes (the C side stays single-
# threaded below 1 MiB, where spawn cost would dominate); bounded so
# encode never crowds out the network/serve thread pools
ENCODE_THREADS = max(1, min(4, (os.cpu_count() or 2) // 2))


def apply_matrix(
    matrix: np.ndarray, parts: list[np.ndarray], threads: int | None = None,
    out: list[np.ndarray] | None = None,
) -> list[np.ndarray]:
    """out[i] = XOR_j matrix[i,j] * parts[j] via the SIMD kernel.

    ``out``: optional caller-owned destination rows (each contiguous
    uint8 of the part size) — the kernel writes parity in place, so hot
    paths can encode straight into a send buffer."""
    assert _lib is not None
    rows, k = matrix.shape
    assert k == len(parts)
    size = parts[0].shape[0] if parts else 0
    if out is None:
        out = [np.empty(size, dtype=np.uint8) for _ in range(rows)]
    else:
        assert len(out) == rows and all(
            o.flags.c_contiguous and o.dtype == np.uint8
            and o.shape[0] == size
            for o in out
        )
    if size == 0 or rows == 0:
        return out
    mat = np.ascontiguousarray(matrix, dtype=np.uint8)
    srcs = [np.ascontiguousarray(p, dtype=np.uint8) for p in parts]
    nthreads = ENCODE_THREADS if threads is None else threads
    if nthreads > 1 and hasattr(_lib, "lz_ec_encode_mt"):
        _lib.lz_ec_encode_mt(
            size, k, rows,
            mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            _ptr_array(srcs),
            _ptr_array(out),
            nthreads,
        )
        return out
    _lib.lz_ec_encode(
        size, k, rows,
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        _ptr_array(srcs),
        _ptr_array(out),
    )
    return out


def crc32(data: bytes | np.ndarray, crc: int = 0) -> int:
    assert _lib is not None
    arr = np.frombuffer(bytes(data), dtype=np.uint8) if isinstance(data, (bytes, bytearray)) else np.ascontiguousarray(data, dtype=np.uint8)
    return int(
        _lib.lz_crc32(
            crc, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), arr.size
        )
    )


def stripe_helpers_available() -> bool:
    return _lib is not None and hasattr(_lib, "lz_stripe_scatter")


def stripe_scatter(
    data: np.ndarray, d: int, blocks_per_part: int,
    out: np.ndarray | None = None,
) -> np.ndarray:
    """(nbytes,) chunk bytes -> (d, part_len) zero-padded part streams
    in one contiguous buffer, via the GIL-free native kernel. ``out``
    lets hot paths reuse a staging buffer (a fresh 64 MiB allocation
    pays its page faults inside the copy)."""
    assert stripe_helpers_available()
    part_len = blocks_per_part * MFSBLOCKSIZE
    if out is None:
        out = np.empty((d, part_len), dtype=np.uint8)
    assert (
        out.flags.c_contiguous and out.dtype == np.uint8
        and out.shape == (d, part_len)
    )
    data = np.ascontiguousarray(data, dtype=np.uint8)
    _lib.lz_stripe_scatter(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        data.shape[0], d, blocks_per_part,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def stripe_gather(
    parts: list[np.ndarray], nbytes: int, out: np.ndarray | None = None
) -> np.ndarray:
    """d part streams (each contiguous, long enough to cover its share
    of ``nbytes``) -> (nbytes,) chunk bytes, no intermediate stacking."""
    assert stripe_helpers_available()
    srcs = [np.ascontiguousarray(p, dtype=np.uint8) for p in parts]
    if out is None:
        out = np.empty(nbytes, dtype=np.uint8)
    assert out.flags.c_contiguous and out.shape[0] >= nbytes
    _lib.lz_stripe_gather(
        _ptr_array(srcs), len(srcs), nbytes,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def crc32_blocks(blocks: np.ndarray) -> np.ndarray:
    assert _lib is not None
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    n, bs = blocks.shape
    out = np.empty(n, dtype=np.uint32)
    _lib.lz_crc32_blocks(
        blocks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, bs, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


class CppChunkEncoder(ChunkEncoder):
    """SIMD C++ backend (ISA-L-equivalent technique), byte-identical to
    the golden path."""

    name = "cpp"

    def __init__(self):
        if _lib is None:
            raise RuntimeError(
                "libec_native.so not built — run `make -C native`"
            )

    def encode(self, k, m, data_parts):
        if len(data_parts) != k:
            raise ValueError(f"expected {k} data parts, got {len(data_parts)}")
        nonzero = [i for i, p in enumerate(data_parts) if p is not None]
        if not nonzero:
            raise ValueError("at least one data part must be non-None")
        mat = gf256.encoding_matrix(k, m)
        mat = gf256.reduce_columns(mat, nonzero)
        parts = [np.asarray(data_parts[i], dtype=np.uint8) for i in nonzero]
        return apply_matrix(mat, parts)

    def encode_into(self, k, m, data_parts, out):
        if len(data_parts) != k:
            raise ValueError(f"expected {k} data parts, got {len(data_parts)}")
        mat = gf256.encoding_matrix(k, m)
        parts = [np.asarray(p, dtype=np.uint8) for p in data_parts]
        apply_matrix(mat, parts, out=list(out))

    def recover(self, k, m, parts, wanted):
        used, mat = gf256.recovery_selection(k, m, list(parts.keys()), wanted)
        nonzero_pos = [j for j, i in enumerate(used) if parts[i] is not None]
        if not nonzero_pos:
            raise ValueError("at least one available part must be non-None")
        mat = gf256.reduce_columns(mat, nonzero_pos)
        in_parts = [np.asarray(parts[used[j]], dtype=np.uint8) for j in nonzero_pos]
        out = apply_matrix(mat, in_parts)
        return {w: out[i] for i, w in enumerate(wanted)}

    def checksum(self, blocks):
        return crc32_blocks(np.ascontiguousarray(blocks))

    def encode_with_checksums(self, k, m, data, block_size=MFSBLOCKSIZE):
        n = data.shape[1]
        nb = n // block_size
        parity = np.stack(self.encode(k, m, list(data)))
        data_crcs = self.checksum(data.reshape(k * nb, block_size)).reshape(k, nb)
        parity_crcs = self.checksum(parity.reshape(m * nb, block_size)).reshape(m, nb)
        return parity, data_crcs, parity_crcs
