"""Native bulk-IO bindings: whole data-plane exchanges in C++.

The asyncio stack stays in charge of control flow, plans, and retries;
when a read or write moves enough bytes, the piece loop (framing, CRC,
scatter) runs in ``native/io_native.cpp`` over a blocking socket from a
worker thread, with the GIL released. This is the native runtime layer
for the data path — the Python per-piece path remains as the portable
fallback and handles small requests where thread hop latency would
dominate.
"""

from __future__ import annotations

import asyncio
import ctypes
import functools
import os
import socket
import struct
import threading
import weakref
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from lizardfs_tpu.core import native as _native_lib
from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import accounting

# exchanges smaller than this stay on the asyncio path
NATIVE_READ_THRESHOLD = 128 * 1024
NATIVE_WRITE_THRESHOLD = 128 * 1024

_lib = _native_lib._load()
if _lib is not None:
    try:
        _lib.lz_read_part.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
        ]
        _lib.lz_read_part.restype = ctypes.c_int
        _lib.lz_read_part_bulk.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
        ]
        _lib.lz_read_part_bulk.restype = ctypes.c_int
        _lib.lz_write_part.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
        ]
        _lib.lz_write_part.restype = ctypes.c_int
        _lib.lz_write_part_bulk.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
        ]
        _lib.lz_write_part_bulk.restype = ctypes.c_int
        _lib.lz_load_read.argtypes = [
            ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint32),
        ]
        _lib.lz_load_read.restype = ctypes.c_int
        _lib.lz_stream_read.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint32,
        ]
        _lib.lz_stream_read.restype = ctypes.c_int
        try:
            _lib.lz_read_parts_gather.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_uint32,
            ]
            _lib.lz_read_parts_gather.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: the whole-stripe fast path stays off
        try:
            _lib.lz_write_parts_scatter.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_uint32,
            ]
            _lib.lz_write_parts_scatter.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: multi-part write fast path stays off
        try:
            _lib.lz_trace_set.argtypes = [ctypes.c_uint64]
            _lib.lz_trace_set.restype = None
        except AttributeError:
            pass  # stale .so: native requests stay untraced
        try:
            _lib.lz_session_set.argtypes = [ctypes.c_uint64]
            _lib.lz_session_set.restype = None
        except AttributeError:
            pass  # stale .so: native requests stay session-less
        try:
            _lib.lz_write_parts_scatterv.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
            ]
            _lib.lz_write_parts_scatterv.restype = ctypes.c_int
            _lib.lz_write_collect_acks.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
            ]
            _lib.lz_write_collect_acks.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: the windowed/vectored write path stays off
        try:
            _lib.lz_shm_write_descs.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
            ]
            _lib.lz_shm_write_descs.restype = ctypes.c_int
        except AttributeError:
            pass  # stale .so: the shm-ring send path stays off
    except AttributeError:
        _lib = None


def available() -> bool:
    return _lib is not None


class NativeIOError(Exception):
    def __init__(self, code: int, what: str):
        self.code = code
        names = {-1: "socket error", -2: "protocol violation", -3: "CRC mismatch"}
        msg = names.get(code, f"status {st.name(code) if code > 0 else code}")
        super().__init__(f"native {what}: {msg}")


class _SocketPool:
    """Thread-safe pool of blocking sockets keyed by address."""

    def __init__(self, max_idle: int = 4):
        self.max_idle = max_idle
        self._lock = threading.Lock()
        self._idle: dict[tuple[str, int], list[socket.socket]] = {}

    def acquire(self, addr: tuple[str, int]) -> socket.socket:
        with self._lock:
            bucket = self._idle.get(addr)
            if bucket:
                return bucket.pop()
        return _blocking_socket(addr, 30.0)

    def try_acquire(self, addr: tuple[str, int]):
        """Pop an idle socket or return None — never dials."""
        with self._lock:
            bucket = self._idle.get(addr)
            if bucket:
                return bucket.pop()
        return None

    def release(self, addr: tuple[str, int], sock: socket.socket) -> None:
        with self._lock:
            bucket = self._idle.setdefault(addr, [])
            if len(bucket) < self.max_idle:
                bucket.append(sock)
                return
        shm_ring_drop(sock)
        sock.close()

    def discard(self, sock: socket.socket) -> None:
        shm_ring_drop(sock)
        sock.close()


# --- same-host shared-memory part rings (native/shm_ring.h) ----------------
#
# One memfd payload segment per data-plane connection, negotiated over
# the abstract-UDS fast path via a CltocsShmInit frame carrying the fd
# as SCM_RIGHTS (the SO_PEERCRED gate already vetted the peer).  After
# the handshake, encoded parts land straight in the mapped arena and
# "sending" a part is one tiny CltocsShmWritePart descriptor frame —
# the per-byte socket copy is gone.  The CLIENT owns allocation: a
# classic FIFO ring (regions freed in ack-collection order), so the
# server only ever reads ranges named by descriptors.
#
# Rings ride the pooled socket they were negotiated on (keyed weakly by
# the socket object), so back-to-back chunk writes reuse one segment
# instead of re-negotiating per chunk.  LZ_SHM_RING=0 kills the whole
# path; LZ_SHM_RING_MB sizes segments (default 16).

SHM_MEMFD_NAME = "lzshm"  # grep-able in /proc/<pid>/maps (leak tests)


def shm_ring_enabled() -> bool:
    from lizardfs_tpu.constants import env_flag

    return env_flag("LZ_SHM_RING")


def uds_disabled() -> bool:
    """LZ_NO_UDS operational kill switch for the same-host UDS fast
    path (default: UDS stays on). Four-spelling parity like every
    other switch — LZ_NO_UDS=0/off/false/no means "not disabled"; the
    old bare-truthiness read treated ``0`` as set-and-therefore-kill
    (spelling-parity inversion, now linted away). wire.h uds_enabled()
    mirrors these spellings C-side."""
    from lizardfs_tpu.constants import env_flag

    return env_flag("LZ_NO_UDS", default=False)


def shm_seg_bytes() -> int:
    from lizardfs_tpu.constants import MFSBLOCKSIZE

    try:
        mb = float(os.environ.get("LZ_SHM_RING_MB", "16"))
    except ValueError:
        mb = 16.0
    nbytes = int(mb * 2**20)
    nbytes = max(MFSBLOCKSIZE, min(nbytes, 1 << 30))
    return (nbytes // MFSBLOCKSIZE) * MFSBLOCKSIZE


def parts_shm_available() -> bool:
    """Shm-ring descriptor sends: the windowed path's copy-free rung."""
    return (
        _lib is not None
        and hasattr(_lib, "lz_shm_write_descs")
        and hasattr(_lib, "lz_write_collect_acks")
        and hasattr(os, "memfd_create")
    )


class ShmRing:
    """Client side of one connection's memfd payload ring.

    A FIFO bump allocator over a raw arena: :meth:`alloc` hands out
    contiguous regions (wrapping past the end wastes the tail, charged
    to the allocation that wrapped), :meth:`free` returns the oldest
    allocation's cost.  Correct because frees happen strictly in alloc
    order — acks are FIFO per connection and the windowed client
    collects segments oldest-first."""

    def __init__(self, size: int):
        import mmap as _mmap

        self.size = size
        self.memfd = os.memfd_create(SHM_MEMFD_NAME, 0)
        try:
            os.ftruncate(self.memfd, size)
            self.mm = _mmap.mmap(self.memfd, size)
        except BaseException:
            os.close(self.memfd)
            raise
        try:
            # forked children (the master's image-dump fork being the
            # in-process-cluster case) have no use for the arena, and
            # copying PTEs for every touched ring page would tax every
            # fork the process makes — exclude the mapping outright
            self.mm.madvise(_mmap.MADV_DONTFORK)
        except (AttributeError, OSError):
            pass  # pre-3.8 mmap or exotic kernel: fork just pays PTEs
        self.arr = np.frombuffer(self.mm, dtype=np.uint8)
        self._head = 0
        self._used = 0
        self._closed = False

    def alloc(self, nbytes: int):
        """-> (offset, cost) or None when the ring cannot fit it."""
        if nbytes <= 0 or nbytes > self.size:
            return None
        pad = 0
        if self._head + nbytes > self.size:
            pad = self.size - self._head  # wasted tail, freed with us
        if self._used + pad + nbytes > self.size:
            return None
        off = 0 if pad else self._head
        self._head = (off + nbytes) % self.size
        self._used += pad + nbytes
        return off, pad + nbytes

    def free(self, cost: int) -> None:
        self._used -= cost

    def unalloc(self, off: int, cost: int, nbytes: int) -> None:
        """LIFO undo of the NEWEST allocation (staging rollback).

        ``free`` retires the OLDEST allocation — using it to roll back
        the newest would advance the implied tail instead of retracting
        the head, leaving a hole the accounting no longer covers, and a
        later alloc could hand out a region overlapping a sent-but-
        unacked segment's live bytes.  Undo restores the exact
        pre-alloc head: ``cost - nbytes`` is the wrap pad the
        allocation charged, so the head it advanced from is
        ``off - pad`` (mod size)."""
        self._head = (off - (cost - nbytes)) % self.size
        self._used -= cost

    def view(self, off: int, nbytes: int) -> np.ndarray:
        return self.arr[off : off + nbytes]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.arr = None
        try:
            self.mm.close()
        except BufferError:
            # a caller still holds an arena view; the mapping is freed
            # when the last view dies (the memfd below is closed now, so
            # nothing else can map it)
            pass
        try:
            os.close(self.memfd)
        except OSError:
            pass

    def __del__(self):  # noqa: D105 — last-resort fd hygiene
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


# ring negotiated on a socket, surviving pool round trips (a pooled
# connection keeps its server-side mapping, so the next session skips
# the handshake); entries die with the socket object
_SOCK_RINGS: "weakref.WeakKeyDictionary[socket.socket, ShmRing]" = (
    weakref.WeakKeyDictionary()
)


def shm_ring_of(sock: socket.socket) -> "ShmRing | None":
    return _SOCK_RINGS.get(sock)


def shm_ring_drop(sock) -> None:
    """Release a socket's ring (called wherever the socket leaves the
    reuse cycle — pool discard/overflow, session close)."""
    ring = _SOCK_RINGS.pop(sock, None)
    if ring is not None:
        ring.close()


def shm_ring_capable(sock: socket.socket) -> bool:
    """Is this a same-host data connection a ring may ride?  Abstract-
    UDS connections qualify outright (SO_PEERCRED gate).  Loopback TCP
    also qualifies: pure-Python chunkservers have no UDS listener, so
    their demux's only reachable transport is 127.0.0.1 — the server
    still enforces the same-uid gate through its /proc/<pid>/fd open,
    and a native server just refuses ShmInit on TCP (the connection
    stays on the socket-copy path)."""
    if sock.family == socket.AF_UNIX:
        return True
    try:
        peer = sock.getpeername()
    except OSError:
        return False
    return (
        isinstance(peer, tuple)
        and bool(peer)
        and peer[0] in ("127.0.0.1", "::1")
    )


def shm_ring_handshake(sock: socket.socket) -> "ShmRing | None":
    """Negotiate (or reuse) a ring on a same-host data connection.

    On a unix socket the memfd rides the CltocsShmInit frame as
    SCM_RIGHTS ancillary data; on loopback TCP (asyncio chunkserver)
    the frame goes bare and the server maps /proc/<pid>/fd/<n>
    instead.  Any refusal leaves the connection on the socket-copy
    path. Raises on socket errors (a server that predates the frame
    closes the connection — the caller treats that like any other
    failed exchange)."""
    ring = _SOCK_RINGS.get(sock)
    if ring is not None:
        return ring
    size = shm_seg_bytes()
    ring = ShmRing(size)
    try:
        frame = framing.encode(m.CltocsShmInit(
            req_id=1, pid=os.getpid(), mem_fd=ring.memfd, seg_size=size,
        ))
        if sock.family == socket.AF_UNIX:
            sock.sendmsg(
                [frame],
                [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                  struct.pack("i", ring.memfd))],
            )
        else:
            sock.sendall(frame)
        reply = _recv_message(sock)
    except BaseException:
        ring.close()
        raise
    if (
        not isinstance(reply, m.CstoclWriteStatus)
        or reply.status != st.OK
    ):
        ring.close()
        return None
    _SOCK_RINGS[sock] = ring
    return ring


POOL = _SocketPool()

# Connections that negotiated a shm ring are pooled SEPARATELY: their
# server side lives on the epoll proactor, which serves the write-
# session protocol (descriptors + bulk frames + init/end) but not the
# read plane — reads and legacy per-part writes must keep drawing from
# the plain POOL so they never land on a proactor-owned connection.
RING_POOL = _SocketPool()

# observability + contract pin: how many data-plane connections took the
# same-host unix-socket fast path (tests assert this moves, so a silent
# name-format drift between here and native/wire.h fails loudly)
UDS_CONNECTS = 0
_UDS_COUNT_LOCK = threading.Lock()  # incremented from executor threads

# Dedicated executor: native IO calls block for a full network exchange.
# Sharing asyncio's default to_thread pool would let a burst of bulk
# transfers starve unrelated to_thread work (e.g. an in-process
# chunkserver's disk jobs — whose acks these very calls wait on).
EXECUTOR = ThreadPoolExecutor(max_workers=32, thread_name_prefix="native-io")

# Server-side serving gets its own pool: in-process clusters (tests,
# benches) have client exchanges above PARKED in EXECUTOR threads
# waiting on the very responses these serve calls produce — sharing one
# pool would deadlock at saturation.
SERVE_EXECUTOR = ThreadPoolExecutor(
    max_workers=16, thread_name_prefix="native-serve"
)


_prestarted = False


def prestart_executors() -> None:
    """Spawn every pool thread up front. ThreadPoolExecutor creates
    threads lazily inside submit(), and Thread.start() BLOCKS until the
    new thread's bootstrap runs — under GIL pressure (busy encode/IO
    threads) that wait was measured at 150-600 ms ON THE EVENT LOOP
    during EC write fan-out. Pre-started threads make submit() a pure
    enqueue.

    Runs once per process, at the FIRST daemon/client startup (while
    the pools are quiet — parking tasks in an already-busy shared pool
    would queue behind live work and head-of-line-block it); later
    callers no-op. The spawn/join phase itself runs on a helper daemon
    thread: Thread.start() × 48 workers can take seconds on a loaded
    single-core box, and the caller is usually ON the event loop
    (connect/failover) — the very stall this function exists to avoid."""
    global _prestarted
    if _prestarted:
        return
    _prestarted = True
    import threading

    threading.Thread(
        target=_prestart_blocking, name="lz-prestart", daemon=True
    ).start()


def _prestart_blocking() -> None:
    import threading

    for pool in (EXECUTOR, SERVE_EXECUTOR):
        # park one task per worker: a parked thread is not idle, so
        # every submit() spawns a fresh thread until the pool is full
        release = threading.Event()
        started = threading.Semaphore(0)

        def _parked(started=started, release=release):
            started.release()
            release.wait(10.0)

        try:
            futs = [
                pool.submit(_parked)
                for _ in range(pool._max_workers)  # noqa: SLF001
            ]
        except RuntimeError:
            continue  # pool already shut down
        deadline_ok = all(started.acquire(timeout=2.0) for _ in futs)
        release.set()
        if not deadline_ok:
            # partial spawn (loaded box): fine — whatever started stays
            return
# native serves in flight above this fall back to the asyncio path, so
# stalled slow-draining clients (which may legally pin a serve thread
# until their deadline) cannot head-of-line-block healthy readers. The
# counter is process-global like the executor it guards (an in-process
# cluster runs several chunkservers on one pool).
SERVE_CONCURRENCY_LIMIT = 12
active_serves = 0


def serve_slot_available() -> bool:
    return active_serves < SERVE_CONCURRENCY_LIMIT


def serve_slot_acquire() -> None:
    global active_serves
    active_serves += 1


def serve_slot_release() -> None:
    global active_serves
    active_serves -= 1


async def run(fn, *args):
    """Run a blocking native-IO function on the dedicated executor.

    The caller's request trace id (runtime/tracing.py contextvar) is
    captured HERE — run_in_executor does not carry context into the
    worker thread — and installed as the C side's thread-local
    (lz_trace_set) for the duration of the call, so the native request
    builders tag their frames with the trace of the request they serve."""
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(EXECUTOR, partial_with_trace(fn, *args))


def partial_with_trace(fn, *args):
    """``functools.partial`` carrying the caller's trace id AND wire
    session into the executor thread — for call sites that need raw
    run_in_executor (shield/abort-cell patterns) instead of
    :func:`run`. Both are captured HERE, in the calling task, because
    neither contextvars nor the task's session scope reach an executor
    thread."""
    from lizardfs_tpu.runtime import tracing

    trace_id = tracing.current_trace_id()
    if trace_id:
        return functools.partial(
            _traced_call, trace_id, accounting.wire_session(), fn, *args
        )
    return functools.partial(fn, *args)


# worker-thread trace id: read by the python-framed handshakes
# (_send_write_init) the same way the C builders read lz_trace_set
_TRACE_TL = threading.local()


def _thread_trace_id() -> int:
    return getattr(_TRACE_TL, "trace_id", 0)


def _traced_call(trace_id, session_id, fn, *args):
    _TRACE_TL.trace_id = trace_id
    has_c = _lib is not None and hasattr(_lib, "lz_trace_set")
    # the caller's session rides next to the trace (per-session op
    # accounting on the chunkserver); a stale .so simply lacks the
    # setter and frames stay session-less
    has_sess = _lib is not None and hasattr(_lib, "lz_session_set")
    if has_c:
        _lib.lz_trace_set(trace_id)
    if has_sess:
        _lib.lz_session_set(session_id)
    try:
        return fn(*args)
    finally:
        # pooled executor threads serve many requests — never leak a
        # trace id into the next one
        _TRACE_TL.trace_id = 0
        if has_c:
            _lib.lz_trace_set(0)
        if has_sess:
            _lib.lz_session_set(0)


async def run_serve(fn, *args):
    """Run a blocking server-side serve function on its own executor."""
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        SERVE_EXECUTOR, functools.partial(fn, *args)
    )


def _blocking_socket(addr: tuple[str, int], io_timeout: float) -> socket.socket:
    """Connect and return a socket whose fd is BLOCKING (a Python-level
    timeout makes the fd non-blocking, which breaks the C send/recv
    loops); IO deadlines are enforced by the kernel via SO_*TIMEO.

    Same-host addresses first try the data plane's abstract unix
    listener (``\\0lzfs-data-<advertised-host>-<port>``, bound by
    lz_serve_start — KEEP IN SYNC with serve_native.cpp
    uds_data_addr; the contract is pinned by
    test_fast_paths.py::test_uds_fast_path_engages): ~2.5x less
    per-byte CPU than loopback TCP on the measured boxes. The name
    embeds the host STRING the server advertised, so a port forward to
    a remote server never aliases to a local listener. Absent listener
    (asyncio data plane, remote host, LZ_NO_UDS set) falls back to TCP
    transparently."""
    global UDS_CONNECTS
    sock = None
    if (
        addr[0] in ("127.0.0.1", "localhost")  # exactly wire.h uds_host()
        # operational kill-switch, default off; env_flag gives it the
        # four-spelling parity the bare truthiness read lacked
        # (LZ_NO_UDS=0 used to DISABLE the fast path)
        and not uds_disabled()
    ):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.settimeout(5.0)
            s.connect(f"\0lzfs-data-{addr[0]}-{addr[1]}")
            # abstract names bypass filesystem permissions: verify the
            # peer is OUR uid (or root) via SO_PEERCRED before trusting
            # it with chunk data — anything else could be an impostor
            # that bound the name first
            pid_uid_gid = s.getsockopt(
                socket.SOL_SOCKET, socket.SO_PEERCRED, struct.calcsize("3i")
            )
            _pid, uid, _gid = struct.unpack("3i", pid_uid_gid)
            if uid not in (os.geteuid(), 0):
                raise OSError("unix listener owned by another uid")
            s.settimeout(None)
            sock = s
            with _UDS_COUNT_LOCK:
                UDS_CONNECTS += 1
        except OSError:
            s.close()
    if sock is None:
        sock = socket.create_connection(addr, timeout=30.0)
        sock.settimeout(None)  # back to a blocking fd
    tv = struct.pack("ll", int(io_timeout), 0)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVTIMEO, tv)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
    if sock.family != socket.AF_UNIX:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    # deep buffers cut syscall/context-switch count for bulk streams
    for opt in (socket.SO_RCVBUF, socket.SO_SNDBUF):
        try:
            sock.setsockopt(socket.SOL_SOCKET, opt, 4 * 1024 * 1024)
        except OSError:
            pass
    return sock


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        piece = sock.recv(n - len(out))
        if not piece:
            raise ConnectionError("peer closed")
        out += piece
    return bytes(out)


def _recv_message(sock: socket.socket):
    header = _recv_exact(sock, 8)
    msg_type, length = struct.unpack(">II", header)
    payload = _recv_exact(sock, length)
    return framing.decode(msg_type, payload)


def abort_read(cell: dict) -> None:
    """Kill an in-flight read_part_blocking from another thread: the
    executor thread is uninterruptible inside the C exchange, but a
    socket shutdown makes its recv fail immediately. Used before
    retrying a read whose thread may still be scattering into a shared
    destination buffer."""
    cell["aborted"] = True
    sock = cell.get("sock")
    if sock is not None:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass


def read_part_blocking(
    addr: tuple[str, int],
    chunk_id: int,
    version: int,
    part_id: int,
    offset: int,
    size: int,
    out: np.ndarray,
    cell: dict | None = None,
) -> None:
    """Fill ``out[:size]`` with the requested range (called via
    asyncio.to_thread). Retries once on a stale pooled socket.

    Block-aligned requests use the bulk exchange (one reply frame,
    receiver-verified CRCs, server sendfile) — the fast path; unaligned
    ones fall back to the per-piece protocol.  ``cell`` (optional dict)
    publishes the live socket so abort_read() can cancel the exchange."""
    from lizardfs_tpu.constants import MFSBLOCKSIZE

    assert out.flags.c_contiguous and out.nbytes >= size
    ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    fn = (_lib.lz_read_part_bulk if offset % MFSBLOCKSIZE == 0
          else _lib.lz_read_part)
    for attempt in (0, 1):
        # second attempt dials fresh: the pool may hold several sockets
        # staled by the same server restart
        sock = POOL.acquire(addr) if attempt == 0 else _blocking_socket(addr, 30.0)
        if cell is not None:
            cell["sock"] = sock
            if cell.get("aborted"):
                POOL.discard(sock)
                raise NativeIOError(-1, "read (aborted)")
        rc = fn(
            sock.fileno(), chunk_id, version, part_id, offset, size, ptr
        )
        if cell is not None:
            cell.pop("sock", None)
        if rc == 0:
            POOL.release(addr, sock)
            return
        POOL.discard(sock)
        if rc == -1 and attempt == 0 and not (cell or {}).get("aborted"):
            continue  # stale pooled socket: retry on a fresh connection
        raise NativeIOError(rc, "read")


def write_part_blocking(
    addr: tuple[str, int],
    chunk_id: int,
    version: int,
    part_id: int,
    chain: list,
    payload: bytes | np.ndarray,
    part_offset: int,
    cell: dict | None = None,
) -> None:
    """Full write exchange: WriteInit handshake (Python framing), bulk
    WriteData streaming + acks (native), WriteEnd handshake. ``cell``
    publishes the live socket so abort_write() can cancel the exchange
    (the executor thread is otherwise unkillable while it streams from
    the caller's buffer); ``cell["finished"]`` is set when this thread
    has stopped touching ``payload``."""
    sock = _blocking_socket(addr, 60.0)
    if cell is not None:
        cell["sock"] = sock
        if cell.get("aborted"):
            sock.close()
            cell["finished"] = True
            raise NativeIOError(-1, "write (aborted)")
    try:
        sock.sendall(
            framing.encode(
                m.CltocsWriteInit(
                    req_id=1, chunk_id=chunk_id, version=version,
                    part_id=part_id, chain=chain, create=False,
                    trace_id=_thread_trace_id(),
                    session_id=accounting.wire_session(),
                )
            )
        )
        init = _recv_message(sock)
        if not isinstance(init, m.CstoclWriteStatus) or init.status != st.OK:
            raise st.StatusError(getattr(init, "status", st.EIO), "write init")
        buf = (payload if isinstance(payload, np.ndarray)
               else np.frombuffer(payload, dtype=np.uint8))
        if not buf.flags.c_contiguous:
            buf = np.ascontiguousarray(buf)
        from lizardfs_tpu.constants import MFSBLOCKSIZE

        fn = (_lib.lz_write_part_bulk if part_offset % MFSBLOCKSIZE == 0
              else _lib.lz_write_part)
        rc = fn(
            sock.fileno(), chunk_id,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            len(buf), part_offset, 1,
        )
        if rc != 0:
            raise NativeIOError(rc, "write")
        sock.sendall(framing.encode(m.CltocsWriteEnd(req_id=0, chunk_id=chunk_id)))
        end = _recv_message(sock)
        if not isinstance(end, m.CstoclWriteStatus) or end.status != st.OK:
            raise st.StatusError(getattr(end, "status", st.EIO), "write end")
    finally:
        sock.close()
        if cell is not None:
            cell.pop("sock", None)
            cell["finished"] = True


def _n_pieces(offset: int, size: int) -> int:
    from lizardfs_tpu.constants import MFSBLOCKSIZE
    return (offset + size - 1) // MFSBLOCKSIZE - offset // MFSBLOCKSIZE + 1


def load_read_blocking(
    path: str, offset: int, size: int, data_len: int
) -> tuple[int, np.ndarray, np.ndarray]:
    """Server side, phase 1: load + CRC-verify one part range.

    Runs with the chunk-file lock held (caller's job). OSError from a
    vanished file propagates — the caller maps it to a status frame.
    Returns ``(status, data, piece_crcs)``.
    """
    buf = np.empty(size, dtype=np.uint8)
    crcs = np.empty(_n_pieces(offset, size), dtype=np.uint32)
    file_fd = os.open(path, os.O_RDONLY)
    try:
        rc = _lib.lz_load_read(
            file_fd, offset, size, data_len,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            crcs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
    finally:
        os.close(file_fd)
    return rc, buf, crcs


def stream_read_blocking(
    sock_fd: int,
    chunk_id: int,
    req_id: int,
    offset: int,
    size: int,
    data: np.ndarray,
    crcs: np.ndarray,
) -> int:
    """Server side, phase 2: stream loaded pieces on the asyncio socket.

    ``sock_fd`` is non-blocking — the C side polls on EAGAIN. The caller
    passes a dup'd fd and THIS function owns it: the connection task may
    be cancelled (and the transport's fd closed and reused) while this
    thread is still sending, so the thread must work on its own fd and
    close it here. The caller must have flushed the asyncio write buffer
    and be the only writer on the connection until this returns.
    Returns 0, or -1 if the socket died mid-stream.
    """
    # absolute deadline: 30 s of grace plus a 512 KiB/s floor rate, so a
    # stalled client cannot pin a serve thread indefinitely
    max_ms = 30_000 + size // 512
    try:
        return _lib.lz_stream_read(
            sock_fd, chunk_id, req_id, offset, size,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            crcs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), max_ms,
        )
    finally:
        os.close(sock_fd)


class _PartReq(ctypes.Structure):
    _fields_ = [
        ("fd", ctypes.c_int),
        ("chunk_id", ctypes.c_uint64),
        ("version", ctypes.c_uint32),
        ("part_id", ctypes.c_uint32),
        ("rc", ctypes.c_int32),
    ]


def parts_gather_available() -> bool:
    return _lib is not None and hasattr(_lib, "lz_read_parts_gather")


def read_parts_gather_blocking(
    addrs: list[tuple[str, int]],
    chunk_id: int,
    version: int,
    part_ids: list[int],
    offset: int,
    region_blocks: int,
    out: np.ndarray,
    cell: dict | None = None,
) -> None:
    """Read ``region_blocks`` 64 KiB chunk blocks spread over d data
    parts (all starting at part-local ``offset``) in ONE poll-driven
    native exchange, de-interleaving straight into ``out`` (block j of
    part i -> out[(j*d+i)*64Ki : ...]). The whole-chunk EC read fast
    path: one executor thread and one C call replace d of each. Raises
    NativeIOError with the first failing part's code; the caller falls
    back to the wave executor (which handles recovery)."""
    from lizardfs_tpu.constants import MFSBLOCKSIZE

    d = len(addrs)
    assert d == len(part_ids) and out.flags.c_contiguous
    assert out.nbytes >= region_blocks * MFSBLOCKSIZE
    # attempt 0 uses pooled sockets; a socket-level failure (-1) retries
    # once with fresh dials — the pool may hold connections staled by a
    # server restart (mirrors read_part_blocking's retry)
    for attempt in (0, 1):
        reqs = (_PartReq * d)()
        socks = []
        try:
            for i, addr in enumerate(addrs):
                s = (POOL.acquire(addr) if attempt == 0
                     else _blocking_socket(addr, 30.0))
                socks.append((addr, s))
                reqs[i].fd = s.fileno()
                reqs[i].chunk_id = chunk_id
                reqs[i].version = version
                reqs[i].part_id = part_ids[i]
                reqs[i].rc = 0
            if cell is not None:
                cell["socks"] = [s for _, s in socks]
                if cell.get("aborted"):
                    raise NativeIOError(-1, "parts gather (aborted)")
            rc = _lib.lz_read_parts_gather(
                ctypes.cast(reqs, ctypes.c_void_p), d, offset,
                region_blocks,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                120_000,
            )
            if cell is not None:
                cell.pop("socks", None)
            if rc == 0:
                for addr, s in socks:
                    POOL.release(addr, s)
                socks.clear()
                return
            bad = next((int(r.rc) for r in reqs if r.rc != 0), -1)
            if (
                attempt == 0 and bad == -1
                and not (cell is not None and cell.get("aborted"))
            ):
                continue  # stale pooled socket: redial everything once
            raise NativeIOError(bad, "parts gather")
        finally:
            for _, s in socks:
                POOL.discard(s)


def abort_parts_gather(cell: dict) -> None:
    """Kill an in-flight read_parts_gather_blocking from another thread
    (socket shutdowns make its recvs fail immediately)."""
    cell["aborted"] = True
    for sock in cell.get("socks", ()):
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
    sock = cell.get("sock")
    if sock is not None:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass


# write-side aborts use the same cell shape ("sock"/"socks" + "aborted");
# a cancelled write task must kill its executor thread's exchange before
# the staging buffer the thread streams from can be reused
abort_write = abort_parts_gather
abort_parts_scatter = abort_parts_gather


def parts_scatter_available() -> bool:
    return _lib is not None and hasattr(_lib, "lz_write_parts_scatter")


def parts_scatterv_available() -> bool:
    """Vectored + windowed scatter writes (lz_write_parts_scatterv /
    lz_write_collect_acks): required by the adaptive write window."""
    return (
        _lib is not None
        and hasattr(_lib, "lz_write_parts_scatterv")
        and hasattr(_lib, "lz_write_collect_acks")
    )


# lz_write_parts_scatterv flags (keep in sync with io_native.cpp)
SCATTER_NO_ACK = 1


# shared building blocks of the two scatter-write paths (the one-shot
# write_parts_scatter_blocking and the multi-segment PartsScatterSession):
# a protocol change lands in exactly one place


def _send_write_init(sock: socket.socket, chunk_id: int, version: int,
                     part_id: int) -> None:
    sock.sendall(framing.encode(m.CltocsWriteInit(
        req_id=1, chunk_id=chunk_id, version=version,
        part_id=part_id, chain=[], create=False,
        trace_id=_thread_trace_id(),
        session_id=accounting.wire_session(),
    )))


def _recv_write_init_acks(socks: list[socket.socket]) -> None:
    """Collect one WriteInit ack per socket (inits were sent for ALL
    sockets first — serialized request/response would pay n round
    trips instead of ~1); raises NativeIOError on a refusal."""
    for s in socks:
        init = _recv_message(s)
        if not isinstance(init, m.CstoclWriteStatus) or init.status != st.OK:
            raise NativeIOError(getattr(init, "status", -2), "write init")


def _marshal_part_reqs(
    fds: list[int], chunk_id: int, write_id: int, part_ids: list[int],
    payloads: list[np.ndarray], lengths: list[int],
):
    """-> (reqs, ptrs, lens) ctypes arrays for lz_write_parts_scatter.
    The req's ``version`` slot carries the bulk frame's write_id."""
    n = len(fds)
    reqs = (_PartReq * n)()
    ptrs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    for i in range(n):
        buf = payloads[i]
        assert buf.flags.c_contiguous and buf.nbytes >= lengths[i]
        reqs[i].fd = fds[i]
        reqs[i].chunk_id = chunk_id
        reqs[i].version = write_id
        reqs[i].part_id = part_ids[i]
        reqs[i].rc = 0
        ptrs[i] = buf.ctypes.data_as(ctypes.c_void_p).value
        lens[i] = lengths[i]
    return reqs, ptrs, lens


def _write_end_handshake(socks: list[socket.socket], chunk_id: int) -> None:
    for s in socks:
        s.sendall(framing.encode(
            m.CltocsWriteEnd(req_id=0, chunk_id=chunk_id)
        ))
    for s in socks:
        end = _recv_message(s)
        if not isinstance(end, m.CstoclWriteStatus) or end.status != st.OK:
            raise NativeIOError(getattr(end, "status", -2), "write end")


class PartsScatterSession:
    """Pipelined multi-segment part writes over persistent connections.

    The write-path building block of the client's double-buffered stripe
    pipeline: ``open()`` dials every part's holder once and runs the
    WriteInit handshakes; ``send_segment()`` streams one slot-aligned
    segment of every part (one poll-driven ``lz_write_parts_scatter``
    call: bulk frame + ack per part, per-block CRCs computed in C);
    ``finish()`` runs the WriteEnd handshakes. One handshake pair per
    part per *chunk* instead of per segment — the per-segment cost is
    only the bulk frames themselves, so encode(i+1) can overlap
    send(i) without paying n extra round trips per segment.

    Every method is blocking (call via :func:`run`). Any failure leaves
    the sockets closed and the exchange dead; the caller falls back to
    the serial write path (a full-part rewrite heals torn segments).
    ``cell`` follows the abort contract of write_parts_scatter_blocking:
    abort_write(cell) from another thread kills the exchange,
    ``cell["finished"]`` marks when no thread reads the payloads anymore.
    """

    def __init__(
        self,
        addrs: list[tuple[str, int]],
        chunk_id: int,
        version: int,
        part_ids: list[int],
        cell: dict | None = None,
        share_connections: bool = False,
    ):
        assert len(addrs) == len(part_ids)
        self.addrs = addrs
        self.chunk_id = chunk_id
        self.version = version
        self.part_ids = part_ids
        self.cell = cell if cell is not None else {}
        # share_connections: parts that target the same chunkserver
        # ride ONE connection (the windowed/vectored path demuxes them
        # with part-addressed 1215 frames server-side). The legacy
        # barrier path keeps one socket per part — its 1214 frames
        # carry no part id, so a shared connection cannot demux them.
        self.share = share_connections
        if share_connections:
            self.unique_addrs: list[tuple[str, int]] = []
            self._conn_of: list[int] = []
            index: dict[tuple[str, int], int] = {}
            for addr in addrs:
                if addr not in index:
                    index[addr] = len(self.unique_addrs)
                    self.unique_addrs.append(addr)
                self._conn_of.append(index[addr])
        else:
            self.unique_addrs = list(addrs)
            self._conn_of = list(range(len(addrs)))
        self._socks: list[socket.socket] = []
        # write_id -> live part indices of an unacked windowed segment
        self._pending: dict[int, list[int]] = {}
        # shm rings per connection (None = socket-copy path for that
        # conn) + staged ring regions per in-flight write_id:
        # write_id -> list of (part_index, conn_index, off, cost, view)
        self._rings: list[ShmRing | None] = []
        self._ring_staged: dict[int, list[tuple]] = {}
        # folded into Client.metrics by the owner after the chunk write
        self.ring_stats = {
            "segments_mapped": 0, "desc_parts": 0, "full_waits": 0,
            "fallbacks": 0,
        }

    def _sock_of(self, part_index: int) -> socket.socket:
        return self._socks[self._conn_of[part_index]]

    def _ring_eligible(self) -> bool:
        return self.share and shm_ring_enabled() and parts_shm_available()

    def open(self) -> None:
        self.cell["submitted"] = True
        ring_mode = self._ring_eligible()
        for attempt in (0, 1):
            try:
                for addr in self.unique_addrs:
                    # pooled sockets first (the write hot path dials
                    # d+m connections per chunk — churn that the pool
                    # exists to absorb); ring-negotiated connections
                    # live in their own pool (their server side is the
                    # proactor) and are only reused by ring-eligible
                    # sessions. A stale pooled connection (server
                    # restart) fails the init handshake and retries
                    # once with fresh dials, mirroring
                    # _write_parts_scatter
                    s = None
                    if attempt == 0 and ring_mode:
                        s = RING_POOL.try_acquire(addr)
                    if s is None:
                        s = (POOL.acquire(addr) if attempt == 0
                             else _blocking_socket(addr, 60.0))
                    self._socks.append(s)
                for i in range(len(self.part_ids)):
                    _send_write_init(
                        self._sock_of(i), self.chunk_id, self.version,
                        self.part_ids[i],
                    )
                self.cell["socks"] = list(self._socks)
                if self.cell.get("aborted"):
                    raise NativeIOError(-1, "scatter session (aborted)")
                # one ack per part, read from its connection in init
                # order (a connection answers its inits FIFO, so the
                # global part order is safe to follow)
                _recv_write_init_acks(
                    [self._sock_of(i) for i in range(len(self.part_ids))]
                )
                self._setup_rings()
                return
            except (ConnectionError, OSError, st.StatusError):
                for s in self._socks:
                    POOL.discard(s)
                self._socks.clear()
                self.cell.pop("socks", None)
                if attempt == 1 or self.cell.get("aborted"):
                    self.cell["finished"] = True
                    raise
            except BaseException:
                self.close()
                raise

    # --- shm-ring staging (native/shm_ring.h) -------------------------

    def _setup_rings(self) -> None:
        """Negotiate a memfd ring per shared connection where the
        same-host fast path applies. Only the windowed/shared mode uses
        rings (the legacy per-part barrier path keeps its wire shape);
        any per-connection failure just leaves that connection on the
        socket-copy path — never fails the session."""
        self._rings = [None] * len(self._socks)
        if not self._ring_eligible():
            return
        for ci, sock in enumerate(self._socks):
            if not shm_ring_capable(sock):
                continue  # same-host connections only
            try:
                had = shm_ring_of(sock) is not None
                ring = shm_ring_handshake(sock)
            except (ConnectionError, OSError):
                # a peer predating the frame kills the connection; the
                # session keeps running and the next exchange on the
                # dead socket fails into the ordinary fallback chain
                continue
            self._rings[ci] = ring
            if ring is not None and not had:
                self.ring_stats["segments_mapped"] += 1

    def ring_ready(self) -> bool:
        """True when EVERY connection negotiated a ring — segment
        staging is all-or-nothing so one encode pass targets one kind
        of memory (mixed ring/socket conns take the scatterv path)."""
        return bool(self._rings) and all(
            r is not None for r in self._rings
        )

    def ring_stage(self, write_id: int, lengths: list[int],
                   widths: list[int] | None = None):
        """Allocate this segment's per-part regions in the rings and
        return arena views to encode/copy into (None entries for parts
        skipped this segment), or None when any ring is full — the
        caller reaps acks (freeing regions) and retries, or falls back
        to the socket-copy send for this segment.

        ``widths[i]`` (>= ``lengths[i]``, default equal) sizes the
        allocation and the returned view: an encoder that produces the
        full padded segment width needs the whole region writable even
        when only the part's live ``lengths[i]`` bytes go on the wire
        (ragged tail segments)."""
        if not self.ring_ready():
            return None
        staged: list[tuple] = []
        views: list = [None] * len(self.part_ids)
        for i, length in enumerate(lengths):
            if length <= 0:
                continue
            width = max(length, widths[i]) if widths is not None else length
            ci = self._conn_of[i]
            ring = self._rings[ci]
            got = ring.alloc(width)
            if got is None:
                for _i, _ci, _off, cost, _v in reversed(staged):
                    self._rings[_ci].unalloc(_off, cost, _v.nbytes)
                self.ring_stats["full_waits"] += 1
                return None
            off, cost = got
            view = ring.view(off, width)
            staged.append((i, ci, off, cost, view))
            views[i] = view
        self._ring_staged[write_id] = staged
        return views

    def ring_unstage(self, write_id: int) -> None:
        """Roll back a staged-but-never-sent segment (encode failure).

        Valid because staging/sending are serialized per session, so a
        just-staged segment's regions are strictly the ring's newest —
        the LIFO precondition of :meth:`ShmRing.unalloc`."""
        for _i, ci, _off, cost, _v in reversed(
            self._ring_staged.pop(write_id, ())
        ):
            self._rings[ci].unalloc(_off, cost, _v.nbytes)

    def _ring_send_descs(self, staged, payloads, lengths, part_offset,
                         write_id):
        """Move + describe one staged segment: entries whose payload
        still lives outside the arena (data rows) get their one GIL-free
        memcpy in C; entries encoded straight into the arena (parity —
        payload IS the staged view) move zero bytes."""
        n = len(staged)
        reqs = (_PartReq * n)()
        srcs = (ctypes.c_void_p * n)()
        dsts = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        offs = (ctypes.c_uint64 * n)()
        for j, (i, _ci, off, _cost, view) in enumerate(staged):
            src = payloads[i]
            assert src.flags.c_contiguous and src.nbytes >= lengths[i]
            reqs[j].fd = self._sock_of(i).fileno()
            reqs[j].chunk_id = self.chunk_id
            reqs[j].version = write_id
            reqs[j].part_id = self.part_ids[i]
            reqs[j].rc = 0
            srcs[j] = src.ctypes.data_as(ctypes.c_void_p).value
            dsts[j] = view.ctypes.data_as(ctypes.c_void_p).value
            lens[j] = lengths[i]
            offs[j] = off
        rc = _lib.lz_shm_write_descs(
            ctypes.cast(reqs, ctypes.c_void_p), n, srcs, dsts, lens,
            offs, part_offset, 120_000, SCATTER_NO_ACK,
        )
        if rc != 0:
            bad = next((int(r.rc) for r in reqs if r.rc != 0), -1)
            raise NativeIOError(bad, "shm descriptor send")
        self.ring_stats["desc_parts"] += n

    def send_segment(
        self,
        payloads: list[np.ndarray],
        lengths: list[int],
        part_offset: int,
        write_id: int,
    ) -> None:
        """Stream ``payloads[i][:lengths[i]]`` at ``part_offset`` within
        every live part and wait for every ack (the barrier path). A
        zero length skips that part this segment (tail segments cover
        fewer parts)."""
        assert self._socks, "session not open"
        n = len(self.part_ids)
        assert n == len(payloads) == len(lengths)
        live = [i for i in range(n) if lengths[i] > 0]
        if not live:
            return
        try:
            if self.cell.get("aborted"):
                raise NativeIOError(-1, "scatter session (aborted)")
            reqs, ptrs, lens = _marshal_part_reqs(
                [self._sock_of(i).fileno() for i in live],
                self.chunk_id, write_id,
                [self.part_ids[i] for i in live],
                [payloads[i] for i in live],
                [lengths[i] for i in live],
            )
            if self.share:
                # shared connections need part-addressed frames (and a
                # duplicate-fd-aware send loop): the vectored call
                rc = _lib.lz_write_parts_scatterv(
                    ctypes.cast(reqs, ctypes.c_void_p), len(live), ptrs,
                    lens, part_offset, 120_000, 0,
                )
            else:
                rc = _lib.lz_write_parts_scatter(
                    ctypes.cast(reqs, ctypes.c_void_p), len(live), ptrs,
                    lens, part_offset, 120_000,
                )
            if rc != 0:
                bad = next((int(r.rc) for r in reqs if r.rc != 0), -1)
                raise NativeIOError(bad, "scatter session segment")
        except BaseException:
            self.close()
            raise

    def send_segment_window(
        self,
        payloads: list[np.ndarray],
        lengths: list[int],
        part_offset: int,
        write_id: int,
    ) -> None:
        """Windowed send: stream one segment's part-addressed bulk
        frames (vectored sendmsg, header+payload in one syscall per
        socket pass) WITHOUT waiting for acks — collect them later via
        :meth:`collect_acks`. The caller bounds how many segments ride
        unacknowledged (the adaptive write window's credits)."""
        assert self._socks, "session not open"
        n = len(self.part_ids)
        assert n == len(payloads) == len(lengths)
        staged = self._ring_staged.get(write_id)
        if staged is not None:
            if not staged:  # fully dead segment (ragged tail)
                self._ring_staged.pop(write_id, None)
                self._pending[write_id] = []
                return
            # staged segment: payloads move into the arena with at most
            # one GIL-free memcpy each (zero for parity, which the
            # caller encoded straight into its staged view), then tiny
            # descriptors ship instead of megabytes
            try:
                if self.cell.get("aborted"):
                    raise NativeIOError(-1, "scatter session (aborted)")
                self._ring_send_descs(staged, payloads, lengths,
                                      part_offset, write_id)
                self._pending[write_id] = [e[0] for e in staged]
            except BaseException:
                self.close()
                raise
            return
        live = [i for i in range(n) if lengths[i] > 0]
        if not live:
            self._pending[write_id] = []
            return
        if self.ring_ready():
            # rings are up but this segment didn't fit (or wasn't
            # staged): socket-copy send, counted as a fallback
            self.ring_stats["fallbacks"] += 1
        try:
            if self.cell.get("aborted"):
                raise NativeIOError(-1, "scatter session (aborted)")
            reqs, ptrs, lens = _marshal_part_reqs(
                [self._sock_of(i).fileno() for i in live],
                self.chunk_id, write_id,
                [self.part_ids[i] for i in live],
                [payloads[i] for i in live],
                [lengths[i] for i in live],
            )
            rc = _lib.lz_write_parts_scatterv(
                ctypes.cast(reqs, ctypes.c_void_p), len(live), ptrs, lens,
                part_offset, 120_000, SCATTER_NO_ACK,
            )
            if rc != 0:
                bad = next((int(r.rc) for r in reqs if r.rc != 0), -1)
                raise NativeIOError(bad, "windowed segment send")
            self._pending[write_id] = live
        except BaseException:
            self.close()
            raise

    def collect_acks(self, write_id: int) -> None:
        """Collect one segment's outstanding acks (sent via
        :meth:`send_segment_window`). Segments must be collected in
        send order — acks are FIFO per connection (and so are ring
        region frees, which keeps the FIFO arena allocator exact)."""
        live = self._pending.pop(write_id, None)
        staged = self._ring_staged.pop(write_id, None)
        if not live:
            return
        try:
            if self.cell.get("aborted"):
                raise NativeIOError(-1, "scatter session (aborted)")
            n = len(live)
            reqs = (_PartReq * n)()
            for j, i in enumerate(live):
                reqs[j].fd = self._sock_of(i).fileno()
                reqs[j].chunk_id = self.chunk_id
                reqs[j].version = write_id
                reqs[j].part_id = self.part_ids[i]
                reqs[j].rc = 0
            rc = _lib.lz_write_collect_acks(
                ctypes.cast(reqs, ctypes.c_void_p), n, 120_000
            )
            if rc != 0:
                bad = next((int(r.rc) for r in reqs if r.rc != 0), -1)
                raise NativeIOError(bad, "windowed segment ack")
            if staged:
                # the server acked: it is done reading these regions
                for _i, ci, _off, cost, _v in staged:
                    self._rings[ci].free(cost)
        except BaseException:
            self.close()
            raise

    def finish(self) -> None:
        try:
            # the windowed caller collects every segment before
            # finishing; a leftover here means an unacked segment and
            # the End status below would desync — refuse
            if self._pending:
                raise NativeIOError(-2, "finish with unacked segments")
            # one WriteEnd per CONNECTION: the server seals every part
            # session of the chunk on that connection and answers once
            _write_end_handshake(self._socks, self.chunk_id)
        except BaseException:
            self.close()
            raise
        # clean end: the sockets sit in the same reusable protocol
        # state the one-shot scatter path pools — release, don't close.
        # Ring-negotiated connections go to THEIR pool (the server side
        # is the proactor; only ring-eligible sessions may reuse them)
        for addr, s in zip(self.unique_addrs, self._socks):
            pool = RING_POOL if shm_ring_of(s) is not None else POOL
            pool.release(addr, s)
        self._socks.clear()
        self.cell.pop("socks", None)
        self.cell["finished"] = True

    def close(self) -> None:
        for s in self._socks:
            shm_ring_drop(s)  # dead socket: its segment dies with it
            try:
                s.close()
            except OSError:
                pass
        self._socks.clear()
        self._rings = []
        self._ring_staged.clear()
        self.cell.pop("socks", None)
        self.cell["finished"] = True


def write_parts_scatter_blocking(
    addrs: list[tuple[str, int]],
    chunk_id: int,
    version: int,
    part_ids: list[int],
    payloads: list[np.ndarray],
    lengths: list[int],
    part_offset: int = 0,
    cell: dict | None = None,
) -> None:
    """Write n whole parts (one bulk frame + ack each) in ONE
    poll-driven native exchange — the write-path mirror of
    read_parts_gather_blocking: one executor thread and one C call
    (which also runs the per-block CRC pass) replace n of each. The
    WriteInit/WriteEnd handshakes stay in Python framing (they carry
    the variable-length chain list). Raises NativeIOError on the first
    failing part; the caller falls back to per-part writes. ``cell``
    publishes the live sockets so abort_parts_scatter() can kill the
    exchange from another thread; ``cell["finished"]`` marks when this
    thread has stopped reading from ``payloads``."""
    n = len(addrs)
    assert n == len(part_ids) == len(payloads) == len(lengths)
    try:
        _write_parts_scatter(
            addrs, chunk_id, version, part_ids, payloads, lengths,
            part_offset, cell,
        )
    finally:
        if cell is not None:
            cell.pop("socks", None)
            cell["finished"] = True


def _write_parts_scatter(
    addrs, chunk_id, version, part_ids, payloads, lengths,
    part_offset, cell,
) -> None:
    n = len(addrs)
    for attempt in (0, 1):
        socks: list[tuple[tuple[str, int], socket.socket]] = []
        try:
            for i, addr in enumerate(addrs):
                s = (POOL.acquire(addr) if attempt == 0
                     else _blocking_socket(addr, 60.0))
                socks.append((addr, s))
                _send_write_init(s, chunk_id, version, part_ids[i])
            if cell is not None:
                cell["socks"] = [s for _, s in socks]
                if cell.get("aborted"):
                    raise NativeIOError(-1, "parts scatter (aborted)")
            _recv_write_init_acks([s for _, s in socks])
            reqs, ptrs, lens = _marshal_part_reqs(
                [s.fileno() for _, s in socks], chunk_id, 1, part_ids,
                payloads, lengths,
            )
            rc = _lib.lz_write_parts_scatter(
                ctypes.cast(reqs, ctypes.c_void_p), n, ptrs, lens,
                part_offset, 120_000,
            )
            if rc == 0:
                _write_end_handshake([s for _, s in socks], chunk_id)
                for addr, s in socks:
                    POOL.release(addr, s)
                socks.clear()
                return
            bad = next((int(r.rc) for r in reqs if r.rc != 0), -1)
            if attempt == 0 and bad == -1 and not (
                cell is not None and cell.get("aborted")
            ):
                continue  # stale pooled sockets: redial everything once
            raise NativeIOError(bad, "parts scatter write")
        except (ConnectionError, OSError, st.StatusError):
            if attempt == 0 and not (
                cell is not None and cell.get("aborted")
            ):
                continue  # redial once (pool may hold staled sockets)
            raise
        finally:
            for _, s in socks:
                POOL.discard(s)
