"""Core abstractions: ChunkEncoder plugin boundary, slice/goal geometry."""
