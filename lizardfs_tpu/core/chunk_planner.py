"""Whole-chunk read planner: choose among a chunk's representations.

The master may hold several SLICES of one chunk at once — a standard
copy plus ec parts mid-conversion after a goal change, or two striped
layouts during rebalancing. The reference's ChunkReadPlanner
(src/common/chunk_read_planner.cc) scores every representation and
picks the cheapest healthy one before the per-slice planner takes over;
round 1 read whichever slice type happened to be listed first and mixed
parts across types. This module is that missing stage: group locations
by slice type, score each group with the shared per-chunkserver health
registry, and rank.

Ranking: viability first (enough parts to serve at all), then
completeness (no recovery needed), then mean part health (flaky-server
demotion), then fewer network ops (std over striped), then fewer
recoveries.
"""

from __future__ import annotations

from dataclasses import dataclass

from lizardfs_tpu.core import geometry

Addr = tuple[str, int]


@dataclass
class SliceCandidate:
    type: geometry.SliceType
    # part index -> [(addr, wire part id), ...] copies of that part
    copies: dict[int, list[tuple[Addr, int]]]
    complete: bool
    health: float
    recovery_parts: int

    def sort_key(self):
        # health quantized to 0.1 so tiny score noise doesn't override
        # the structural preferences (completeness, fewer ops)
        return (
            self.complete,
            round(self.health, 1),
            1 if self.type.is_standard else 0,
            -self.recovery_parts,
        )


def candidates(
    locations,
    score_fn,
    avoid: set[Addr] = frozenset(),
) -> list[SliceCandidate]:
    """Rank a chunk's slice representations, best first.

    ``locations`` are PartLocation messages; ``score_fn(addr) -> float``
    is the health score (core.cs_stats). Replicas in ``avoid`` (already
    failed this read) don't count toward viability unless they are the
    only copy left.
    """
    groups: dict[int, dict[int, list[tuple[Addr, int]]]] = {}
    for pl in locations:
        cpt = geometry.ChunkPartType.from_id(pl.part_id)
        addr = (pl.addr.host, pl.addr.port)
        groups.setdefault(int(cpt.type), {}).setdefault(cpt.part, []).append(
            (addr, pl.part_id)
        )

    out: list[SliceCandidate] = []
    for type_id, copies in groups.items():
        t = geometry.SliceType(type_id)
        usable = {
            p for p, locs in copies.items()
            if any(a not in avoid for a, _ in locs)
        }
        if t.is_standard:
            viable = 0 in usable
            needed = {0}
        else:
            d = t.data_parts
            first_data = 1 if t.is_xor else 0
            needed = {first_data + i for i in range(d)}
            # any d distinct parts reconstruct the data (xor: level of
            # level+1; ec: k of k+m)
            viable = len(usable) >= d
        if not viable:
            continue
        missing_data = len(needed - usable)
        part_scores = [
            max(score_fn(a) for a, _ in locs) for locs in copies.values()
        ]
        out.append(SliceCandidate(
            type=t,
            copies=copies,
            complete=len(usable) >= t.expected_parts,
            health=sum(part_scores) / len(part_scores),
            recovery_parts=missing_data,
        ))
    out.sort(key=SliceCandidate.sort_key, reverse=True)
    if not out and avoid:
        # every slice lost a needed part to the blacklist: desperation
        # pass ignoring it (a flaky replica beats a failed read)
        return candidates(locations, score_fn)
    return out
