"""Wave-scheduled read plans with EC/XOR recovery post-processing.

Re-implementation of the reference's declarative read-plan machinery
(reference: src/common/read_plan.h:54-191, slice_read_plan.h:33-111,
ec_read_plan.h:33-147, xor_read_plan.h): a plan lists per-part read
operations grouped into **waves** (wave 0 = the minimal/cheapest set;
later waves are fallbacks fired on timeout or failure), plus a
post-process step that zero-pads short trailing parts and recovers
missing parts (RS via the ChunkEncoder boundary, or XOR).

The executor (client side) drives sockets and timeouts; everything here
is pure logic over an in-memory flat buffer, which keeps it testable the
same way the reference tests plans with an in-memory simulator
(src/unittests/plan_tester.h).

Parts within a plan are identified by their *slice part index* (one plan
always reads a single slice): for ec(k,m) parts 0..k-1 are data and
k..k+m-1 parity; for xorN part 0 is parity and 1..N are data.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.core import geometry
from lizardfs_tpu.core.encoder import ChunkEncoder, get_encoder


@dataclass
class ReadOp:
    """One read request to a chunkserver (read_plan.h:58-63)."""

    part: int  # slice part index
    request_offset: int
    request_size: int  # may be 0 for parts with no data in range
    buffer_offset: int
    wave: int


@dataclass
class RequestedPartInfo:
    """A part whose bytes the caller asked for (slice_read_plan.h:35-38)."""

    part: int
    size: int  # real bytes available in this part (<= buffer_part_size)


class SliceReadPlan:
    """Read plan for a set of parts of one slice.

    Buffer layout: requested parts first (each ``buffer_part_size``
    bytes, caller-visible result region), then any extra parts read only
    for potential recovery.
    """

    def __init__(
        self,
        slice_type: geometry.SliceType,
        requested_parts: list[RequestedPartInfo],
        buffer_part_size: int,
    ):
        self.slice_type = slice_type
        self.requested_parts = requested_parts
        self.buffer_part_size = buffer_part_size
        self.read_operations: list[ReadOp] = []

    @property
    def buffer_size(self) -> int:
        ops_end = max(
            (op.buffer_offset + self.buffer_part_size for op in self.read_operations),
            default=0,
        )
        return max(ops_end, len(self.requested_parts) * self.buffer_part_size)

    @property
    def result_size(self) -> int:
        return len(self.requested_parts) * self.buffer_part_size

    def is_reading_finished(self, available_parts: list[int]) -> bool:
        """Enough parts arrived to produce the result
        (slice_read_plan.h:47-65)."""
        if len(set(available_parts)) >= geometry.required_parts_to_recover(
            self.slice_type
        ):
            return True
        avail = set(available_parts)
        return all(info.part in avail for info in self.requested_parts)

    def is_finishing_possible(self, unreadable_parts: list[int]) -> bool:
        """Can the plan still succeed given these parts failed
        (slice_read_plan.h:71-88)."""
        if len(self.read_operations) - len(unreadable_parts) >= (
            geometry.required_parts_to_recover(self.slice_type)
        ):
            return True
        bad = set(unreadable_parts)
        return not any(info.part in bad for info in self.requested_parts)

    def postprocess_read(
        self, buffer: np.ndarray, available_parts: list[int]
    ) -> int:
        """Zero-pad short trailing parts (slice_read_plan.h:94-105)."""
        for i, info in enumerate(self.requested_parts):
            start = i * self.buffer_part_size + info.size
            end = (i + 1) * self.buffer_part_size
            buffer[start:end] = 0
        return self.result_size

    def postprocess(self, buffer: np.ndarray, available_parts: list[int]) -> np.ndarray:
        """Run post-processing; returns the caller-visible result view."""
        size = self.postprocess_read(buffer, available_parts)
        return buffer[:size]


class ECReadPlan(SliceReadPlan):
    """Slice plan with Reed-Solomon recovery (ec_read_plan.h:33-147)."""

    def __init__(self, slice_type, requested_parts, buffer_part_size, encoder=None):
        assert slice_type.is_ec
        super().__init__(slice_type, requested_parts, buffer_part_size)
        self._encoder: ChunkEncoder = encoder or get_encoder("cpu")

    def postprocess_read(self, buffer, available_parts):
        super().postprocess_read(buffer, available_parts)
        avail = set(available_parts)
        if any(info.part not in avail for info in self.requested_parts):
            self._recover_parts(buffer, avail)
        return self.result_size

    def _recover_parts(self, buffer: np.ndarray, available: set[int]) -> None:
        """Rebuild missing requested parts from any k available ones
        (ec_read_plan.h:113-146). EC slice part indices are already the
        codec's global part indices."""
        k = self.slice_type.data_parts
        m = self.slice_type.parity_parts
        bps = self.buffer_part_size
        parts: dict[int, np.ndarray] = {}
        for op in self.read_operations:
            if op.part in available and op.part not in parts and len(parts) < k:
                parts[op.part] = buffer[op.buffer_offset : op.buffer_offset + bps]
        wanted = [
            info.part
            for info in self.requested_parts
            if info.part not in available
        ]
        recovered = self._encoder.recover(k, m, parts, wanted)
        for i, info in enumerate(self.requested_parts):
            if info.part in recovered:
                buffer[i * bps : (i + 1) * bps] = recovered[info.part]


class XorReadPlan(SliceReadPlan):
    """Slice plan with XOR parity recovery (xor_read_plan.h:39-121).

    A xorN slice can lose at most one part; the missing part is the XOR
    of all the others.
    """

    def __init__(self, slice_type, requested_parts, buffer_part_size, encoder=None):
        assert slice_type.is_xor
        super().__init__(slice_type, requested_parts, buffer_part_size)
        self._encoder: ChunkEncoder = encoder or get_encoder("cpu")

    def postprocess_read(self, buffer, available_parts):
        super().postprocess_read(buffer, available_parts)
        avail = set(available_parts)
        missing = [i for i in (info.part for info in self.requested_parts) if i not in avail]
        if not missing:
            return self.result_size
        assert len(missing) == 1, "xor slice can recover at most one part"
        bps = self.buffer_part_size
        sources = []
        for op in self.read_operations:
            if op.part in avail and op.part != missing[0]:
                sources.append(buffer[op.buffer_offset : op.buffer_offset + bps].copy())
        need = self.slice_type.xor_level  # N others required (N data + parity - 1)
        assert len(sources) >= need
        parity = self._encoder.xor_parity(sources[: need])
        for i, info in enumerate(self.requested_parts):
            if info.part == missing[0]:
                buffer[i * bps : (i + 1) * bps] = parity
        return self.result_size


def plan_for_standard(requested_size: int) -> SliceReadPlan:
    """Trivial plan for std (single-copy) chunk parts."""
    plan = SliceReadPlan(
        geometry.SliceType(geometry.STANDARD),
        [RequestedPartInfo(0, requested_size)],
        requested_size,
    )
    plan.read_operations.append(ReadOp(0, 0, requested_size, 0, 0))
    return plan


class SliceReadPlanner:
    """Builds a SliceReadPlan for requested parts of one slice, given
    which parts are available and per-part scores (higher = healthier).

    Mirrors src/common/slice_read_planner.{h,cc}: requested+available
    parts are read directly in wave 0; if a requested part is missing,
    the k best-scored other parts join wave 0 (recovery read) and
    whatever remains is scheduled as fallback waves.
    """

    def __init__(
        self,
        slice_type: geometry.SliceType,
        available_parts: list[int],
        scores: dict[int, float] | None = None,
        encoder: ChunkEncoder | None = None,
    ):
        self.slice_type = slice_type
        self.available = list(dict.fromkeys(available_parts))
        self.scores = scores or {}
        self.encoder = encoder

    def _score(self, part: int) -> float:
        return self.scores.get(part, 1.0)

    def is_readable(self, wanted_parts: list[int]) -> bool:
        avail = set(self.available)
        if all(p in avail for p in wanted_parts):
            return True
        k = geometry.required_parts_to_recover(self.slice_type)
        if self.slice_type.is_xor:
            # xor recovery needs every other part of the full slice
            missing = [p for p in wanted_parts if p not in avail]
            full = set(range(self.slice_type.expected_parts))
            return len(missing) == 1 and (full - {missing[0]}) <= avail
        return len(avail) >= k

    def build_plan(
        self,
        wanted_parts: list[int],
        first_block: int,
        block_count: int,
        part_sizes: dict[int, int] | None = None,
    ) -> SliceReadPlan:
        """part_sizes: byte length of each part (defaults to full parts)."""
        if not self.is_readable(wanted_parts):
            raise ValueError("not enough available parts to read/recover")
        bps = block_count * MFSBLOCKSIZE
        off = first_block * MFSBLOCKSIZE

        def psize(part: int) -> int:
            if part_sizes is None:
                return bps
            return max(0, min(part_sizes.get(part, 0) - off, bps))

        requested = [RequestedPartInfo(p, psize(p)) for p in wanted_parts]
        if self.slice_type.is_xor:
            plan = XorReadPlan(self.slice_type, requested, bps, self.encoder)
        elif self.slice_type.is_ec:
            plan = ECReadPlan(self.slice_type, requested, bps, self.encoder)
        else:
            plan = SliceReadPlan(self.slice_type, requested, bps)

        avail = set(self.available)
        wanted_avail = [p for p in wanted_parts if p in avail]
        missing = [p for p in wanted_parts if p not in avail]
        extras = sorted(
            (p for p in self.available if p not in wanted_parts),
            key=self._score,
            reverse=True,
        )

        # wave 0: requested parts we can read directly
        pos = {p: i for i, p in enumerate(wanted_parts)}
        for p in wanted_avail:
            plan.read_operations.append(
                ReadOp(p, off, psize(p), pos[p] * bps, 0)
            )
        extra_offset = len(wanted_parts) * bps
        wave = 0
        if missing:
            # recovery: enough extra parts in wave 0 to reach k sources
            k = geometry.required_parts_to_recover(self.slice_type)
            if self.slice_type.is_xor:
                k = self.slice_type.expected_parts - 1
            need = max(0, k - len(wanted_avail))
            for p in extras[:need]:
                plan.read_operations.append(
                    ReadOp(p, off, psize(p), extra_offset, 0)
                )
                extra_offset += bps
            extras = extras[need:]
        # remaining available parts become fallback waves
        for p in extras:
            wave += 1
            plan.read_operations.append(
                ReadOp(p, off, psize(p), extra_offset, wave)
            )
            extra_offset += bps
        return plan
