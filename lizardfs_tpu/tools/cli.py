"""`lizardfs` — busybox-style file tool (reference: src/tools/, the
setgoal/getgoal/fileinfo/dirinfo/... multi-tool).

Works daemonless against the master/chunkservers through the client
library (no FUSE mount needed):

    python -m lizardfs_tpu.tools.cli --master host:port <command> [...]

Commands: ls, mkdir, rmdir, rm, mv, ln, symlink, readlink, put, get,
cat, stat, setgoal, getgoal, geteattr, seteattr, settrashtime,
gettrashtime, fileinfo, dirinfo, checkfile, rremove, truncate.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import stat as stat_mod
import sys

from lizardfs_tpu.constants import MFSCHUNKSIZE
from lizardfs_tpu.core import geometry
from lizardfs_tpu.client.client import Client
from lizardfs_tpu.proto import messages as msgs
from lizardfs_tpu.proto import status as st

FTYPE_CHAR = {1: "-", 2: "d", 3: "l"}


def _addrs(s: str) -> list[tuple[str, int]]:
    if s.startswith("mount:"):
        # discover the master through a mounted filesystem's local proxy
        # (masterproxy.cc analog): .masterinfo names the relay address
        import os

        info = os.path.join(s[len("mount:"):], ".masterinfo")
        try:
            with open(info) as f:
                for line in f:
                    if line.startswith("masterproxy:"):
                        host, _, port = line.split()[1].rpartition(":")
                        return [(host, int(port))]
        except OSError as e:
            raise ConnectionError(
                f"{s!r} is not a lizardfs mount ({e})"
            ) from e
        raise ConnectionError(f"no masterproxy line in {info}")
    out = []
    for item in s.split(","):
        host, _, port = item.strip().rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


async def _connect(args) -> Client:
    addrs = _addrs(args.master)
    c = Client("", 0, master_addrs=addrs)
    # lint: waive(unbounded-await): delegates to Client.connect — dials via the 5 s-bounded RpcConnection.connect and a 30 s-capped register RPC
    await c.connect(info="lizardfs-cli")
    return c


def _fmt_attr(name: str, a) -> str:
    kind = FTYPE_CHAR.get(a.ftype, "?")
    mode = stat_mod.filemode(
        (stat_mod.S_IFDIR if a.ftype == 2 else
         stat_mod.S_IFLNK if a.ftype == 3 else stat_mod.S_IFREG) | a.mode
    )[1:]
    return (
        f"{kind}{mode} {a.nlink:3d} {a.uid:5d} {a.gid:5d} "
        f"{a.length:12d} goal:{a.goal:<3d} {name}"
    )


async def cmd_ls(c: Client, args) -> int:
    a = await c.resolve(args.path)
    if a.ftype != msgs.FTYPE_DIR:
        print(_fmt_attr(args.path, a))
        return 0
    for e in await c.readdir(a.inode):
        ea = await c.getattr(e.inode)
        print(_fmt_attr(e.name, ea))
    return 0


async def cmd_mkdir(c: Client, args) -> int:
    parent, name = await c.resolve_parent(args.path)
    await c.mkdir(parent.inode, name)
    return 0


async def cmd_rmdir(c: Client, args) -> int:
    parent, name = await c.resolve_parent(args.path)
    await c.rmdir(parent.inode, name)
    return 0


async def cmd_rm(c: Client, args) -> int:
    parent, name = await c.resolve_parent(args.path)
    await c.unlink(parent.inode, name)
    return 0


async def cmd_mv(c: Client, args) -> int:
    psrc, nsrc = await c.resolve_parent(args.src)
    pdst, ndst = await c.resolve_parent(args.dst)
    await c.rename(psrc.inode, nsrc, pdst.inode, ndst)
    return 0


async def cmd_ln(c: Client, args) -> int:
    target = await c.resolve(args.target)
    parent, name = await c.resolve_parent(args.link)
    await c.link(target.inode, parent.inode, name)
    return 0


async def cmd_symlink(c: Client, args) -> int:
    parent, name = await c.resolve_parent(args.link)
    await c.symlink(parent.inode, name, args.target)
    return 0


async def cmd_readlink(c: Client, args) -> int:
    a = await c.resolve(args.path)
    print(await c.readlink(a.inode))
    return 0


async def cmd_put(c: Client, args) -> int:
    with open(args.local, "rb") as f:
        data = f.read()
    try:
        a = await c.resolve(args.remote)
    except st.StatusError:
        parent, name = await c.resolve_parent(args.remote)
        a = await c.create(parent.inode, name)
    if args.goal:
        await c.setgoal(a.inode, args.goal)
    await c.write_file(a.inode, data)
    print(f"wrote {len(data)} bytes to {args.remote}")
    return 0


async def cmd_get(c: Client, args) -> int:
    a = await c.resolve(args.remote)
    data = await c.read_file(a.inode)
    with open(args.local, "wb") as f:
        f.write(data)
    print(f"read {len(data)} bytes from {args.remote}")
    return 0


async def cmd_cat(c: Client, args) -> int:
    a = await c.resolve(args.path)
    sys.stdout.buffer.write(await c.read_file(a.inode))
    return 0


async def cmd_stat(c: Client, args) -> int:
    a = await c.resolve(args.path)
    print(json.dumps({
        "inode": a.inode, "type": a.ftype, "mode": oct(a.mode),
        "uid": a.uid, "gid": a.gid, "nlink": a.nlink, "length": a.length,
        "goal": a.goal, "trash_time": a.trash_time,
        "eattr": _eattr_flags(a.eattr),
        "atime": a.atime, "mtime": a.mtime, "ctime": a.ctime,
    }, indent=2))
    return 0


async def cmd_setgoal(c: Client, args) -> int:
    a = await c.resolve(args.path)
    await c.setgoal(a.inode, args.goal)
    return 0


async def cmd_getgoal(c: Client, args) -> int:
    a = await c.resolve(args.path)
    print(f"{args.path}: goal {a.goal}")
    return 0


def _eattr_flags(eattr: int) -> str:
    from lizardfs_tpu.constants import EATTR_NAMES

    names = [n for n, bit in EATTR_NAMES.items() if eattr & bit]
    return ",".join(names) if names else "-"


async def cmd_geteattr(c: Client, args) -> int:
    a = await c.resolve(args.path)
    print(f"{args.path}: eattr {_eattr_flags(a.eattr)}")
    return 0


async def cmd_seteattr(c: Client, args) -> int:
    """FLAGS: comma list of [+|-]name over noowner/nocache/noentrycache.
    Bare names replace the whole set; +name/-name edit the current one
    (mfsseteattr -f style)."""
    from lizardfs_tpu.constants import EATTR_NAMES

    a = await c.resolve(args.path)
    tokens = [t.strip() for t in args.flags.split(",") if t.strip()]
    relative = all(t[0] in "+-" for t in tokens) and tokens
    eattr = a.eattr if relative else 0
    for t in tokens:
        op, name = (t[0], t[1:]) if t[0] in "+-" else ("+", t)
        bit = EATTR_NAMES.get(name)
        if bit is None:
            print(f"unknown eattr flag {name!r} "
                  f"(known: {', '.join(EATTR_NAMES)})", file=sys.stderr)
            return 2
        eattr = (eattr | bit) if op == "+" else (eattr & ~bit)
    attr = await c.seteattr(a.inode, eattr)
    print(f"{args.path}: eattr {_eattr_flags(attr.eattr)}")
    return 0


async def cmd_settrashtime(c: Client, args) -> int:
    a = await c.resolve(args.path)
    await c.settrashtime(a.inode, args.seconds)
    return 0


async def cmd_gettrashtime(c: Client, args) -> int:
    a = await c.resolve(args.path)
    print(f"{args.path}: trash time {a.trash_time}s")
    return 0


async def cmd_truncate(c: Client, args) -> int:
    a = await c.resolve(args.path)
    await c.truncate(a.inode, args.size)
    return 0


async def cmd_fileinfo(c: Client, args) -> int:
    a = await c.resolve(args.path)
    nchunks = (a.length + MFSCHUNKSIZE - 1) // MFSCHUNKSIZE
    print(f"{args.path}: {a.length} bytes, {nchunks} chunk(s)")
    tape = await c.tape_info(a.inode)
    if tape.get("demoted"):
        state = "recalling" if tape.get("recalling") else "tape-only"
        print(f"  tier: demoted ({state}) — GET/read triggers recall")
    if tape["wanted"] or tape["copies"] or tape.get("forced"):
        state = "pending" if tape["pending"] else "in sync"
        print(
            f"  tape: {tape['fresh']}/{tape['wanted']} fresh copies"
            f" ({state})"
        )
        for cp in tape["copies"]:
            stale = "" if (cp["length"], cp["mtime"]) == \
                (a.length, a.mtime) else " [stale]"
            print(f"    label {cp['label']}: {cp['length']} bytes{stale}")
    for i in range(nchunks):
        info = await c.chunk_info(a.inode, i)
        if info.chunk_id == 0:
            print(f"  chunk {i}: hole")
            continue
        print(f"  chunk {i}: id {info.chunk_id:016X} version {info.version}")
        for loc in info.locations:
            cpt = geometry.ChunkPartType.from_id(loc.part_id)
            print(
                f"    part {cpt.to_string():>12s} on "
                f"{loc.addr.host}:{loc.addr.port}"
            )
    return 0


async def cmd_checkfile(c: Client, args) -> int:
    a = await c.resolve(args.path)
    nchunks = (a.length + MFSCHUNKSIZE - 1) // MFSCHUNKSIZE
    problems = 0
    for i in range(nchunks):
        info = await c.chunk_info(a.inode, i)
        if info.chunk_id == 0:
            continue
        parts = {geometry.ChunkPartType.from_id(l.part_id).part for l in info.locations}
        if not info.locations:
            print(f"  chunk {i}: NO COPIES (lost)")
            problems += 1
            continue
        t = geometry.ChunkPartType.from_id(info.locations[0].part_id).type
        missing = t.expected_parts - len(parts)
        if t.is_standard:
            print(f"  chunk {i}: {len(info.locations)} cop(ies)")
        elif missing > 0:
            k = t.data_parts
            state = "ENDANGERED" if len(parts) >= k else "UNREADABLE"
            print(f"  chunk {i}: {len(parts)}/{t.expected_parts} parts — {state}")
            problems += 1
    print(f"{args.path}: {'OK' if problems == 0 else f'{problems} problem chunk(s)'}")
    return 0 if problems == 0 else 1


async def cmd_filerepair(c: Client, args) -> int:
    """Repair a file with missing/unrecoverable chunks: repairable
    chunks are rebuilt through the master's RebuildEngine, stale-version
    survivors are version-fixed, and only truly unrecoverable chunks
    are zero-filled (reference: mfsfilerepair)."""
    a = await c.resolve(args.path)
    counts = await c.filerepair(a.inode)
    print(
        f"{args.path}: ok {counts['ok_chunks']}, "
        f"queued-rebuild {counts['queued_rebuild']}, "
        f"version-fixed {counts['repaired_versions']}, "
        f"zeroed {counts['zeroed']}"
    )
    return 0 if counts["zeroed"] == 0 else 1


async def cmd_appendchunks(c: Client, args) -> int:
    """Append SRC file(s) onto DST chunk-wise in O(1) per chunk (the
    chunks are shared, not copied; reference: mfsappendchunks)."""
    dst = await c.resolve(args.dst)
    for src_path in args.srcs:
        src = await c.resolve(src_path)
        attr = await c.append_chunks(dst.inode, src.inode)
    print(f"{args.dst}: now {attr.length} bytes")
    return 0


async def _walk_size(c: Client, inode: int) -> tuple[int, int, int]:
    """(files, dirs, bytes) under a directory (dirinfo analog)."""
    files = dirs = total = 0
    for e in await c.readdir(inode):
        if e.ftype == msgs.FTYPE_DIR:
            dirs += 1
            f2, d2, t2 = await _walk_size(c, e.inode)
            files, dirs, total = files + f2, dirs + d2, total + t2
        else:
            files += 1
            total += (await c.getattr(e.inode)).length
    return files, dirs, total


async def cmd_tape_demote(c: Client, args) -> int:
    """Demote a file to the tape tier (frees chunk data once a fresh
    archival copy exists; CHUNK_BUSY = archive queued, retry)."""
    a = await c.resolve(args.path)
    try:
        await c.tape_demote(a.inode)
    except st.StatusError as e:
        if e.code != st.CHUNK_BUSY:
            raise
        print(f"{args.path}: archive queued — not yet demoted, retry "
              "after the tape copy lands")
        return 1
    print(f"{args.path}: demoted to the tape tier")
    return 0


async def cmd_tape_recall(c: Client, args) -> int:
    """Recall a demoted file from the tape tier (blocks until the
    bytes are live again)."""
    a = await c.resolve(args.path)
    await c.tape_recall(a.inode)
    print(f"{args.path}: recalled")
    return 0


async def cmd_dirinfo(c: Client, args) -> int:
    a = await c.resolve(args.path)
    files, dirs, total = await _walk_size(c, a.inode)
    print(f"{args.path}: {files} files, {dirs} dirs, {total} bytes")
    return 0


async def _rremove(c: Client, parent_inode: int, name: str, inode: int, ftype: int) -> None:
    if ftype == msgs.FTYPE_DIR:
        for e in await c.readdir(inode):
            await _rremove(c, inode, e.name, e.inode, e.ftype)
        await c.rmdir(parent_inode, name)
    else:
        await c.unlink(parent_inode, name)


async def cmd_rremove(c: Client, args) -> int:
    parent, name = await c.resolve_parent(args.path)
    a = await c.lookup(parent.inode, name)
    await _rremove(c, parent.inode, name, a.inode, a.ftype)
    return 0


async def cmd_snapshot(c: Client, args) -> int:
    src = await c.resolve(args.src)
    parent, name = await c.resolve_parent(args.dst)
    await c.snapshot(src.inode, parent.inode, name)
    print(f"snapshot {args.src} -> {args.dst}")
    return 0


async def cmd_getxattr(c: Client, args) -> int:
    a = await c.resolve(args.path)
    sys.stdout.buffer.write(await c.get_xattr(a.inode, args.name) + b"\n")
    return 0


async def cmd_setxattr(c: Client, args) -> int:
    a = await c.resolve(args.path)
    await c.set_xattr(a.inode, args.name, args.value.encode())
    return 0


async def cmd_listxattr(c: Client, args) -> int:
    a = await c.resolve(args.path)
    for name in await c.list_xattr(a.inode):
        print(name)
    return 0


async def cmd_quota_set(c: Client, args) -> int:
    owner = args.id
    if args.kind == "dir":
        owner = (await c.resolve(args.id)).inode
    await c.set_quota(
        args.kind, int(owner), soft_inodes=args.soft_inodes,
        hard_inodes=args.hard_inodes, soft_bytes=args.soft_bytes,
        hard_bytes=args.hard_bytes, remove=args.remove,
    )
    return 0


async def cmd_quota_rep(c: Client, args) -> int:
    rows = await c.get_quota()
    for r in rows:
        print(
            f"{r['kind']:6s} {r['id']:<8d} "
            f"inodes {r['used_inodes']}/{r['hard_inodes'] or '-'} "
            f"bytes {r['used_bytes']}/{r['hard_bytes'] or '-'}"
        )
    return 0


async def cmd_trash_list(c: Client, args) -> int:
    for row in await c.trash_list():
        print(f"inode {row['inode']:<8d} expires {row['expires']} {row['name']}")
    return 0


async def cmd_undelete(c: Client, args) -> int:
    await c.undelete(args.inode)
    return 0


async def cmd_setrichacl(c: Client, args) -> int:
    """setrichacl PATH ACE[,ACE...] | setrichacl --clear PATH

    ACE syntax: [deny:]who:rwx[:fdino] — who is owner@|group@|
    everyone@|u:UID|g:GID; flags f=file-inherit d=dir-inherit
    i=inherit-only n=no-propagate. Examples:
      setrichacl /dir 'deny:u:1000:w,everyone@:rx:fd'
    """
    from lizardfs_tpu.master import richacl as rmod

    a = await c.resolve(args.path)
    if args.clear:
        await c.set_rich_acl(a.inode, None)
        return 0
    aces = []
    try:
        for spec in args.aces.split(","):
            parts = spec.strip().split(":")
            ace_type = rmod.ALLOW
            if parts[0] == "deny":
                ace_type = rmod.DENY
                parts = parts[1:]
            if parts[0] in ("u", "g"):
                who = parts[0] + ":" + str(int(parts[1]))
                parts = parts[2:]
            elif parts[0] in (rmod.OWNER, rmod.GROUP, rmod.EVERYONE):
                who = parts[0]
                parts = parts[1:]
            else:
                raise ValueError(f"unknown principal {parts[0]!r}")
            mask = 0
            for ch in parts[0]:
                mask |= {"r": 4, "w": 2, "x": 1}[ch]
            flags = 0
            if len(parts) > 1:
                for ch in parts[1]:
                    flags |= {"f": rmod.FILE_INHERIT, "d": rmod.DIR_INHERIT,
                              "i": rmod.INHERIT_ONLY,
                              "n": rmod.NO_PROPAGATE}[ch]
            aces.append(rmod.Ace(ace_type, flags, mask, who))
    except (ValueError, KeyError, IndexError) as e:
        print(f"error: bad ACE spec: {e} — syntax: "
              "[deny:]owner@|group@|everyone@|u:UID|g:GID:rwx[:fdino]",
              file=sys.stderr)
        return 2
    await c.set_rich_acl(a.inode, rmod.RichAcl(aces).to_dict())
    return 0


async def cmd_getrichacl(c: Client, args) -> int:
    from lizardfs_tpu.master import richacl as rmod

    a = await c.resolve(args.path)
    doc = await c.get_rich_acl(a.inode)
    if doc is None:
        # synthesize the equivalent view from mode + POSIX ACL (the
        # acl_converter.cc getrichacl path for POSIX-only inodes)
        from lizardfs_tpu.master import acl as acl_mod
        from lizardfs_tpu.master.richacl import from_posix

        posix = await c.get_acl(a.inode)
        pacl = (acl_mod.Acl.from_dict(posix["access"])
                if posix.get("access") else None)
        doc = from_posix(posix["mode"], pacl).to_dict()
        print(f"{args.path}: no richacl; synthetic from POSIX:")
    for ace in rmod.RichAcl.from_dict(doc).aces:
        kind = "deny " if ace.ace_type == rmod.DENY else "allow"
        perms = "".join(
            ch for bit, ch in ((4, "r"), (2, "w"), (1, "x")) if ace.mask & bit
        )
        flags = "".join(
            ch for bit, ch in (
                (rmod.FILE_INHERIT, "f"), (rmod.DIR_INHERIT, "d"),
                (rmod.INHERIT_ONLY, "i"), (rmod.NO_PROPAGATE, "n"),
            ) if ace.flags & bit
        )
        print(f"{kind} {ace.who:12s} {perms or '-'}"
              + (f" [{flags}]" if flags else ""))
    return 0


COMMANDS = {
    "ls": (cmd_ls, [("path", {})]),
    "mkdir": (cmd_mkdir, [("path", {})]),
    "rmdir": (cmd_rmdir, [("path", {})]),
    "rm": (cmd_rm, [("path", {})]),
    "mv": (cmd_mv, [("src", {}), ("dst", {})]),
    "ln": (cmd_ln, [("target", {}), ("link", {})]),
    "symlink": (cmd_symlink, [("target", {}), ("link", {})]),
    "readlink": (cmd_readlink, [("path", {})]),
    "put": (cmd_put, [("local", {}), ("remote", {}),
                      ("--goal", {"type": int, "default": 0})]),
    "get": (cmd_get, [("remote", {}), ("local", {})]),
    "cat": (cmd_cat, [("path", {})]),
    "stat": (cmd_stat, [("path", {})]),
    "setgoal": (cmd_setgoal, [("goal", {"type": int}), ("path", {})]),
    "getgoal": (cmd_getgoal, [("path", {})]),
    "geteattr": (cmd_geteattr, [("path", {})]),
    "seteattr": (cmd_seteattr, [("flags", {}), ("path", {})]),
    "settrashtime": (cmd_settrashtime, [("seconds", {"type": int}), ("path", {})]),
    "gettrashtime": (cmd_gettrashtime, [("path", {})]),
    "truncate": (cmd_truncate, [("size", {"type": int}), ("path", {})]),
    "fileinfo": (cmd_fileinfo, [("path", {})]),
    "checkfile": (cmd_checkfile, [("path", {})]),
    "filerepair": (cmd_filerepair, [("path", {})]),
    "appendchunks": (cmd_appendchunks, [
        ("dst", {}), ("srcs", {"nargs": "+"}),
    ]),
    "tape-demote": (cmd_tape_demote, [("path", {})]),
    "tape-recall": (cmd_tape_recall, [("path", {})]),
    "dirinfo": (cmd_dirinfo, [("path", {})]),
    "rremove": (cmd_rremove, [("path", {})]),
    "snapshot": (cmd_snapshot, [("src", {}), ("dst", {})]),
    "setrichacl": (cmd_setrichacl, [
        ("path", {}), ("aces", {"nargs": "?", "default": ""}),
        ("--clear", {"action": "store_true"}),
    ]),
    "getrichacl": (cmd_getrichacl, [("path", {})]),
    "getxattr": (cmd_getxattr, [("path", {}), ("name", {})]),
    "setxattr": (cmd_setxattr, [("path", {}), ("name", {}), ("value", {})]),
    "listxattr": (cmd_listxattr, [("path", {})]),
    "quota-set": (cmd_quota_set, [
        ("kind", {"choices": ["user", "group", "dir"]}), ("id", {}),
        ("--soft-inodes", {"type": int, "default": 0, "dest": "soft_inodes"}),
        ("--hard-inodes", {"type": int, "default": 0, "dest": "hard_inodes"}),
        ("--soft-bytes", {"type": int, "default": 0, "dest": "soft_bytes"}),
        ("--hard-bytes", {"type": int, "default": 0, "dest": "hard_bytes"}),
        ("--remove", {"action": "store_true"}),
    ]),
    "quota-rep": (cmd_quota_rep, []),
    "trash-list": (cmd_trash_list, []),
    "undelete": (cmd_undelete, [("inode", {"type": int})]),
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="lizardfs", description=__doc__)
    p.add_argument(
        "--master", default="127.0.0.1:9420",
        help="master address(es) host:port[,host:port...], or "
             "mount:/path to discover via a mounted FS's .masterinfo",
    )
    sub = p.add_subparsers(dest="command", required=True)
    for name, (_, params) in COMMANDS.items():
        sp = sub.add_parser(name)
        for pname, kw in params:
            sp.add_argument(pname, **kw)
    return p


async def _amain(argv) -> int:
    args = build_parser().parse_args(argv)
    fn = COMMANDS[args.command][0]
    c = await _connect(args)
    try:
        return await fn(c, args)
    except st.StatusError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await c.close()


def main(argv=None) -> int:
    try:
        return asyncio.run(_amain(argv if argv is not None else sys.argv[1:]))
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) went away: exit quietly
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    except (ConnectionError, OSError) as e:
        print(f"error: cannot reach master: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
