"""User-facing CLIs: the `lizardfs` file tool and `lizardfs-admin`."""
