"""Web status UI — the CGI monitoring panel, modernized.

The reference ships a Python CGI rendering master state tables + charts
(reference: src/cgi/mfs.cgi.in). This is the stdlib-only equivalent: a
small HTTP server that queries the master's admin protocol and serves a
live HTML dashboard plus raw JSON endpoints.

    python -m lizardfs_tpu.tools.webui --master 127.0.0.1:9420 --port 9425

Endpoints: /  (dashboard), /api/info, /api/health, /api/metrics,
/api/top (cluster-wide per-session workload rollup),
/api/rebuild (RebuildEngine progress/ETA JSON),
/metrics (Prometheus text exposition of the master's registry),
/health (cluster health rollup JSON — SLO burn, per-CS snapshots)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m

PAGE = """<!doctype html>
<html><head><title>lizardfs-tpu status</title>
<meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: monospace; margin: 2em; background: #111; color: #ddd; }}
 h1 {{ color: #7fd4a0; }} h2 {{ color: #8ab4f8; margin-top: 1.5em; }}
 table {{ border-collapse: collapse; }}
 td, th {{ border: 1px solid #444; padding: 4px 10px; text-align: left; }}
 th {{ background: #222; }}
 .ok {{ color: #7fd4a0; }} .bad {{ color: #f28b82; }}
</style></head><body>
<h1>lizardfs-tpu &mdash; {personality} @ v{version}</h1>
<h2>cluster</h2>
<table>
<tr><th>inodes</th><td>{inodes}</td></tr>
<tr><th>chunks</th><td>{chunks}</td></tr>
<tr><th>sessions</th><td>{sessions}</td></tr>
<tr><th>chunks healthy / endangered / lost</th>
    <td><span class="ok">{healthy}</span> /
        <span class="{endangered_cls}">{endangered}</span> /
        <span class="{lost_cls}">{lost}</span></td></tr>
</table>
<h2>chunkservers</h2>
<table><tr><th>id</th><th>address</th><th>label</th><th>state</th>
<th>used / total GiB</th></tr>{servers}</table>
<h2>rebuild engine</h2>
<table>
<tr><th>queued (lost / endangered / rebalance)</th>
    <td><span class="{lostq_cls}">{q_lost}</span> /
        {q_endangered} / {q_rebalance}</td></tr>
<tr><th>active / cap</th><td>{rb_active} / {rb_cap}</td></tr>
<tr><th>throttle</th><td>{rb_throttle}</td></tr>
<tr><th>completed / failed</th><td>{rb_completed} / {rb_failed}</td></tr>
<tr><th>rate / ETA</th><td>{rb_rate} MB/s &mdash; {rb_eta}</td></tr>
</table>
<h2>workload top &mdash; per-session (ops/s over the accounting window)</h2>
<table><tr><th>session</th><th>who</th><th>ops/s</th><th>p99 ms</th>
<th>hot classes</th><th>read roofline</th>
<th>exemplar trace</th></tr>{top_rows}</table>
<h2>metadata ops (last 120 s)</h2>
<pre>{ops}</pre>
<h2>charts &mdash; range: {range_links} (showing {span})</h2>
{charts}
<h2>chunkserver charts ({span})</h2>
{cs_charts}
</body></html>
"""

# resolution -> human span of the full ring (runtime.metrics.RESOLUTIONS)
SPANS = {
    "sec": "2 min", "min": "3 h", "tenmin": "1 day",
    "hour": "1 week", "day": "3 months",
}


def sparkline(points, width=480, height=60, color="#8ab4f8"):
    """Inline SVG sparkline of a numeric series (charts rendering)."""
    pts = [max(float(p), 0.0) for p in points][-120:]
    if not pts:
        pts = [0.0]
    peak = max(pts) or 1.0
    n = len(pts)
    step = width / max(n - 1, 1)
    coords = " ".join(
        f"{i * step:.1f},{height - 2 - (v / peak) * (height - 6):.1f}"
        for i, v in enumerate(pts)
    )
    return (
        f'<svg width="{width}" height="{height}" '
        f'style="background:#1a1a1a;border:1px solid #333">'
        f'<polyline fill="none" stroke="{color}" stroke-width="1.5" '
        f'points="{coords}"/>'
        f'<text x="4" y="12" fill="#888" font-size="10">peak {peak:.0f}</text>'
        f"</svg>"
    )


async def _admin(addr, msg):
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(*addr), 5.0
    )
    try:
        await framing.send_message(writer, msg)
        return await framing.read_message(reader)
    finally:
        writer.close()


class Dashboard:
    def __init__(self, master_addr: tuple[str, int]):
        self.master_addr = master_addr
        self.loop = asyncio.new_event_loop()
        threading.Thread(target=self.loop.run_forever, daemon=True).start()

    def _call(self, msg):
        fut = asyncio.run_coroutine_threadsafe(
            _admin(self.master_addr, msg), self.loop
        )
        return fut.result(10)

    def info(self) -> dict:
        return json.loads(self._call(m.AdminInfo(req_id=1)).json)

    def health(self) -> dict:
        return json.loads(
            self._call(
                m.AdminCommand(req_id=1, command="chunks-health", json="{}")
            ).json
        )

    def cluster_health(self) -> dict:
        """The master's cluster-wide health rollup (SLO burn, breach
        counts, per-chunkserver snapshots, endangered/lost chunks)."""
        return json.loads(
            self._call(
                m.AdminCommand(req_id=1, command="health", json="{}")
            ).json
        )

    def rebuild_status(self) -> dict:
        """The master RebuildEngine's progress/ETA document."""
        return json.loads(
            self._call(
                m.AdminCommand(req_id=1, command="rebuild-status", json="{}")
            ).json
        )

    def top(self) -> dict:
        """The master's cluster-wide per-session workload rollup
        (`lizardfs-admin top` over the admin link)."""
        return json.loads(
            self._call(
                m.AdminCommand(req_id=1, command="top", json="{}")
            ).json
        )

    def heat(self) -> dict:
        """The cluster heat map: hottest chunks/inodes/servers, goal
        boosts, placement loads (`lizardfs-admin heat`)."""
        return json.loads(
            self._call(
                m.AdminCommand(req_id=1, command="heat", json="{}")
            ).json
        )

    def metrics(self, resolution: str = "sec") -> dict:
        return json.loads(
            self._call(
                m.AdminCommand(
                    req_id=1, command="metrics",
                    json=json.dumps({"resolution": resolution}),
                )
            ).json
        )

    def metrics_prom(self) -> str:
        """Prometheus text exposition of the master's registry (the
        daemon renders it; this just unwraps the admin relay)."""
        return json.loads(
            self._call(
                m.AdminCommand(req_id=1, command="metrics-prom", json="{}")
            ).json
        )["text"]

    def cs_metrics_all(self, addrs: list[tuple[str, int]],
                       resolution: str = "sec") -> list[dict | None]:
        """Fetch every chunkserver's metrics concurrently; a slow or
        dead CS yields None after a short timeout instead of stalling
        the whole page render."""

        async def one(addr):
            try:
                reply = await asyncio.wait_for(
                    _admin(addr, m.AdminCommand(
                        req_id=1, command="metrics",
                        json=json.dumps({"resolution": resolution}),
                    )),
                    timeout=3.0,
                )
                return json.loads(reply.json)
            except Exception:  # noqa: BLE001
                return None

        async def all_():
            return await asyncio.gather(*(one(a) for a in addrs))

        return asyncio.run_coroutine_threadsafe(all_(), self.loop).result(10)

    def render(self, res: str = "sec") -> str:
        info = self.info()
        health = self.health()
        try:
            rb = self.rebuild_status()
        except Exception:  # noqa: BLE001 — older master: no verb
            rb = {}
        try:
            top = self.top()
        except Exception:  # noqa: BLE001 — older master: no verb
            top = {}
        top_rows = []
        sessions = sorted(
            top.get("sessions", {}).items(),
            key=lambda kv: -kv[1].get("master", {}).get("rate_ops", 0.0),
        )
        from html import escape as _esc

        for label, entry in sessions[:12]:
            mrow = entry.get("master", {})
            classes = mrow.get("classes", {})
            hot = " ".join(
                f"{cls}:{v.get('ops', 0)}"
                for cls, v in sorted(
                    classes.items(), key=lambda kv: -kv[1].get("ops", 0)
                )[:3]
            )
            # session info and gateway-pushed fields are CLIENT-supplied
            # strings (CltomaRegister.info / CltomaSessionStats JSON) —
            # escape everything interpolated, or a hostile client's
            # registration string runs as script in the operator's
            # browser
            who = entry.get("info", "") or "?"
            gw = entry.get("gateway")
            if gw:
                who += f" ({gw.get('role', '?')} gateway)"
            exemplar = str(mrow.get("exemplar", entry.get("exemplar", "")))
            # client-pushed read PhaseBreakdown (top_report lifts it
            # from the session-stats doc): name the dominant phase so
            # the table answers "what bounds this session's reads"
            phases = entry.get("read_phases") or {}
            roofline = ""
            if phases.get("reps"):
                busy = {
                    k[:-3]: v for k, v in phases.items()
                    if k.endswith("_ms") and k != "wall_ms"
                }
                if busy:
                    dom = max(busy, key=lambda k: busy[k])
                    roofline = f"{dom} {busy[dom]:.0f}ms"
            top_rows.append(
                f"<tr><td>{_esc(str(label))}</td><td>{_esc(who)}</td>"
                f"<td>{mrow.get('rate_ops', 0.0):.1f}</td>"
                f"<td>{mrow.get('p99_ms', 0.0):.1f}</td>"
                f"<td>{_esc(hot)}</td><td>{_esc(roofline)}</td>"
                f"<td>{_esc(exemplar)}</td></tr>"
            )
        rows = []
        for s in info.get("chunkservers", []):
            state = (
                '<span class="ok">up</span>' if s["connected"]
                else '<span class="bad">DOWN</span>'
            )
            rows.append(
                f"<tr><td>{s['cs_id']}</td><td>{s['host']}:{s['port']}</td>"
                f"<td>{s['label']}</td><td>{state}</td>"
                f"<td>{s['used_space']/2**30:.1f} / {s['total_space']/2**30:.1f}</td></tr>"
            )
        if res not in SPANS:
            res = "sec"
        metrics = self.metrics(res)
        sec_metrics = metrics if res == "sec" else self.metrics("sec")
        ops_lines = []
        for name, series in sec_metrics.items():
            if name.startswith("op.") or name == "metadata_ops":
                pts = series["points"][-60:]
                ops_lines.append(
                    f"{name:<24s} total={series['total']:<10.0f} "
                    f"last120s={sum(pts):.0f}"
                )
        charts_html = []
        for name in ("metadata_ops", "chunks", "chunkservers_connected",
                     "chunks_per_server"):
            series = metrics.get(name)
            if series:
                tag = " (derived)" if series.get("kind") == "derived" else ""
                charts_html.append(
                    f"<div><b>{name}</b>{tag}<br>"
                    f"{sparkline(series['points'])}</div>"
                )
        cs_charts = []
        live = [s for s in info.get("chunkservers", []) if s["connected"]]
        fetched = self.cs_metrics_all(
            [(s["host"], s["port"]) for s in live], res
        )
        for s, csm in zip(live, fetched):
            if csm is None:
                continue
            row = []
            for name in ("bytes_read", "bytes_written", "bytes_total"):
                series = csm.get(name)
                if series:
                    row.append(
                        f"<div style='display:inline-block;margin-right:1em'>"
                        f"<b>cs{s['cs_id']} {name}</b><br>"
                        f"{sparkline(series['points'], width=300)}</div>"
                    )
            cs_charts.append("<div>" + "".join(row) + "</div>")
        range_links = " | ".join(
            (f"<b>[{r}]</b>" if r == res
             else f'<a style="color:#8ab4f8" href="/?res={r}">{r}</a>')
            for r in SPANS
        )
        rb_q = rb.get("queued", {})
        rb_thr = rb.get("throttle", {})
        rb_eta = rb.get("eta_s")
        rb_bps = rb_thr.get("rebuild_bps", 0)
        return PAGE.format(
            q_lost=rb_q.get("lost", 0),
            q_endangered=rb_q.get("endangered", 0),
            q_rebalance=rb_q.get("rebalance", 0),
            lostq_cls="bad" if rb_q.get("lost") else "ok",
            rb_active=len(rb.get("active", [])),
            rb_cap=rb_thr.get("rebuild_concurrency", 0),
            rb_throttle=(f"{rb_bps / 1e6:.1f} MB/s" if rb_bps
                         else "unlimited"),
            rb_completed=rb.get("completed", 0),
            rb_failed=rb.get("failed", 0),
            rb_rate=f"{rb.get('rate_bps', 0) / 1e6:.1f}",
            # eta None means EITHER no backlog (idle) or a backlog with
            # no completions in the rate window yet (stalled/starting)
            # — during an incident the second reading is the one that
            # matters, so never render it as "idle"
            rb_eta=(f"{rb_eta:.0f} s backlog" if rb_eta is not None
                    else ("stalled backlog, no recent completions"
                          if rb.get("pending_bytes", 0) else "idle")),
            personality=info.get("personality", "?"),
            version=info.get("version", 0),
            inodes=info.get("inodes", 0),
            chunks=info.get("chunks", 0),
            sessions=info.get("sessions", 0),
            healthy=health.get("healthy", 0),
            endangered=health.get("endangered", 0),
            lost=health.get("lost", 0),
            endangered_cls="bad" if health.get("endangered") else "ok",
            lost_cls="bad" if health.get("lost") else "ok",
            top_rows="".join(top_rows)
            or "<tr><td colspan=6>no sessions tracked</td></tr>",
            servers="".join(rows) or "<tr><td colspan=5>none</td></tr>",
            ops="\n".join(sorted(ops_lines)) or "(no ops yet)",
            charts="".join(charts_html) or "(no series yet)",
            cs_charts="".join(cs_charts) or "(no chunkservers)",
            range_links=range_links,
            span=SPANS[res],
        )


def make_handler(dash: Dashboard):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _send(self, body: str, ctype: str = "text/html"):
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            try:
                if self.path == "/metrics":
                    # standard Prometheus scrape endpoint
                    self._send(
                        dash.metrics_prom(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/health":
                    # cluster health rollup — the load-balancer/monitor
                    # probe endpoint ("is the cluster healthy?")
                    self._send(
                        json.dumps(dash.cluster_health()),
                        "application/json",
                    )
                elif self.path == "/api/top":
                    # cluster-wide per-session workload rollup (the
                    # `lizardfs-admin top` document)
                    self._send(json.dumps(dash.top()), "application/json")
                elif self.path == "/api/heat":
                    # cluster heat map (the `lizardfs-admin heat` doc)
                    self._send(json.dumps(dash.heat()), "application/json")
                elif self.path == "/api/rebuild":
                    # RebuildEngine progress/ETA (rebuild-status verb)
                    self._send(
                        json.dumps(dash.rebuild_status()),
                        "application/json",
                    )
                elif self.path == "/api/info":
                    self._send(json.dumps(dash.info()), "application/json")
                elif self.path == "/api/health":
                    self._send(json.dumps(dash.health()), "application/json")
                elif self.path.startswith("/api/metrics"):
                    res = self.path.rpartition("=")[2] if "=" in self.path else "sec"
                    self._send(json.dumps(dash.metrics(res)), "application/json")
                else:
                    res = "sec"
                    if "res=" in self.path:
                        res = self.path.rpartition("res=")[2].split("&")[0]
                    self._send(dash.render(res))
            except Exception as e:  # noqa: BLE001
                self.send_error(502, f"master unreachable: {e}")

    return Handler


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="lizardfs-webui", description=__doc__)
    p.add_argument("--master", default="127.0.0.1:9420")
    p.add_argument("--port", type=int, default=9425)
    p.add_argument("--host", default="127.0.0.1")
    args = p.parse_args(argv)
    host, _, port = args.master.rpartition(":")
    dash = Dashboard((host or "127.0.0.1", int(port)))
    server = ThreadingHTTPServer((args.host, args.port), make_handler(dash))
    print(f"lizardfs-tpu web UI on http://{args.host}:{server.server_port}/")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
