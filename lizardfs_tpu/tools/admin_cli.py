"""`lizardfs-admin` — cluster administration CLI (reference: src/admin/).

    python -m lizardfs_tpu.tools.admin_cli <host:port> <command>

Commands: info, list-chunkservers, list-sessions, chunks-health,
save-metadata, metadata-checksum, promote-shadow, faults, qos.

``qos`` shows the master's multi-tenant fair-share state (weights,
per-class rates, sheds, per-tenant objectives) and sets it live::

    lizardfs-admin HOST:PORT qos                   # show
    lizardfs-admin HOST:PORT qos weight bulk 2     # tenant weight
    lizardfs-admin HOST:PORT qos rate locate 3000  # class ops/s
    lizardfs-admin HOST:PORT qos data-inflight-mb 32

``faults`` steers the live fault-injection rule set of any daemon
(runtime/faults.py) over the tweaks/admin channel::

    lizardfs-admin HOST:PORT faults                 # list rules + fires
    lizardfs-admin HOST:PORT faults arm 'chunkserver:disk_pread flip,limit=1'
    lizardfs-admin HOST:PORT faults clear
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st


async def _admin(addr: tuple[str, int], command: str, payload: str = "{}",
                 password: str | None = None):
    # bounded dial: an admin command against a blackholed daemon must
    # error out in seconds, not the OS SYN timeout
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(*addr), 5.0
    )
    try:
        if password:
            # challenge-response: the password never crosses the wire
            import hmac

            await framing.send_message(
                writer,
                m.AdminCommand(req_id=1, command="auth-challenge", json="{}"),
            )
            ch = await framing.read_message(reader)
            nonce = json.loads(ch.json).get("nonce", "")
            digest = hmac.new(
                password.encode(), nonce.encode(), "sha256"
            ).hexdigest()
            await framing.send_message(
                writer,
                m.AdminCommand(
                    req_id=2, command="auth",
                    json=json.dumps({"digest": digest}),
                ),
            )
            auth = await framing.read_message(reader)
            if getattr(auth, "status", 1) != st.OK:
                return auth
        if command == "info":
            await framing.send_message(writer, m.AdminInfo(req_id=1))
        else:
            await framing.send_message(
                writer, m.AdminCommand(req_id=1, command=command, json=payload)
            )
        return await framing.read_message(reader)
    finally:
        writer.close()


def _parse_trace_id(raw: str) -> int:
    """Accept ids as timelines display them (0x-prefixed hex) as well
    as decimal; a bare hex string with letters also parses, so
    copy-pasting from any output works."""
    try:
        return int(raw, 0)
    except ValueError:
        return int(raw, 16)


async def _amain(argv) -> int:
    p = argparse.ArgumentParser(prog="lizardfs-admin", description=__doc__)
    p.add_argument("master", help="daemon host:port (master or chunkserver)")
    p.add_argument(
        "command",
        choices=[
            "info", "list-chunkservers", "list-sessions", "chunks-health",
            "save-metadata", "metadata-checksum", "promote-shadow",
            "metrics", "metrics-csv", "metrics-prom", "tweaks", "tweaks-set",
            "trace-dump", "health", "slowops", "rebuild-status", "faults",
            "top", "profile", "qos", "heat",
        ],
    )
    p.add_argument("extra", nargs="*",
                   help="tweaks-set: NAME VALUE; metrics: [resolution]; "
                        "trace-dump: [trace_id]; "
                        "faults: [arm RULE | clear]; "
                        "top: [watch]; profile: [top_n]; "
                        "qos: [weight TENANT W | rate CLASS OPS | "
                        "data-inflight-mb MB | data-bps BPS | "
                        "rebuild-weight W]")
    p.add_argument("--attribute", action="store_true",
                   help="trace-dump: append the latency attribution "
                        "(queue/disk/net/compute/unattributed buckets)")
    p.add_argument("--password", default=None,
                   help="admin password (challenge-response)")
    args = p.parse_args(argv)
    host, _, port = args.master.rpartition(":")
    addr = (host or "127.0.0.1", int(port))

    cmd = args.command
    if cmd in ("list-chunkservers", "list-sessions"):
        reply = await _admin(addr, "info", password=args.password)
    elif cmd in ("metrics", "metrics-csv"):
        resolution = args.extra[0] if args.extra else "sec"
        reply = await _admin(addr, cmd, json.dumps({"resolution": resolution}), password=args.password)
        if cmd == "metrics-csv" and reply.status == 0:
            print(json.loads(reply.json)["csv"], end="")
            return 0
    elif cmd == "metrics-prom":
        reply = await _admin(addr, cmd, password=args.password)
        if reply.status == 0:
            # raw Prometheus text exposition, ready to pipe to a scraper
            print(json.loads(reply.json)["text"], end="")
            return 0
    elif cmd == "trace-dump":
        trace_id = _parse_trace_id(args.extra[0]) if args.extra else 0
        reply = await _admin(
            addr, cmd, json.dumps({"trace_id": trace_id}),
            password=args.password,
        )
        if reply.status == 0:
            from lizardfs_tpu.runtime import tracing

            spans = json.loads(reply.json).get("spans", [])
            if trace_id:
                # merged per-request timeline for one trace
                timeline = tracing.merge_timeline(spans, trace_id)
                print(tracing.format_timeline(timeline))
                if args.attribute:
                    # where the milliseconds went: bucket decomposition
                    # of the same timeline (sums exactly to wall)
                    print(tracing.format_attribution(
                        tracing.attribute_timeline(timeline)
                    ))
            else:
                print(json.dumps(spans, indent=2))
            return 0
    elif cmd == "faults":
        sub = args.extra[0] if args.extra else "list"
        if sub == "arm":
            if len(args.extra) != 2:
                print("usage: faults arm 'ROLE:SITE[:OP[:PEER]] ACTION...'",
                      file=sys.stderr)
                return 2
            reply = await _admin(
                addr, "faults-arm",
                json.dumps({"rule": args.extra[1]}),
                password=args.password,
            )
        elif sub == "clear":
            reply = await _admin(addr, "faults-clear",
                                 password=args.password)
        elif sub == "list":
            reply = await _admin(addr, "faults", password=args.password)
        else:
            print("usage: faults [arm RULE | clear]", file=sys.stderr)
            return 2
        if getattr(reply, "status", 1) == st.OK:
            _print_faults(json.loads(reply.json))
            return 0
    elif cmd == "top":
        # live cluster workload view (the cluster analog of the
        # reference's per-mount .oplog): `top watch` refreshes until ^C
        watch = bool(args.extra) and args.extra[0] == "watch"
        while True:
            reply = await _admin(addr, "top", password=args.password)
            if getattr(reply, "status", 1) != st.OK:
                break
            if watch:
                print("\x1b[2J\x1b[H", end="")  # clear + home
            _print_top(json.loads(reply.json))
            if not watch:
                return 0
            await asyncio.sleep(2.0)
    elif cmd == "profile":
        top_n = int(args.extra[0]) if args.extra else 0
        reply = await _admin(
            addr, "profile",
            json.dumps({"top": top_n} if top_n else {}),
            password=args.password,
        )
        if getattr(reply, "status", 1) == st.OK:
            doc = json.loads(reply.json)
            print(
                f"# profiler role={doc.get('role', '?')} "
                f"enabled={doc.get('enabled')} "
                f"samples={doc.get('samples', 0)} "
                f"stacks={doc.get('stacks', 0)} "
                f"interval={doc.get('interval_ms', 0)}ms "
                f"cost={doc.get('sample_cost_us', 0)}us "
                f"budget={doc.get('overhead_budget_pct', 0)}%",
                file=sys.stderr,
            )
            # stdout carries pure collapsed-stack text, ready to pipe
            # into flamegraph.pl
            if doc.get("collapsed"):
                print(doc["collapsed"])
            return 0
    elif cmd == "qos":
        payload: dict = {}
        if args.extra:
            sub = args.extra[0]
            try:
                if sub == "weight" and len(args.extra) == 3:
                    payload = {"weight": {args.extra[1]:
                                          float(args.extra[2])}}
                elif sub == "rate" and len(args.extra) == 3:
                    payload = {"rate": {args.extra[1]:
                                        float(args.extra[2])}}
                elif sub in ("data-inflight-mb", "data-bps",
                             "rebuild-weight") and len(args.extra) == 2:
                    payload = {sub.replace("-", "_"):
                               float(args.extra[1])}
                else:
                    raise ValueError(sub)
            except ValueError:
                print("usage: qos [weight TENANT W | rate CLASS OPS | "
                      "data-inflight-mb MB | data-bps BPS | "
                      "rebuild-weight W]", file=sys.stderr)
                return 2
        reply = await _admin(addr, "qos", json.dumps(payload),
                             password=args.password)
        if getattr(reply, "status", 1) == st.OK:
            _print_qos(json.loads(reply.json))
            return 0
    elif cmd == "tweaks-set":
        if len(args.extra) != 2:
            print("usage: tweaks-set NAME VALUE", file=sys.stderr)
            return 2
        reply = await _admin(
            addr, cmd,
            json.dumps({"name": args.extra[0], "value": args.extra[1]}),
            password=args.password,
        )
    else:
        reply = await _admin(addr, cmd, password=args.password)
    if getattr(reply, "status", 1) != st.OK:
        print(f"error: {st.name(reply.status)} {getattr(reply, 'json', '')}",
              file=sys.stderr)
        return 1
    doc = json.loads(reply.json) if reply.json else {}
    if cmd == "health":
        _print_health(doc)
    elif cmd == "rebuild-status":
        _print_rebuild(doc)
    elif cmd == "heat":
        _print_heat(doc)
    elif cmd == "slowops":
        for e in doc.get("slowops", []):
            cap = "captured" if e.get("captured") else "uncaptured"
            attr = e.get("attribution") or {}
            dom = attr.get("dominant", "")
            dom_s = (
                f"  {dom} {attr.get('pct', {}).get(dom, 0.0):.0f}%"
                if dom else ""
            )
            print(
                f"{e['ms']:>10.1f} ms  {e['op_class']:<10s} "
                f"{e['name']:<20s} trace 0x{e['trace_id']:x}  ({cap})"
                f"{dom_s}"
            )
        if not doc.get("slowops"):
            print("(no SLO breaches recorded)")
    elif cmd == "list-chunkservers":
        for srv in doc.get("chunkservers", []):
            state = "up" if srv["connected"] else "DOWN"
            used = srv["used_space"] / 2**30
            total = srv["total_space"] / 2**30
            print(
                f"cs{srv['cs_id']:<3d} {srv['host']}:{srv['port']:<6d} "
                f"label={srv['label']:<8s} {state:<4s} "
                f"{used:.1f}/{total:.1f} GiB"
            )
    elif cmd == "list-sessions":
        print(f"sessions: {doc.get('sessions', 0)}")
    else:
        print(json.dumps(doc, indent=2, sort_keys=True))
    return 0


def _spark(points: list, width: int = 24) -> str:
    """ASCII sparkline of a metrics-history ring (trend rendering for
    the `top` view; empty ring -> empty string)."""
    pts = [max(float(p), 0.0) for p in points][-width:]
    if not pts:
        return ""
    peak = max(pts) or 1.0
    marks = " .:-=+*#%@"
    return "".join(
        marks[min(int(v / peak * (len(marks) - 1)), len(marks) - 1)]
        for v in pts
    )


def _print_top(doc: dict) -> None:
    """Render the master's cluster-wide `top` rollup: per-session op
    rates / bytes / p99 / exemplars, gateway protocol mixes, and the
    metrics-history trends."""
    totals = doc.get("totals", {})
    if not doc.get("enabled", True):
        print("per-session accounting is DISABLED (LZ_TOP=0)")
    print(
        f"cluster top — {totals.get('rate_ops', 0):.1f} ops/s across "
        f"{totals.get('sessions_tracked', 0)} tracked sessions "
        f"({totals.get('sessions_connected', 0)} connected)"
    )
    history = doc.get("history", {})
    for name in ("session_ops_rate", "cluster_slo_breaches",
                 "endangered_queue"):
        pts = history.get(name) or []
        if pts:
            print(f"  {name:<22s} [{_spark(pts):<24s}] now "
                  f"{pts[-1]:.1f}")
    # per-tenant rollup: aggregate rates + the admission verdict per
    # tenant (the multi-tenant QoS view; absent pre-QoS masters)
    tenants = doc.get("tenants", {})
    for tenant, row in sorted(
        tenants.items(), key=lambda kv: -kv[1].get("rate_ops", 0.0)
    ):
        flag = "  THROTTLED" if row.get("throttled") else ""
        print(f"  tenant {tenant:<12s} {row.get('sessions', 0)} sessions  "
              f"{row.get('rate_ops', 0.0):8.1f} ops/s{flag}")
    rows = sorted(
        doc.get("sessions", {}).items(),
        key=lambda kv: -kv[1].get("master", {}).get("rate_ops", 0.0),
    )
    print(
        f"  {'session':<10s} {'who':<22s} {'tenant':<10s} {'ops/s':>8s} "
        f"{'MB/s':>8s} {'p99 ms':>8s}  hot (class: ops/s, p99) / exemplar"
    )
    for label, entry in rows:
        mrow = entry.get("master", {})
        # bytes move on the data plane: sum this session's chunkserver
        # legs (the master leg has no payload bytes)
        cs_bytes = sum(
            r.get("rate_bytes", 0.0)
            for r in entry.get("chunkservers", {}).values()
        )
        who = (entry.get("info", "") or "?")[:22]
        classes = mrow.get("classes", {})
        hot = sorted(
            classes.items(), key=lambda kv: -kv[1].get("ops", 0)
        )[:2]
        hot_s = " ".join(
            f"{cls}:{v.get('ops', 0)}op/{v.get('p99_ms', 0):.0f}ms"
            for cls, v in hot
        )
        exemplar = mrow.get("exemplar", entry.get("exemplar", ""))
        print(
            f"  {label:<10s} {who:<22s} "
            f"{(entry.get('tenant', '') or '-')[:10]:<10s} "
            f"{mrow.get('rate_ops', 0.0):>8.1f} "
            f"{cs_bytes / 1e6:>8.2f} "
            f"{mrow.get('p99_ms', 0.0):>8.1f}  "
            f"{hot_s}{('  trace ' + exemplar) if exemplar else ''}"
        )
        phases = entry.get("read_phases")
        if phases and phases.get("reps"):
            busy = {
                k[:-3]: v for k, v in phases.items()
                if k.endswith("_ms") and k != "wall_ms"
            }
            dom = max(busy, key=lambda k: busy[k]) if busy else "?"
            busy_s = " ".join(
                f"{k}={v:.0f}ms" for k, v in sorted(
                    busy.items(), key=lambda kv: -kv[1]
                ) if v > 0
            )
            print(
                f"             `- read phases ({phases.get('reps', 0)} "
                f"reads, wall {phases.get('wall_ms', 0.0):.0f}ms) "
                f"dominant {dom}  {busy_s}"
            )
        gw = entry.get("gateway")
        if gw and gw.get("protocol"):
            proto = gw.get("protocol") or []
            mix = proto[0].get("classes", {}) if proto else {}
            top3 = sorted(
                mix.items(), key=lambda kv: -kv[1].get("ops", 0)
            )[:3]
            mix_s = " ".join(
                f"{cls}={v.get('ops', 0)}" for cls, v in top3
            )
            print(
                f"             `- {gw.get('role', '?')} gateway "
                f"{gw.get('endpoint', '')}  {mix_s}  "
                f"(pushed {gw.get('age_s', 0)}s ago)"
            )
    if not rows:
        print("  (no sessions tracked yet)")


def _print_qos(doc: dict) -> None:
    """Render the master's multi-tenant QoS state."""
    state = "armed" if doc.get("armed") else "unconfigured (admits all)"
    if not doc.get("enabled", True):
        state = "DISABLED (LZ_QOS off)"
    print(f"qos: {state}  generation {doc.get('generation', 0)}")
    rates = doc.get("rates", {})
    if rates:
        print("  rates   " + "  ".join(
            f"{cls}={int(r)}/s" for cls, r in sorted(rates.items())
        ))
    data = doc.get("data", {})
    if data:
        print(f"  data    inflight {data.get('inflight_mb', 0):.0f} MiB"
              f"  bps {int(data.get('data_bps', 0)) or 'off'}"
              f"  rebuild-weight {data.get('rebuild_weight', 1.0):g}")
    weights = doc.get("weights", {})
    sheds = doc.get("sheds", {})
    objectives = doc.get("objectives", {})
    active = set(doc.get("active_tenants", []))
    for tenant in sorted(set(weights) | set(sheds) | active):
        shed = sheds.get(tenant, {})
        obj = objectives.get(tenant)
        obj_s = ""
        if obj:
            flag = "BREACHED" if obj.get("breached") else "ok"
            obj_s = (f"  p99 {obj.get('p99_ms', 0):.1f}/"
                     f"{obj.get('objective_ms', 0):.0f}ms {flag}")
        print(f"  tenant {tenant:<12s} weight {weights.get(tenant, 1.0):g}"
              f"  {'active ' if tenant in active else '       '}"
              f"sheds {shed.get('count', 0)}"
              + (f" ({shed.get('age_s', 0)}s ago)" if shed else "")
              + obj_s)
    if not weights and not active:
        print("  (no tenants configured or active)")


def _print_faults(doc: dict) -> None:
    """Render a daemon's live fault-injection state."""
    state = "ARMED" if doc.get("active") else "inactive"
    print(f"faults: {state}  seed={doc.get('seed', 0)}  "
          f"role={doc.get('role', '?')}")
    for r in doc.get("rules", []):
        alias = f"  (alias {r['alias']})" if r.get("alias") else ""
        limit = f"/{r['limit']}" if r.get("limit") else ""
        print(f"  rule {r['rule']}  fired {r['fired']}{limit} "
              f"of {r['matched']} matches{alias}")
    if not doc.get("rules"):
        print("  (no rules armed)")
    for e in doc.get("events", [])[-8:]:
        print(f"  event {e['role']}:{e['site']}:{e['op']} -> {e['action']}")


def _print_rebuild(doc: dict) -> None:
    """Render the master RebuildEngine's progress report."""
    q = doc.get("queued", {})
    thr = doc.get("throttle", {})
    bps = thr.get("rebuild_bps", 0)
    eta = doc.get("eta_s")
    print(
        f"queued: lost {q.get('lost', 0)}  "
        f"endangered {q.get('endangered', 0)}  "
        f"rebalance {q.get('rebalance', 0)}  "
        f"(endangered-fifo {doc.get('endangered_queue', 0)})"
    )
    print(
        f"active {len(doc.get('active', []))}/"
        f"{thr.get('rebuild_concurrency', 0)}  "
        f"throttle {bps if bps else 'unlimited'} B/s  "
        f"rate {doc.get('rate_bps', 0):.0f} B/s  "
        f"eta {f'{eta:.0f}s' if eta is not None else '-'}"
    )
    print(
        f"completed {doc.get('completed', 0)}  "
        f"failed {doc.get('failed', 0)}  "
        f"bytes {doc.get('bytes_rebuilt', 0)}"
    )
    for rb in doc.get("active", []):
        print(
            f"  active {rb['kind']:<9s} chunk {rb['chunk_id']:016X} "
            f"part {rb['part']:<3d} [{rb['class']}] "
            f"{rb['running_s']:.1f}s trace 0x{rb['trace_id']:x}"
        )
    for e in doc.get("recent", [])[:8]:
        state = "ok" if e["ok"] else "FAILED"
        print(
            f"  recent {e['kind']:<9s} chunk {e['chunk_id']:016X} "
            f"part {e['part']:<3d} [{e['class']}] {state} {e['ms']:.0f}ms"
        )


def _print_health(doc: dict) -> None:
    """Render a health report: the master's cluster rollup, or a single
    daemon's snapshot when pointed at a chunkserver."""
    if "summary" not in doc:  # single-daemon snapshot
        print(f"{doc.get('role', '?')}: {doc.get('status', '?')}")
        for cls, s in sorted(doc.get("slo", {}).items()):
            print(
                f"  slo {cls:<10s} {s['status']:<9s} "
                f"burn {s['burn_fast']:.2f}/{s['burn_slow']:.2f}  "
                f"breaches {s['breaches']}/{s['ops']}"
            )
        print(
            f"  stalls {doc.get('loop_stalls', 0)}  "
            f"span-drops {doc.get('span_ring_dropped', 0)}  "
            f"disk-errors {doc.get('disk_errors', 0)}"
        )
        return
    s = doc["summary"]
    print(
        f"cluster: {doc['status'].upper()}  "
        f"(endangered {s['endangered']}, lost {s['lost']}, "
        f"cs-unhealthy {s['cs_unhealthy']}, "
        f"breaches {s['breaches_total']}, "
        f"worst-burn {s['worst_burn_fast']:.2f})"
    )
    master = doc.get("master", {})
    print(
        f"  master        {master.get('status', '?'):<9s} "
        f"breaches {master.get('breaches_total', 0)}  "
        f"stalls {master.get('loop_stalls', 0)}  "
        f"span-drops {master.get('span_ring_dropped', 0)}"
    )
    # multi-tenant QoS: NAME currently-throttled tenants + breached
    # per-tenant objectives right in the health render
    qos = doc.get("qos") or {}
    if qos.get("throttled"):
        print("  qos throttled: " + ", ".join(qos["throttled"]))
    for tenant, obj in sorted((qos.get("objectives") or {}).items()):
        if obj.get("breached"):
            print(f"  qos objective BREACHED: {tenant} p99 "
                  f"{obj.get('p99_ms', 0):.1f}ms > "
                  f"{obj.get('objective_ms', 0):.0f}ms")
    # shadow read replicas: applied-position lag per connected shadow
    # (the incident metric for the replica plane — staleness retries
    # climb when lag does)
    for i, sh in enumerate(doc.get("shadows", [])):
        print(
            f"  shadow{i:<7d} "
            f"{'serving' if sh.get('serving') else 'standby':<9s} "
            f"v{sh.get('version', 0)}  lag {sh.get('lag', 0)}  "
            f"acked {sh.get('age_s', 0)}s ago"
        )
    for cs_id, snap in sorted(doc.get("chunkservers", {}).items(),
                              key=lambda kv: int(kv[0])):
        print(
            f"  cs{cs_id:<12s} {snap.get('status', '?'):<9s} "
            f"breaches {snap.get('breaches_total', 0)}  "
            f"stalls {snap.get('loop_stalls', 0)}  "
            f"disk-errors {snap.get('disk_errors', 0)}"
        )


def _print_heat(doc: dict) -> None:
    """Render the cluster heat map: hottest chunks / inodes / servers
    (decayed scores), standing goal boosts, placement loads, and any
    heat-armed QoS pressure."""
    if not doc.get("enabled", True):
        print("cluster heat loop is DISABLED (LZ_HEAT=0)")
    th = doc.get("thresholds", {})
    print(
        f"heat map — half-life {doc.get('half_life_s', 0):.0f}s, "
        f"boost at {th.get('heat_boost_bytes', 0) / 2**20:.0f} MiB, "
        f"demote under {th.get('heat_demote_bytes', 0) / 2**20:.0f} MiB, "
        f"+{th.get('heat_boost_copies', 0)} copies, "
        f"max {th.get('heat_max_boosted', 0)} boosted"
    )
    boosted = doc.get("boosted") or {}
    if boosted:
        print("  boosted: " + ", ".join(
            f"chunk {cid} (+{b})" for cid, b in sorted(
                boosted.items(), key=lambda kv: int(kv[0])
            )
        ))
    if doc.get("qos_pressure"):
        print("  qos pressure armed on: " + ", ".join(doc["qos_pressure"]))
    for kind in ("chunks", "inodes", "servers"):
        rows = doc.get(kind) or []
        if not rows:
            continue
        print(f"  hottest {kind}:")
        for r in rows[:8]:
            trace = f"  trace {r['trace_id']}" if r.get("trace_id") else ""
            print(
                f"    {kind[:-1]:>6s} {r['key']:<12d} "
                f"{r['heat_bytes'] / 2**20:>8.1f} MiB-heat "
                f"{r['heat_ops']:>8.1f} ops-heat  "
                f"(lifetime {r['total_bytes'] / 2**20:.1f} MiB / "
                f"{r['total_ops']} ops){trace}"
            )
    load = doc.get("server_load") or {}
    if load:
        print("  placement load: " + ", ".join(
            f"cs{cs}={v:.2f}" for cs, v in sorted(
                load.items(), key=lambda kv: int(kv[0])
            )
        ))


def main(argv=None) -> int:
    try:
        return asyncio.run(_amain(argv if argv is not None else sys.argv[1:]))
    except KeyboardInterrupt:
        return 0  # `top watch` exits via ^C by design
    except (ConnectionError, OSError, asyncio.TimeoutError) as e:
        # TimeoutError: the bounded 5 s dial — on 3.10 it is not an
        # OSError subclass, and a blackholed daemon must print the
        # clean error, not a traceback
        print(f"error: cannot reach daemon: {str(e) or 'dial timed out'}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
