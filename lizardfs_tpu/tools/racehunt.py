"""``racehunt`` — replay async tests (and chaos schedules) across K
deterministic-scheduler seeds.

The cross-await-race checker names *candidate* interleavings; this tool
hunts them dynamically: each seed runs the target pytest selection
under ``runtime/detsched.py``'s seeded event loop (``LZ_DETSCHED=<n>``
— tests/conftest.py routes every async test through ``detsched.run``
when the var is set), so each seed executes a DIFFERENT but fully
reproducible interleaving of every awaited race window. A failure
prints the exact replay command; re-running it executes a
byte-identical schedule (pinned by tests/test_detsched.py's digest
tests).

    python -m lizardfs_tpu.tools.racehunt                 # smoke set, seeds 1..3
    python -m lizardfs_tpu.tools.racehunt --seeds 10 tests/test_shadow_reads.py
    python -m lizardfs_tpu.tools.racehunt --seed 7 tests/test_detsched.py -- -k reconnect
    python -m lizardfs_tpu.tools.racehunt --chaos kill-write --seeds 5

``--chaos`` delegates a schedule to ``tools/chaos.py`` per seed (chaos
drives REAL process clusters — its determinism comes from the seeded
fault engine, not detsched; both hunts share the seed discipline and
the replay-command contract).

Exit status: 0 = every seed green, 1 = at least one failing seed (the
summary lists each with its replay command), 2 = bad invocation.
``make racehunt`` wraps the default hunt.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

# the default smoke selection: fast, pure-asyncio, detsched-sensitive
# (the seeded race fixtures + the single-flight regression pins)
SMOKE_TARGETS = ("tests/test_detsched.py",)


def _pytest_cmd(targets: list[str], extra: list[str]) -> list[str]:
    return [
        sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
        *targets, *extra,
    ]


def _chaos_cmd(schedule: str, seed: int) -> list[str]:
    return [
        sys.executable, "-m", "lizardfs_tpu.tools.chaos",
        "--schedule", schedule, "--seed", str(seed),
    ]


def _shell(env_prefix: str, cmd: list[str]) -> str:
    return (env_prefix + " " + " ".join(cmd)).strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="racehunt",
        description="seeded deterministic-interleaving hunt "
                    "(see doc/operations.md)",
    )
    ap.add_argument(
        "targets", nargs="*",
        help="pytest files/nodeids (default: the detsched smoke set); "
        "args after `--` pass through to pytest",
    )
    ap.add_argument(
        "--seeds", type=int, default=3, metavar="K",
        help="hunt seeds 1..K (default 3)",
    )
    ap.add_argument(
        "--seed", type=int, default=None,
        help="replay exactly this one seed",
    )
    ap.add_argument(
        "--chaos", metavar="SCHEDULE", default=None,
        help="hunt a chaos schedule instead of a pytest selection",
    )
    if argv is None:
        argv = sys.argv[1:]
    # everything after `--` rides through to pytest untouched
    extra: list[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, extra = argv[:split], argv[split + 1:]
    args = ap.parse_args(argv)

    if args.seed is None and args.seeds < 1:
        # a hunt over zero seeds would exit 0 with nothing hunted — a
        # misconfigured CI variable must fail loudly, not pass the gate
        ap.error(f"--seeds {args.seeds}: need at least 1 seed")
    if args.chaos and (args.targets or extra):
        # silently dropping a pytest selection would report the hunt
        # green without anything having hunted it
        ap.error("--chaos runs a chaos schedule; pytest targets/args "
                 "don't apply — drop them or drop --chaos")
    seeds = [args.seed] if args.seed is not None else list(
        range(1, args.seeds + 1)
    )
    targets = list(args.targets) or list(SMOKE_TARGETS)

    failures: list[tuple[int, str]] = []
    for seed in seeds:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the replay prefix names the platform actually used — a
        # pre-exported JAX_PLATFORMS must replay on ITSELF
        jax = f"JAX_PLATFORMS={env['JAX_PLATFORMS']}"
        if args.chaos:
            cmd = _chaos_cmd(args.chaos, seed)
            replay = _shell(jax, cmd)
        else:
            env["LZ_DETSCHED"] = str(seed)
            cmd = _pytest_cmd(targets, extra)
            replay = _shell(f"LZ_DETSCHED={seed} {jax}", cmd)
        t0 = time.monotonic()
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True
        )
        dt = time.monotonic() - t0
        status = "ok" if proc.returncode == 0 else "FAIL"
        print(f"racehunt seed={seed} {status} ({dt:.1f}s)", flush=True)
        if proc.returncode != 0:
            failures.append((seed, replay))
            tail = (proc.stdout + proc.stderr).splitlines()[-25:]
            for line in tail:
                print(f"  | {line}")
            print(f"  REPLAY: {replay}")
    if failures:
        print(f"racehunt: {len(failures)}/{len(seeds)} seeds failed")
        for seed, replay in failures:
            print(f"  seed {seed}: {replay}")
        return 1
    print(f"racehunt: all {len(seeds)} seeds green")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
