"""Invariant lint engine — machine-checks the correctness conventions
the last several PRs policed by hand.

Seven repo-specific rules ride a shared AST visitor framework
(:mod:`engine`), each one born from a bug class this tree has already
paid for at review time:

``cross-await-race``   (:mod:`races`)      shared daemon/client state
    read-modify-written across an ``await`` without an asyncio.Lock or
    a supersession guard — the interleaving class behind PR 7's four
    rounds of guard hardening.
``unbounded-await``    (:mod:`awaits`)     an ``await`` on a blocking
    primitive (connect/read/readexactly/drain/wait/queue-get) outside
    ``wait_for``/``bounded_wait`` — PR 8's one-shot audit, permanent.
``wire-skew``          (:mod:`wire`)       every message's optional
    fields must be a trailing, ``SKEW_TOLERANT_FROM``-covered suffix
    (constructor-defaulted + decode default-filled by the codec), with
    skew-variable messages nested terminally only.
``kill-switch``        (:mod:`killswitch`) every ``LZ_*`` env read
    routes through one accessor, boolean switches honor the four
    documented off spellings, and each var is inventoried, documented,
    and test-referenced.
``changelog-durability`` (:mod:`changelog`) every metadata-store op is
    digest-covered, replay-deterministic, image-persisted, and named
    by a test — the checklist PRs 4/7/10 ran by hand; committed op
    literals must name real ``_op_`` methods.
``native-wire``        (:mod:`native_wire`) the Python<->C++ wire
    contract without compiling: message-type constants, layout
    declarations, status codes, proto version, and off-spelling parity
    at native ``getenv`` sites all cross-checked against the catalog.
``telemetry-coverage`` (:mod:`telemetry`)  every client-facing verb
    maps to an SLO class (or a reasoned waiver), a live fault choke
    point, and the per-surface span/metric instruments — the PR
    2/3/8/10 conventions as a standing gate.

Dynamic companions: ``tests/test_changelog_durability.py`` replays
every op against a shadow + image round trip, and ``tools/racehunt.py``
(with ``runtime/detsched.py``) explores cross-await-race windows under
seeded deterministic schedules.

Run as ``lizardfs-lint`` / ``python -m lizardfs_tpu.tools.lint`` /
``make lint``; the tier-1 gate is ``tests/test_invariant_lint.py``
(tree held at ZERO unwaived findings). Deliberate exceptions carry an
inline ``# lint: waive(<rule>): <reason>`` the report counts — and a
waiver that stops matching a finding is itself an error, so silent
suppressions cannot accumulate.
"""

from lizardfs_tpu.tools.lint.engine import (  # noqa: F401
    Finding,
    LintConfig,
    LintResult,
    run_lint,
)
