"""``python -m lizardfs_tpu.tools.lint`` == ``lizardfs-lint``."""

import sys

from lizardfs_tpu.tools.lint.cli import main

sys.exit(main())
