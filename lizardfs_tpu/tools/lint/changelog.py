"""``changelog-durability``: every changelog op is digest-covered,
replay-deterministic, image-persisted, and test-named.

PRs 4, 7 and 10 each added changelog ops (``repair_zero_chunk``,
``synth_populate``, ``tape_demote``/``tape_recall_done``) and each ran
the same four-point checklist by hand before review would pass them:

1. **digest coverage** — ``MetadataStore.apply`` maintains the
   incremental divergence digest from ``_touched(op)``; an op the
   dispatch doesn't name XORs nothing in/out, so a shadow that applies
   it still "matches" the primary while its state silently drifts. An
   op must either appear in ``_touched``'s literal dispatch or maintain
   ``self._digest`` itself (the ``synth_populate`` pattern).
2. **replay determinism** — shadows and crash recovery re-apply the
   same records through the same ``_op_*`` methods. A method that reads
   the clock, RNG, environment, or does IO converges only by luck; all
   inputs must ride the op record. (Async op methods are flagged too:
   ``apply`` is synchronous by contract.)
3. **image persistence** — every ``self.<store>`` an op method touches
   must round-trip through ``to_sections``/``load_sections``, or a
   restart loses what replay rebuilt (the PR-10 ``demoted`` map
   checklist item).
4. **a test naming it** — at least one file under ``tests/`` must
   mention the op name as a string literal; an op nobody replays in a
   test has no pinned shadow/restore story.

Plus dispatch integrity: every ``{"op": "<name>", ...}`` literal built
anywhere in the package must name a real ``_op_<name>`` method — a
typo'd commit site otherwise fails at runtime, on the live master,
mid-mutation.
"""

from __future__ import annotations

import ast
import glob
import os

from lizardfs_tpu.tools.lint.engine import Finding, SourceFile

RULE = "changelog-durability"

# digest-excluded private attrs + derived plumbing an op may touch
# without persistence implications
_NON_STORES = {"_digest"}

# nondeterminism sources an op method must not call: dotted-path
# prefixes whose every call is volatile (matched against the full
# attribute chain, so os.environ.get and datetime.datetime.now both
# hit), plus bare names the from-import spellings land on
_NONDET_PREFIXES = (
    ("time",), ("random",), ("uuid",), ("secrets",), ("datetime",),
    ("os", "environ"), ("os", "urandom"), ("os", "getenv"),
)
_NONDET_BARE = {
    "open", "input", "print",        # IO
    "getenv", "urandom",             # from os import ...
    "time", "monotonic", "perf_counter", "time_ns",  # from time import ...
    "uuid4", "token_bytes",
}


def extra_inputs(cfg) -> list[str]:
    """Non-scanned files whose content this checker's verdict depends
    on: the metadata store itself plus every test file (the test-naming
    leg). The engine folds their hashes into the global-results cache
    key, so editing any of them re-runs this pass."""
    out = []
    if cfg.metadata_path:
        out.append(cfg.metadata_path)
    if cfg.tests_dir and os.path.isdir(cfg.tests_dir):
        out.extend(sorted(glob.glob(os.path.join(cfg.tests_dir, "*.py"))))
    return out


def collect_file(src: SourceFile) -> list:
    """Cacheable per-file summary: every ``{"op": "<name>"}`` dict
    literal (a changelog commit/apply site) with its line."""
    out = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Dict) or not node.keys:
            continue
        for k, v in zip(node.keys, node.values):
            if (
                isinstance(k, ast.Constant) and k.value == "op"
                and isinstance(v, ast.Constant) and isinstance(v.value, str)
            ):
                out.append([v.value, node.lineno])
    return out


class _Method:
    def __init__(self, node: ast.FunctionDef | ast.AsyncFunctionDef):
        self.node = node
        self.name = node.name
        self.line = node.lineno
        self.attrs: set[str] = set()       # self.<attr> roots touched
        self.self_calls: set[str] = set()  # self.<method>() called
        self.nondet: list[tuple[int, str]] = []
        self.uses_digest = False
        self.is_async = isinstance(node, ast.AsyncFunctionDef)
        self._walk()

    @staticmethod
    def _dotted(node) -> tuple[str, ...] | None:
        """('os', 'environ', 'get') for os.environ.get — None when any
        link is not a plain name/attribute chain."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return tuple(reversed(parts))

    def _walk(self):
        for node in ast.walk(self.node):
            if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name
            ) and node.value.id == "self":
                if node.attr == "_digest":
                    self.uses_digest = True
                elif not node.attr.startswith("__"):
                    self.attrs.add(node.attr)
            if isinstance(node, ast.Call):
                f = node.func
                chain = self._dotted(f)
                if chain and chain[0] == "self":
                    if len(chain) == 2:
                        self.self_calls.add(chain[1])
                elif chain and any(
                    chain[:len(p)] == p for p in _NONDET_PREFIXES
                ):
                    self.nondet.append((node.lineno, ".".join(chain) + "()"))
                elif isinstance(f, ast.Name) and f.id in _NONDET_BARE:
                    self.nondet.append((node.lineno, f"{f.id}()"))
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                # os.environ[...] / environ[...] reads
                chain = self._dotted(node.value)
                if chain and (
                    chain == ("os", "environ") or chain == ("environ",)
                ):
                    self.nondet.append(
                        (node.lineno, ".".join(chain) + "[...]")
                    )
            if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                self.nondet.append(
                    (node.lineno, "await (apply() is synchronous)")
                )


def _touched_ops(methods: dict[str, _Method]) -> set[str]:
    """Op names the ``_touched`` dispatch mentions as string literals
    (``t == "x"`` / ``t in ("x", "y")`` comparisons)."""
    m = methods.get("_touched")
    if m is None:
        return set()
    out: set[str] = set()
    for node in ast.walk(m.node):
        if not isinstance(node, ast.Compare) or not isinstance(
            node.left, ast.Name
        ):
            continue
        for comp in node.comparators:
            if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
                out.add(comp.value)
            elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                for el in comp.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                        el.value, str
                    ):
                        out.add(el.value)
    return out


def _closure(
    name: str, methods: dict[str, _Method], seen: set[str] | None = None
) -> tuple[set[str], list[tuple[int, str]], bool]:
    """(attrs, nondet sites, uses_digest) for a method plus every
    ``self._helper()`` it calls, transitively (the ``_release_one``
    pattern: ops share mutation helpers)."""
    seen = seen if seen is not None else set()
    if name in seen or name not in methods:
        return set(), [], False
    seen.add(name)
    m = methods[name]
    attrs = set(m.attrs)
    nondet = list(m.nondet)
    digest = m.uses_digest
    for callee in m.self_calls:
        a, n, d = _closure(callee, methods, seen)
        attrs |= a
        nondet.extend(n)
        digest = digest or d
    return attrs, nondet, digest


def check_global(cfg, collections: dict) -> list[Finding]:
    path = getattr(cfg, "metadata_path", None)
    if not path or not os.path.exists(path):
        return []
    rel = os.path.relpath(path, cfg.root)
    try:
        with open(path, encoding="utf-8") as fh:
            src = SourceFile(path, rel, fh.read())
    except (OSError, SyntaxError) as e:
        return [Finding(RULE, rel, 0, f"cannot parse metadata store: {e}")]

    store = next(
        (
            n for n in src.tree.body
            if isinstance(n, ast.ClassDef) and any(
                isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))
                and st.name.startswith("_op_")
                for st in n.body
            )
        ),
        None,
    )
    if store is None:
        return [Finding(
            RULE, rel, 0,
            "no class with _op_* methods found — the apply dispatch moved; "
            "update cfg.metadata_path",
        )]
    methods = {
        st.name: _Method(st)
        for st in store.body
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    ops = {n[4:]: m for n, m in methods.items() if n.startswith("_op_")}
    findings: list[Finding] = []

    # section attrs named by the persistence pair: an op-store must
    # appear in BOTH (write half alone loses it at load, read half
    # alone never saves it)
    def _attrs_of(name: str) -> set[str]:
        m = methods.get(name)
        if m is None:
            return set()
        return m.attrs

    saved = _attrs_of("to_sections")
    loaded = _attrs_of("load_sections")
    if not saved or not loaded:
        findings.append(Finding(
            RULE, rel, store.lineno,
            "to_sections/load_sections not found on the op-dispatch class "
            "— image persistence cannot be verified",
        ))

    touched = _touched_ops(methods)
    tests_text = ""
    if cfg.tests_dir and os.path.isdir(cfg.tests_dir):
        for tp in sorted(glob.glob(os.path.join(cfg.tests_dir, "*.py"))):
            try:
                with open(tp, encoding="utf-8") as fh:
                    tests_text += fh.read()
            except OSError:
                continue

    for op, m in sorted(ops.items()):
        attrs, nondet, self_digest = _closure(m.name, methods)
        if m.is_async:
            findings.append(Finding(
                RULE, rel, m.line,
                f"op {op!r}: async op method — apply() is synchronous by "
                "contract (an awaiting op would let another op interleave "
                "mid-mutation on the live master while shadows replay it "
                "atomically)",
            ))
        # 1. digest coverage
        if op not in touched and not self_digest:
            findings.append(Finding(
                RULE, rel, m.line,
                f"op {op!r}: no incremental-digest coverage — name it in "
                "_touched()'s dispatch (or maintain self._digest in the "
                "method, the synth_populate pattern); without it a shadow "
                "drifts while its checksum still matches",
            ))
        # 2. replay determinism
        for line, what in nondet:
            findings.append(Finding(
                RULE, rel, line,
                f"op {op!r}: calls {what} — op application must be a pure "
                "function of (state, op record) or shadow replay and crash "
                "recovery diverge; move the volatile read to the commit "
                "site and ride it on the record",
            ))
        # 3. image persistence: every store the op touches must
        # round-trip. Method names (helpers) and the persistence pair's
        # own plumbing are not stores.
        stores = {
            a for a in attrs
            if a not in _NON_STORES and a not in methods
        }
        if saved and loaded:
            for a in sorted(stores):
                if a not in saved or a not in loaded:
                    half = (
                        "load_sections" if a in saved else
                        "to_sections" if a in loaded else
                        "to_sections/load_sections"
                    )
                    findings.append(Finding(
                        RULE, rel, m.line,
                        f"op {op!r}: touches self.{a} which {half} does not "
                        "carry — a restart loses state that replay already "
                        "rebuilt (add it to the image, or route the op "
                        "through a persisted store)",
                    ))
        # 4. a test naming it
        if tests_text and (
            f'"{op}"' not in tests_text and f"'{op}'" not in tests_text
        ):
            findings.append(Finding(
                RULE, rel, m.line,
                f"op {op!r}: no test under tests/ names it — add one that "
                "replays it (two stores + checksum compare) and round-trips "
                "the image, the PR-10 test_demoted_state pattern",
            ))

    # 5. dispatch integrity: committed op literals must have methods.
    # The metadata file's own record-shape literals (none today) and
    # test fixtures are out of scope — collections cover cfg.paths.
    for file_rel, sites in sorted(collections.items()):
        for op, line in sites:
            if op not in ops:
                findings.append(Finding(
                    RULE, file_rel, line,
                    f"op literal {op!r} has no _op_{op} method on the "
                    "metadata store — this commit site raises on the live "
                    "master mid-mutation",
                ))
    return findings
