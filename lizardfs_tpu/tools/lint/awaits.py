"""``unbounded-await``: blocking primitives awaited without a budget.

PR 8's audit bounded every dial and lone reply wait on the data/dial
planes (``runtime/retry.py``: ``bounded_wait`` inherits the tightest
ambient :class:`Deadline`; ``RetryPolicy.run`` publishes one). This
checker makes that audit permanent: a DIRECT ``await`` of one of the
park-forever primitives —

    connect / open_connection / open_unix_connection,
    read / readexactly / readuntil / readline,
    drain, wait, wait_closed, queue ``get()``

— is a finding unless the call itself carries a ``timeout=`` argument
(``asyncio.wait(..., timeout=t)``). The compliant idioms never match,
because the awaited call is then ``wait_for``/``bounded_wait``/
``policy.run``, not the primitive:

    await bounded_wait(reader.readexactly(n), cap)
    await asyncio.wait_for(writer.drain(), t)

Legitimately unbounded parks — a daemon's ``stop.wait()``, the frame
pump awaiting the next request on a server connection — carry a
``# lint: waive(unbounded-await): <why this wait owns no budget>``.
"""

from __future__ import annotations

import ast

from lizardfs_tpu.tools.lint.engine import Finding, SourceFile

RULE = "unbounded-await"

RISKY = {
    "connect",
    "open_connection",
    "open_unix_connection",
    "read",
    "readexactly",
    "readuntil",
    "readline",
    "drain",
    "wait",
    "wait_closed",
    "get",
}


# classmethod dials that ARE the audited bounded accessors: their
# bodies wrap the raw open_connection in bounded_wait(DIAL_TIMEOUT)
# (and are themselves linted here), so awaiting them is the compliant
# idiom, not a violation
BOUNDED_DELEGATES = {("RpcConnection", "connect")}


def _call_name(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def check_file(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Await):
            continue
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        name = _call_name(call)
        if name not in RISKY:
            continue
        if (
            isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and (call.func.value.id, name) in BOUNDED_DELEGATES
        ):
            continue
        if name == "get" and (call.args or call.keywords):
            continue  # queue-get takes no args; obj.get(key, ...) is not it
        if any(
            kw.arg == "timeout"
            and not (
                isinstance(kw.value, ast.Constant) and kw.value.value is None
            )
            for kw in call.keywords
        ):
            continue  # the primitive bounds itself
        findings.append(
            Finding(
                RULE,
                src.rel,
                node.lineno,
                f"direct `await ....{name}(...)` has no budget — wrap in "
                "bounded_wait()/asyncio.wait_for() (or run under a "
                "RetryPolicy deadline and waive with the reason)",
            )
        )
    return findings
