"""``kill-switch``: every ``LZ_*`` environment variable is inventoried,
single-accessor, spelling-parity, documented, and test-referenced.

The four documented off spellings (``0 / off / false / no``, see
``constants.OFF_SPELLINGS``) were hand-policed into LZ_SHM_RING,
LZ_SHADOW_READS and friends across PRs 6/7 — and review still caught
parity misses twice. Worse, truthiness reads (``if os.environ.get(X)``)
invert the contract silently: ``LZ_TPU_ALLOW_CPU=0`` *enabled* the
escape hatch before this PR. This checker closes the class:

* Boolean switches may be read ONLY inside ``constants.env_flag`` —
  the one accessor that owns the spelling set. Everything else calls
  ``env_flag("LZ_X", default)`` (or a named helper that does), and each
  switch may have at most ONE such accessor call site: two ad-hoc
  ``env_flag`` calls for the same switch re-create the drift the rule
  exists to kill.
* Value vars (specs, sizes, depths) keep direct reads, but all reads
  of one var must live in a single function — one accessor per var.
* Every var must be registered below (switch / value / wildcard),
  mentioned in the ops doc inventory, and — for switches — referenced
  by at least one test under ``tests/`` (the equivalence test that
  pins kill-switch-off behavior).
* ``getenv("LZ_*")`` in ``native/`` must name an inventoried var too
  (C++ spelling parity itself is pinned by the existing server-side
  'off' tests).

Env var names must be string literals (or a literal-prefixed f-string
matching a wildcard entry like ``LZ_SLO_<CLASS>_MS``) — a computed name
is invisible to this inventory and to every grep an operator runs.
"""

from __future__ import annotations

import ast
import glob
import os
import re

from lizardfs_tpu.tools.lint import engine
from lizardfs_tpu.tools.lint.engine import Finding

RULE = "kill-switch"

# ---- the inventory ---------------------------------------------------------
# Boolean switches: read via constants.env_flag only; four-spelling off
# parity; must be documented + test-referenced.
SWITCHES = {
    "LZ_TRACE",            # request tracing (default on)
    "LZ_SLO",              # SLO engine (default on)
    "LZ_SHM_RING",         # same-host shared-memory data plane (on)
    "LZ_SHADOW_READS",     # shadow read replicas (on)
    "LZ_SHARDED_RECOVERY", # mesh-sharded rebuild compute (on)
    "LZ_WRITE_PIPELINE",   # double-buffered stripe pipeline (on)
    "LZ_TPU_ALLOW_CPU",    # encoder escape hatch (default OFF)
    "LZ_NO_UDS",           # disable same-host UDS fast path (default OFF)
    "LZ_S3",               # S3 object gateway (on; off refuses start)
    "LZ_S3_LIFECYCLE",     # master lifecycle tiering scanner (on)
    "LZ_TOP",              # per-session op accounting / `top` view (on)
    "LZ_PROF",             # always-on sampling profiler (on)
    "LZ_QOS",              # multi-tenant fair-share QoS plane (on)
    "LZ_HEAT",             # cluster heat map + adaptive replication (on)
    "LZ_HA",               # autopilot failover: election + fencing (on)
}

# Value vars: one read site each; documented; spelling rules N/A.
VALUES = {
    "LZ_FAULTS",                  # fault-injection rule spec (unset = off)
    "LZ_ROLE",                    # process role for fault attribution
    "LZ_NATIVE_SO",               # alternate native library path
    "LZ_CLIENT_SO",               # alternate C-client library path
    "LZ_SHM_RING_MB",             # shm segment size
    "LZ_WRITE_WINDOW",            # window depth (0 = kill switch)
    "LZ_WRITE_CS_CREDITS",        # per-chunkserver credit override
    "LZ_WRITE_WINDOW_BYTES_MB",   # staging-byte budget
    "LZ_WRITE_PIPELINE_SEGMENTS", # pipeline depth
    "LZ_DETSCHED",                # deterministic-scheduler seed (tests)
}

# Wildcard families: literal prefix of an f-string read.
WILDCARDS = {"LZ_SLO_"}  # LZ_SLO_<CLASS>_MS per-class thresholds

_NATIVE_GETENV = re.compile(r'getenv\(\s*"(LZ_[A-Z0-9_]*)"')


class _Read:
    def __init__(self, rel, func, line, var, prefix=None):
        self.rel = rel
        self.func = func  # enclosing function name or "<module>"
        self.line = line
        self.var = var  # None = dynamic name
        self.prefix = prefix  # literal f-string prefix if any


def _literal_name(node):
    """(var, prefix): var for a Constant str, prefix for a JoinedStr."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, None
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            return None, head.value
    return None, None


def _is_environ(node) -> bool:
    """os.environ / environ (from-imported) as a read receiver."""
    return (isinstance(node, ast.Attribute) and node.attr == "environ") or (
        isinstance(node, ast.Name) and node.id == "environ"
    )


def _collect(src):
    """(env_reads, env_flag_calls) for one SourceFile."""
    reads: list[_Read] = []
    flags: list[_Read] = []

    def walk(node, func):
        for child in ast.iter_child_nodes(node):
            cf = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cf = child.name
            name_node = None
            sink = None
            if isinstance(child, ast.Call):
                f = child.func
                # attribute AND bare-name forms: `from os import
                # getenv/environ` must not bypass the gate
                if (
                    isinstance(f, ast.Attribute)
                    and (
                        (f.attr == "get" and _is_environ(f.value))
                        or f.attr == "getenv"
                    )
                ) or (isinstance(f, ast.Name) and f.id == "getenv"):
                    name_node = child.args[0] if child.args else None
                    sink = reads
                elif (isinstance(f, ast.Name) and f.id == "env_flag") or (
                    isinstance(f, ast.Attribute) and f.attr == "env_flag"
                ):
                    name_node = child.args[0] if child.args else None
                    sink = flags
            elif (
                isinstance(child, ast.Subscript)
                and isinstance(child.ctx, ast.Load)
                and _is_environ(child.value)
            ):
                name_node = child.slice
                sink = reads
            if sink is not None and name_node is not None:
                var, prefix = _literal_name(name_node)
                if (var and var.startswith("LZ_")) or (
                    prefix and prefix.startswith("LZ_")
                ):
                    sink.append(
                        _Read(src.rel, cf, child.lineno, var, prefix)
                    )
            walk(child, cf)

    walk(src.tree, "<module>")
    return reads, flags


def _match_wildcard(read, wildcards):
    probe = read.var or read.prefix or ""
    return next((w for w in wildcards if probe.startswith(w)), None)


def extra_inputs(cfg) -> list[str]:
    """Non-scanned inputs the global pass reads: the ops doc, every
    test file (switch-reference leg), and the native sources (getenv
    sweep). Folded into the engine's global-results cache key so a
    native/doc/tests edit re-runs this pass."""
    out = list(cfg.doc_paths or [])
    if cfg.tests_dir and os.path.isdir(cfg.tests_dir):
        out.extend(sorted(glob.glob(os.path.join(cfg.tests_dir, "*.py"))))
    out.extend(engine.native_sources(cfg.native_dir))
    return out


def collect_file(src) -> dict:
    """Cacheable per-file summary: every env read / env_flag call.
    The engine stores this in the per-file cache so a warm run never
    re-parses a file just to feed this checker's global pass."""
    reads, flags = _collect(src)
    ser = lambda rs: [[r.func, r.line, r.var, r.prefix] for r in rs]  # noqa: E731
    return {"reads": ser(reads), "flags": ser(flags)}


# the ONE file whose env_flag function may read boolean switches
# directly — a same-named function elsewhere is a re-implementation
# (its own spelling set = the drift this rule exists to kill)
ACCESSOR_FILES = ("lizardfs_tpu/constants.py",)


def check_global(cfg, collections: dict) -> list[Finding]:
    switches = getattr(cfg, "ks_switches", SWITCHES)
    values = getattr(cfg, "ks_values", VALUES)
    wildcards = getattr(cfg, "ks_wildcards", WILDCARDS)
    accessor_files = getattr(cfg, "ks_accessor_files", ACCESSOR_FILES)
    findings: list[Finding] = []
    reads: list[_Read] = []
    flags: list[_Read] = []
    for rel, col in collections.items():
        for func, line, var, prefix in col.get("reads", ()):
            reads.append(_Read(rel, func, line, var, prefix))
        for func, line, var, prefix in col.get("flags", ()):
            flags.append(_Read(rel, func, line, var, prefix))

    # ---- direct env reads -------------------------------------------------
    value_sites: dict[str, list[_Read]] = {}
    for rd in reads:
        wc = _match_wildcard(rd, wildcards)
        if rd.var is None:
            if wc is None:
                findings.append(Finding(
                    RULE, rd.rel, rd.line,
                    "LZ_* env read with a computed name — the inventory "
                    "(and operator greps) cannot see it; use a literal or "
                    "register a wildcard family",
                ))
            else:
                value_sites.setdefault(wc, []).append(rd)
            continue
        if rd.var in switches:
            if rd.func != "env_flag" or (
                rd.rel.replace("\\", "/") not in accessor_files
            ):
                findings.append(Finding(
                    RULE, rd.rel, rd.line,
                    f"{rd.var}: boolean kill switch read directly — route "
                    "through constants.env_flag (the one accessor honoring "
                    "the four documented off spellings: 0/off/false/no; "
                    "a same-named function elsewhere is a "
                    "re-implementation, not the accessor)",
                ))
            continue
        if rd.var in values:
            value_sites.setdefault(rd.var, []).append(rd)
            continue
        if wc is not None:
            value_sites.setdefault(wc, []).append(rd)
            continue
        findings.append(Finding(
            RULE, rd.rel, rd.line,
            f"{rd.var}: unregistered LZ_* env var — add it to the "
            "kill-switch checker inventory (switch or value), the ops-doc "
            "inventory, and (switches) an equivalence test",
        ))

    # one accessor per value var
    for var, sites in sorted(value_sites.items()):
        funcs = {(s.rel, s.func) for s in sites}
        if len(funcs) > 1:
            where = ", ".join(sorted(f"{r}:{fn}" for r, fn in funcs))
            for s in sites:
                findings.append(Finding(
                    RULE, s.rel, s.line,
                    f"{var}: read from {len(funcs)} functions ({where}) — "
                    "route every consumer through one accessor",
                ))

    # ---- env_flag call sites ---------------------------------------------
    flag_sites: dict[str, list[_Read]] = {}
    for fl in flags:
        if fl.var is None:
            findings.append(Finding(
                RULE, fl.rel, fl.line,
                "env_flag() with a computed name — switches must be "
                "literal so the inventory can see them",
            ))
            continue
        if fl.var not in switches:
            findings.append(Finding(
                RULE, fl.rel, fl.line,
                f"{fl.var}: env_flag() on a var not registered as a "
                "boolean switch",
            ))
            continue
        flag_sites.setdefault(fl.var, []).append(fl)
    for var, sites in sorted(flag_sites.items()):
        funcs = {(s.rel, s.func) for s in sites}
        if len(funcs) > 1:
            where = ", ".join(sorted(f"{r}:{fn}" for r, fn in funcs))
            for s in sites:
                findings.append(Finding(
                    RULE, s.rel, s.line,
                    f"{var}: env_flag called from {len(funcs)} places "
                    f"({where}) — one accessor per switch; export a named "
                    "helper and call that",
                ))

    # ---- native/ getenv sweep --------------------------------------------
    for path in engine.native_sources(cfg.native_dir):
        rel = os.path.relpath(path, cfg.root)
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                for i, line in enumerate(fh, start=1):
                    for m in _NATIVE_GETENV.finditer(line):
                        var = m.group(1)
                        if var not in switches and var not in values:
                            findings.append(Finding(
                                RULE, rel, i,
                                f"{var}: native getenv of an "
                                "uninventoried LZ_* var",
                            ))
        except OSError:
            continue

    # ---- doc + test inventory --------------------------------------------
    doc_text = ""
    for dp in cfg.doc_paths or []:
        try:
            with open(dp, encoding="utf-8") as fh:
                doc_text += fh.read()
        except OSError:
            pass
    tests_text = ""
    if cfg.tests_dir and os.path.isdir(cfg.tests_dir):
        for tp in sorted(glob.glob(os.path.join(cfg.tests_dir, "*.py"))):
            try:
                with open(tp, encoding="utf-8") as fh:
                    tests_text += fh.read()
            except OSError:
                pass
    anchor = os.path.relpath(
        (cfg.doc_paths or [os.path.join(cfg.root, "doc")])[0], cfg.root
    )
    if cfg.doc_paths:
        for var in sorted(switches | values) + sorted(wildcards):
            # wildcards probe with the raw prefix ("LZ_SLO_"): trimming
            # the underscore would let the unrelated LZ_SLO switch row
            # satisfy the family's doc requirement
            if var not in doc_text:
                findings.append(Finding(
                    RULE, anchor, 0,
                    f"{var}: missing from the ops-doc env inventory",
                ))
    if cfg.tests_dir:
        for var in sorted(switches):
            if var not in tests_text:
                findings.append(Finding(
                    RULE, anchor, 0,
                    f"{var}: boolean switch with no test referencing it — "
                    "add an off-equivalence test under tests/",
                ))
    return findings
