"""``lizardfs-lint`` — run the invariant checkers from the shell.

    lizardfs-lint                     # whole tree, all rules
    lizardfs-lint --rule wire-skew    # one rule
    lizardfs-lint path/to/file.py     # explicit scan set
    lizardfs-lint --json              # machine-readable findings
    lizardfs-lint --no-cache          # ignore .lint-cache.json

Exit status: 0 = zero unwaived findings, 1 = findings (or stale
waivers), 2 = bad invocation. ``make lint`` wraps this and stamps
``.lint-stamp`` on success so ``make chaos`` can nag when lint was
skipped.
"""

from __future__ import annotations

import argparse
import json
import sys

from lizardfs_tpu.tools.lint.engine import LintConfig, all_rules, run_lint


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="lizardfs-lint",
        description="invariant lint engine (see doc/operations.md)",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: tree)")
    ap.add_argument(
        "--rule", action="append", choices=all_rules(),
        help="run only this rule (repeatable)",
    )
    ap.add_argument("--json", action="store_true", help="JSON findings")
    ap.add_argument("--no-cache", action="store_true")
    ap.add_argument(
        "--waivers", action="store_true",
        help="list every waiver with its reason",
    )
    args = ap.parse_args(argv)

    cfg = LintConfig.for_tree()
    if args.paths:
        cfg.paths = args.paths
    if args.rule:
        cfg.rules = args.rule
    if args.no_cache:
        cfg.use_cache = False
    result = run_lint(cfg)

    if args.json:
        print(json.dumps(
            {
                "files": result.files,
                "findings": [
                    {
                        "rule": f.rule, "path": f.path, "line": f.line,
                        "message": f.message, "waived": f.waived,
                        "waive_reason": f.waive_reason,
                    }
                    for f in result.findings
                ],
            },
            indent=2,
        ))
    else:
        print(result.render())
        if args.waivers:
            for w in result.waivers:
                print(f"waiver {w.path}:{w.line} [{w.rule}] {w.reason}")
    return 1 if result.unwaived else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
