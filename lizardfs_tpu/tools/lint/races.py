"""``cross-await-race``: shared state read-modify-written across an
``await`` boundary.

The bug class: a coroutine reads ``self.x`` (directly or into a local),
suspends at an ``await``, and later writes ``self.x`` (or mutates the
object the stale local still names) from the pre-suspension value.
Another coroutine interleaving at the await clobbers or is clobbered —
exactly the class PR 7's supersession guards fixed four times in
review.

Detection is a per-coroutine linear event walk (source order
approximates execution order; loop back-edges are ignored):

* ``load self.X`` events taint locals assigned from them;
* ``store self.X`` events carry the attrs whose loads taint the stored
  value (mutating method calls — append/pop/update/… — on ``self.X``
  or on a tainted alias count as stores of X);
* ``await`` events come from Await / async for / async with.

A load→await→store of the same attribute is a finding UNLESS the code
shows one of the recognized safe idioms between the LAST await and the
store:

* a fresh re-read of the attribute (the supersession-guard shape:
  ``if self.owner is not me: return`` — any post-await load counts);
* a guard branch — an ``if``/``while`` test reading any ``self.*``
  attribute whose body bails (return/raise/continue/break);
* load and store sharing an enclosing ``async with <lock-ish>`` block
  (context expression mentioning lock/mutex/sem) — the await between
  them cannot interleave with a peer holding the same lock.

Deliberately single-assignment-safe patterns that remain flagged carry
a ``# lint: waive(cross-await-race): <why>``.
"""

from __future__ import annotations

import ast
import re

from lizardfs_tpu.tools.lint.engine import Finding, SourceFile

RULE = "cross-await-race"

_LOCKISH = re.compile(r"lock|mutex|sem", re.IGNORECASE)

# method names that mutate their receiver in place
_MUTATORS = {
    "append", "add", "pop", "remove", "discard", "clear", "update",
    "extend", "insert", "setdefault", "popitem", "appendleft", "popleft",
}


class _Ev:
    __slots__ = ("kind", "attr", "line", "locks", "deps")

    def __init__(self, kind, attr=None, line=0, locks=frozenset(), deps=()):
        self.kind = kind  # "load" | "store" | "await" | "guard"
        self.attr = attr
        self.line = line
        self.locks = locks
        self.deps = deps  # store only: tuple[(attr, load_event_idx)]


def _is_self_attr(node) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _base_self_attr(node) -> str | None:
    """self.X, self.X[...], self.X.y, self.X[...].y → "X"."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        a = _is_self_attr(node)
        if a is not None:
            return a
        node = node.value
    return None


class _CoroScan:
    """Event walk over one coroutine body."""

    def __init__(self):
        self.events: list[_Ev] = []
        self.env: dict[str, frozenset] = {}  # local -> {(attr, load_idx)}
        self.locks: list[int] = []
        self._lock_seq = 0
        # store-target reads (the `self.d` in `self.d[k] = v`) must not
        # count as fresh re-reads — they are part of the store itself
        self._quiet = False

    # -- expression walk: emits load/await events, returns taint set ------
    def expr(self, node) -> frozenset:
        taint: set = set()
        self._expr(node, taint)
        return frozenset(taint)

    def _emit(self, kind, attr=None, line=0, deps=()):
        if self._quiet and kind == "load":
            # pseudo-index at "now": a dep on it can never straddle an
            # await, and no event is recorded to suppress others
            return len(self.events)
        self.events.append(
            _Ev(kind, attr, line, frozenset(self.locks), tuple(deps))
        )
        return len(self.events) - 1

    def _expr(self, node, taint: set) -> None:
        if node is None:
            return
        if isinstance(node, ast.Await):
            self._expr(node.value, taint)
            self._emit("await", line=node.lineno)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # separate scope; scanned on its own
        a = _is_self_attr(node)
        if a is not None and isinstance(node.ctx, ast.Load):
            idx = self._emit("load", a, node.lineno)
            taint.add((a, idx))
            return
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            taint.update(self.env.get(node.id, ()))
            return
        if isinstance(node, ast.Call):
            # mutator method call: receiver is written, not just read
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                base = func.value
                base_taint: set = set()
                self._expr(base, base_taint)
                arg_taint: set = set()
                for arg in node.args:
                    self._expr(arg, arg_taint)
                for kw in node.keywords:
                    self._expr(kw.value, arg_taint)
                attr = _base_self_attr(base)
                deps = set(base_taint)
                if attr is not None:
                    # direct self.X.append(...): load+store same statement
                    # — only an await inside the args makes it cross-await
                    deps = {d for d in base_taint if d[0] == attr} or base_taint
                for dattr in {d[0] for d in deps}:
                    self._emit(
                        "store",
                        dattr,
                        node.lineno,
                        deps=[d for d in deps if d[0] == dattr],
                    )
                taint.update(base_taint)
                taint.update(arg_taint)
                return
            # a plain call: the RECEIVER taints the result (`v =
            # self.d.get(k)` derives v from d's contents — the classic
            # cache-RMW read), but a bound self-METHOD does not
            # (`session = self._lookup(k)`: stores to `self._lookup`
            # never happen; tainting through the bound-method read only
            # manufactures false positives on every helper call)
            if isinstance(func, ast.Attribute):
                self._expr(func.value, taint)
            else:
                discard: set = set()
                self._expr(func, discard)
            for arg in node.args:
                self._expr(arg, taint)
            for kw in node.keywords:
                self._expr(kw.value, taint)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child, taint)

    # -- statement walk ---------------------------------------------------
    def _assign_target(self, target, taint: frozenset, line: int) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = taint
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_target(elt, taint, line)
            return
        if isinstance(target, ast.Starred):
            self._assign_target(target.value, taint, line)
            return
        attr = _base_self_attr(target)
        if attr is not None:
            # index/attr path expressions are loads too
            if _is_self_attr(target) is None:
                sub_taint: set = set()
                self._quiet = True
                try:
                    for child in ast.iter_child_nodes(target):
                        if isinstance(child, (ast.Load, ast.Store)):
                            continue
                        self._expr(child, sub_taint)
                finally:
                    self._quiet = False
                taint = taint | frozenset(sub_taint)
            self._emit(
                "store", attr, line,
                deps=[d for d in taint if d[0] == attr],
            )

    def stmts(self, body) -> None:
        for st in body:
            self.stmt(st)

    def stmt(self, st) -> None:
        line = getattr(st, "lineno", 0)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(st, ast.Assign):
            taint = self.expr(st.value)
            for t in st.targets:
                self._assign_target(t, taint, line)
            return
        if isinstance(st, ast.AnnAssign):
            taint = self.expr(st.value) if st.value else frozenset()
            self._assign_target(st.target, taint, line)
            return
        if isinstance(st, ast.AugAssign):
            attr = _base_self_attr(st.target)
            taint: set = set()
            if attr is not None:
                idx = self._emit("load", attr, line)
                taint.add((attr, idx))
            elif isinstance(st.target, ast.Name):
                taint.update(self.env.get(st.target.id, ()))
            self._expr(st.value, taint)
            if attr is not None:
                self._assign_target(st.target, frozenset(taint), line)
            elif isinstance(st.target, ast.Name):
                self.env[st.target.id] = frozenset(taint)
            return
        if isinstance(st, (ast.If, ast.While)):
            test_taint: set = set()
            self._expr(st.test, test_taint)
            reads_self = any(True for _ in test_taint)
            bails = any(
                isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
                for s in st.body
            )
            if reads_self and bails:
                self._emit("guard", line=line)
            self.stmts(st.body)
            self.stmts(st.orelse)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            taint = self.expr(st.iter)
            if isinstance(st, ast.AsyncFor):
                self._emit("await", line=line)
            self._assign_target(st.target, taint, line)
            self.stmts(st.body)
            self.stmts(st.orelse)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            lock_ids = []
            for item in st.items:
                self.expr(item.context_expr)
                try:
                    text = ast.unparse(item.context_expr)
                except Exception:  # pragma: no cover - unparse is total
                    text = ""
                if _LOCKISH.search(text):
                    self._lock_seq += 1
                    lock_ids.append(self._lock_seq)
                if item.optional_vars is not None:
                    self._assign_target(item.optional_vars, frozenset(), line)
            if isinstance(st, ast.AsyncWith):
                self._emit("await", line=line)
            self.locks.extend(lock_ids)
            self.stmts(st.body)
            for _ in lock_ids:
                self.locks.pop()
            if isinstance(st, ast.AsyncWith):
                self._emit("await", line=line)
            return
        if isinstance(st, ast.Try):
            self.stmts(st.body)
            for h in st.handlers:
                self.stmts(h.body)
            self.stmts(st.orelse)
            self.stmts(st.finalbody)
            return
        if isinstance(st, (ast.Return, ast.Expr, ast.Raise, ast.Assert,
                           ast.Delete)):
            for child in ast.iter_child_nodes(st):
                self.expr(child)
            return
        # fallback: walk any embedded expressions generically
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self.expr(child)
            elif isinstance(child, ast.stmt):
                self.stmt(child)


def _analyze(events: list[_Ev]) -> list[tuple[str, int, int]]:
    """Return (attr, load_line, store_line) for each cross-await RMW."""
    out = []
    for s_idx, ev in enumerate(events):
        if ev.kind != "store" or not ev.deps:
            continue
        for (attr, i) in ev.deps:
            if attr != ev.attr:
                continue
            awaits = [
                j for j in range(i + 1, s_idx)
                if events[j].kind == "await"
            ]
            if not awaits:
                continue
            last_await = awaits[-1]
            # fresh re-read or guard between the last await and the store
            window = events[last_await + 1 : s_idx]
            if any(
                e.kind == "guard"
                or (e.kind == "load" and e.attr == attr)
                for e in window
            ):
                continue
            # load and store under one shared lock block
            if events[i].locks & ev.locks:
                continue
            out.append((attr, events[i].line, ev.line))
            break  # one finding per store
    return out


def check_file(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[int, str]] = set()
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        args = node.args.posonlyargs + node.args.args
        if not args or args[0].arg != "self":
            continue
        scan = _CoroScan()
        scan.stmts(node.body)
        for attr, load_line, store_line in _analyze(scan.events):
            key = (store_line, attr)
            if key in seen:
                continue
            seen.add(key)
            findings.append(
                Finding(
                    RULE,
                    src.rel,
                    store_line,
                    f"self.{attr} read at line {load_line} is written back "
                    f"here across an await with no lock, supersession "
                    f"guard, or fresh re-read — interleaving coroutines "
                    f"can clobber it (coroutine {node.name!r})",
                )
            )
    return findings
